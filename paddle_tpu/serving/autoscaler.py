"""Autoscaler — closed-loop fleet elasticity over supervised replicas.

ROADMAP item 5's consumer: the pieces this module closes the loop over
all exist — the supervisor factory rebuilds engines (PR 9), the router
tracks per-replica ``load()`` and (now) takes membership changes at
runtime, the shedder estimates TTFT from EWMAs, drain is graceful end to
end, and ``Gateway.window_stats()`` (PR 13) is the telemetry feed.  The
:class:`Autoscaler` watches that feed from a control thread and turns it
into replica count:

* **scale up** when the TTFT-estimate headroom collapses against the
  SLO, the windowed queue-wait p99 breaches, or the shed rate is
  sustained — a worker thread builds a fresh replica through the
  caller's ``factory`` (the ``scale.up_build`` fault seam; a build that
  dies is retried) and adds it to the router the moment it is ready.
* **scale down** on sustained idle — and scale-down is ALWAYS
  ``drain(deadline)`` → wait → ``remove_replica`` → teardown, never a
  kill: the draining replica is unpickable (the router's third state)
  while its in-flight work finishes, and only an empty replica leaves
  the fleet (``scale.down_drain`` seam; a replica that dies mid-drain is
  absorbed — its supervisor heals it and the drain is retried).
* **hysteresis + per-direction cooldowns** in :class:`ScalePolicy` keep
  the fleet from flapping: an up decision needs ``up_ticks`` consecutive
  breach polls, a down needs ``idle_ticks`` idle polls, and both
  directions refuse to fire inside the other's cooldown window.

Every decision is a flight event (``kind="autoscaler"``) and a
``paddle_tpu_fleet_scale_events_total{direction,reason}`` increment;
``paddle_tpu_fleet_replicas_{desired,alive,draining}`` gauges and
``GET /debug/fleet`` expose the fleet state.

**Simulation mode** (:class:`FleetSim`): the same :class:`ScalePolicy`
object drives virtual replicas through the shedder's latency model
(``prefill_s + token_s * backlog/slots``) in virtual time — no devices,
no sleeping — so scaling policy (flap resistance, drain deadlines, SLO
attainment vs replica-seconds on a flash-crowd trace) is testable in
tier-1 and benchable as a closed-loop curve instead of fixed-QPS points.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional

import numpy as np

from ..observability import flight, registry
from ..testing import faults

__all__ = ["ScalePolicy", "Autoscaler", "FleetSim",
           "FLEET_DESIRED", "FLEET_ALIVE", "FLEET_DRAINING",
           "FLEET_SCALE_EVENTS"]

FLEET_DESIRED = "paddle_tpu_fleet_replicas_desired"
FLEET_ALIVE = "paddle_tpu_fleet_replicas_alive"
FLEET_DRAINING = "paddle_tpu_fleet_replicas_draining"
FLEET_SCALE_EVENTS = "paddle_tpu_fleet_scale_events_total"


class ScalePolicy:
    """Pure decision function over the windowed telemetry feed.

    Stateful only in its streak counters and event stamps, and fed
    explicit ``now`` timestamps, so the SAME object drives the live
    control loop and the virtual-time simulator — and unit tests replay
    synthetic window feeds against it directly.

    Scale-up triggers (any, sustained for ``up_ticks`` polls):

    * ``ttft_headroom`` — the shedder's TTFT estimate ate the SLO
      headroom: ``est_ttft_s > (1 - headroom_frac) * slo_ttft_s``.
    * ``queue_wait_p99`` — windowed fair-share queue wait p99 breach.
    * ``shed_rate`` — sustained shedding (the fleet is rejecting work
      it should be absorbing).
    * ``slo_alert`` — (opt-in, ``scale_on_alerts=True``) the SLO
      engine's firing set is non-empty: the feed's optional
      ``firing_alerts`` field carries the burn-rate alerts a
      :class:`~paddle_tpu.observability.slo.SloEvaluator` is firing —
      the ROADMAP item-5b seam for SLO-class-aware scaling.

    Scale-down trigger (sustained for ``idle_ticks`` polls): queue
    empty, slot utilization at most ``idle_util``, no shedding, and the
    TTFT estimate comfortably inside the SLO (below ``idle_est_frac *
    slo_ttft_s``) — the hysteresis band between the up and down
    thresholds is what keeps a borderline fleet stable.

    Both directions carry a cooldown, and each direction also refuses
    to fire inside the OTHER's window (no up→down→up flap inside one
    cooldown).
    """

    def __init__(self, *, slo_ttft_s: float = 2.0,
                 headroom_frac: float = 0.25,
                 queue_wait_p99_s: float = 1.0,
                 shed_rate: float = 0.05,
                 up_ticks: int = 2, idle_ticks: int = 8,
                 idle_util: float = 0.25, idle_est_frac: float = 0.3,
                 cooldown_up_s: float = 10.0,
                 cooldown_down_s: float = 30.0,
                 min_window_requests: int = 1,
                 scale_on_alerts: bool = False):
        if not 0 < headroom_frac < 1 or not 0 < idle_est_frac < 1:
            raise ValueError("headroom_frac/idle_est_frac must be in (0,1)")
        self.slo_ttft_s = float(slo_ttft_s)
        self.headroom_frac = float(headroom_frac)
        self.queue_wait_p99_s = float(queue_wait_p99_s)
        self.shed_rate = float(shed_rate)
        self.up_ticks = int(up_ticks)
        self.idle_ticks = int(idle_ticks)
        self.idle_util = float(idle_util)
        self.idle_est_frac = float(idle_est_frac)
        self.cooldown_up_s = float(cooldown_up_s)
        self.cooldown_down_s = float(cooldown_down_s)
        self.min_window_requests = int(min_window_requests)
        self.scale_on_alerts = bool(scale_on_alerts)
        self._up_streak = 0
        self._idle_streak = 0
        self._last_up = float("-inf")
        self._last_down = float("-inf")

    # -- the decision ---------------------------------------------------------
    def breach_reason(self, feed: dict) -> str:
        """Which scale-up trigger (if any) the feed is breaching."""
        # a firing burn-rate alert already encodes target + hysteresis;
        # honouring it first lets per-class SLOs drive scale directly
        if self.scale_on_alerts and feed.get("firing_alerts"):
            return "slo_alert"
        est = feed.get("est_ttft_s")
        thresh = (1.0 - self.headroom_frac) * self.slo_ttft_s
        # a breach the fleet can actually fix: replicas drain backlog,
        # so est can at best fall to the prefill floor — if the floor
        # itself blows the threshold (cold-compile-contaminated EWMA,
        # or a genuinely unattainable SLO), adding chips changes
        # nothing and the fleet must stay free to scale DOWN
        if est is not None and est > thresh and \
                (feed.get("prefill_s") or 0.0) <= thresh:
            return "ttft_headroom"
        qw = feed.get("queue_wait_s") or {}
        if qw.get("n", 0) >= self.min_window_requests and \
                qw.get("p99", 0.0) > self.queue_wait_p99_s:
            return "queue_wait_p99"
        traffic = feed.get("requests", 0) + feed.get("shed", 0)
        if traffic >= self.min_window_requests and \
                feed.get("shed_rate", 0.0) >= self.shed_rate:
            return "shed_rate"
        return ""

    def is_idle(self, feed: dict) -> bool:
        util = feed.get("slots_in_use", 0) / max(1, feed.get(
            "total_slots", 1))
        est = feed.get("est_ttft_s")
        # judge the BACKLOG component of the estimate, not the prefill
        # floor: an idle fleet's est_ttft is exactly the prefill EWMA
        # (which early cold-compile observations inflate for a while),
        # and a fleet with zero backlog must still be able to shrink
        backlog_s = (None if est is None
                     else est - (feed.get("prefill_s") or 0.0))
        return (feed.get("queue_depth", 0) == 0 and
                util <= self.idle_util and
                feed.get("shed_rate", 0.0) == 0.0 and
                (backlog_s is None or
                 backlog_s < self.idle_est_frac * self.slo_ttft_s))

    def decide(self, feed: dict, *, replicas: int, min_replicas: int,
               max_replicas: int, now: float) -> tuple:
        """(direction, reason): ("up"/"down", trigger) or (None, "")."""
        reason = self.breach_reason(feed)
        if reason:
            self._up_streak += 1
            self._idle_streak = 0
        elif self.is_idle(feed):
            self._idle_streak += 1
            self._up_streak = 0
        else:
            self._up_streak = 0
            self._idle_streak = 0
        if reason and self._up_streak >= self.up_ticks and \
                replicas < max_replicas and \
                now - self._last_up >= self.cooldown_up_s and \
                now - self._last_down >= self.cooldown_up_s:
            return "up", reason
        if self._idle_streak >= self.idle_ticks and \
                replicas > min_replicas and \
                now - self._last_down >= self.cooldown_down_s and \
                now - self._last_up >= self.cooldown_down_s:
            return "down", "idle"
        return None, ""

    def note_event(self, direction: str, now: float):
        """Stamp a scale event (the autoscaler calls this when an op
        STARTS, the simulator when one applies): streaks reset, the
        cooldown clocks restart."""
        if direction == "up":
            self._last_up = now
        else:
            self._last_down = now
        self._up_streak = 0
        self._idle_streak = 0

    def snapshot(self) -> dict:
        return {
            "slo_ttft_s": self.slo_ttft_s,
            "headroom_frac": self.headroom_frac,
            "queue_wait_p99_s": self.queue_wait_p99_s,
            "shed_rate": self.shed_rate,
            "up_ticks": self.up_ticks, "idle_ticks": self.idle_ticks,
            "idle_util": self.idle_util,
            "cooldown_up_s": self.cooldown_up_s,
            "cooldown_down_s": self.cooldown_down_s,
            "scale_on_alerts": self.scale_on_alerts,
            "up_streak": self._up_streak, "idle_streak": self._idle_streak,
        }


class Autoscaler:
    """Control loop: gateway telemetry in, replica membership out.

    Args:
        stack: the :class:`~paddle_tpu.serving.gateway.Gateway` (or a
            ``GatewayStack`` — its ``.gateway`` is used) whose
            ``window_stats()`` feed and router this loop drives.
        factory: zero-arg callable returning a fresh Engine-shaped
            replica (an ``Engine``, or an ``EngineSupervisor`` for
            self-healing replicas — the production shape).  Called from
            the scale worker thread; a raise fails that scale-up, which
            is retried.  Build one model INSTANCE per replica inside
            the factory: a scale-up build traces its jit programs while
            existing replicas may be compiling new prefill buckets, and
            concurrent tracing over one shared module is not supported.
        min_replicas / max_replicas: hard fleet bounds; scale decisions
            clamp to them, and scale-down never drains the fleet below
            ``min_replicas``.
        policy: a :class:`ScalePolicy` (default one is built).
        poll_interval_s: control-thread poll period.
        drain_deadline_s: per-attempt deadline handed to
            ``replica.drain()`` during scale-down; drain is retried (a
            replica that died mid-drain was healed by its supervisor)
            until the replica is empty — scale-down NEVER kills.
        build_s_hint: seed for the cold-build EWMA before the first
            in-loop build completes (the shedder's Retry-After cap uses
            this to tell shed clients when capacity will arrive).
        name_prefix: replica names are ``{prefix}-s{N}`` with a
            monotone N (never reused, so per-engine metric series never
            collide across builds).
        warm_pool: parked standby replicas (ISSUE 20 / ROADMAP 5c).
            With ``warm_pool=1`` a background worker keeps one replica
            BUILT, PREWARMED and PARKED-DRAINING (``load()`` advertises
            not-alive, so it refuses work on the shelf): a flash
            scale-up routes the spare in instead of cold-building —
            reaction time is a route-in, not the cold-build EWMA — and
            a refill build starts in the background.  Spares follow the
            rollout controller's revision: a rollout upgrades the shelf
            too (stale-revision spares are torn down, never routed in).
    """

    def __init__(self, stack, factory: Callable[[], object], *,
                 min_replicas: int = 1, max_replicas: int = 4,
                 policy: Optional[ScalePolicy] = None,
                 poll_interval_s: float = 1.0,
                 drain_deadline_s: float = 30.0,
                 build_s_hint: float = 10.0,
                 name_prefix: str = "engine", warm_pool: int = 0,
                 start: bool = True):
        gateway = getattr(stack, "gateway", stack)
        if min_replicas < 1 or max_replicas < min_replicas:
            raise ValueError("need 1 <= min_replicas <= max_replicas")
        self.gateway = gateway
        self.factory = factory
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.policy = policy or ScalePolicy()
        self.poll_interval_s = float(poll_interval_s)
        self.drain_deadline_s = float(drain_deadline_s)
        self.name_prefix = str(name_prefix)
        self.warm_pool = int(warm_pool)
        self._lock = threading.Lock()
        self._stop_ev = threading.Event()
        self._wake_ev = threading.Event()
        self._op: Optional[dict] = None      # the in-flight scale op
        self._pending: Optional[tuple] = None  # (direction, reason) retry
        self._replica_n = 0
        self._build_ewma_s = float(build_s_hint)
        self._builds = 0
        self._events: deque = deque(maxlen=64)
        self._desired = len(gateway.router.names)
        self._warm: list = []           # parked (name, engine, revision)
        self._warm_building = False
        self._warm_n = 0
        self._thread: Optional[threading.Thread] = None
        gateway.attach_autoscaler(self)
        if start:
            self.start()

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        if self._stop_ev.is_set():
            raise RuntimeError("autoscaler is shut down")
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._thread = threading.Thread(
                target=self._run, name="paddle-tpu-autoscaler", daemon=True)
            self._thread.start()

    def shutdown(self):
        """Stop the control loop and tear down parked spares (routed
        replicas stay as they are — the stack owns their teardown)."""
        self._stop_ev.set()
        self._wake_ev.set()
        with self._lock:
            th = self._thread
        if th is not None:
            th.join(timeout=10)
        self.drop_warm_pool(reason="shutdown")

    close = shutdown

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False

    # -- control thread ------------------------------------------------------
    def _run(self):
        while not self._stop_ev.is_set():
            try:
                faults.fault_point("autoscaler.tick")
                self._tick()
            except Exception as e:  # noqa: BLE001 — a bad tick must not
                # kill the loop: the fleet would silently stop scaling.
                # Record it loudly and keep polling (the chaos matrix
                # crashes this seam on purpose).
                flight.record("autoscaler", "tick_error",
                              error=f"{type(e).__name__}: {e}")
            self._wake_ev.wait(self.poll_interval_s)
            self._wake_ev.clear()

    def _tick(self):
        gw = self.gateway
        loads = gw.router.loads()
        alive = sum(1 for ld in loads.values()
                    if ld["alive"] and not ld.get("draining"))
        draining = sum(1 for ld in loads.values() if ld.get("draining"))
        feed = gw.window_stats()
        feed["slots_in_use"] = sum(ld["slots_in_use"]
                                   for ld in loads.values())
        feed["total_slots"] = gw.router.total_slots()
        feed["prefill_s"] = gw.shedder.snapshot()["prefill_s"]
        # the SLO engine's firing set rides the policy feed (optional:
        # [] when no engine is attached) — ScalePolicy(scale_on_alerts=
        # True) scales on it, every policy sees it for introspection
        slo = getattr(gw, "slo_engine", None)
        feed["firing_alerts"] = slo.firing() if slo is not None else []
        with self._lock:
            op = self._op
            pending, self._pending = self._pending, None
            desired = self._desired
        self._gauges(desired, alive, draining)
        self._maybe_refill_warm()
        if op is not None:
            return                       # one scale op at a time
        now = time.monotonic()
        if pending is not None:
            direction, reason = pending
        else:
            direction, reason = self.policy.decide(
                feed, replicas=alive, min_replicas=self.min_replicas,
                max_replicas=self.max_replicas, now=now)
        if direction == "up" and alive + draining < self.max_replicas:
            self._start_op("up", reason, now)
        elif direction == "down" and alive > self.min_replicas:
            self._start_op("down", reason, now)

    def _gauges(self, desired: int, alive: int, draining: int):
        reg = registry()
        reg.gauge(FLEET_DESIRED, "replica count the autoscaler wants").set(
            float(desired))
        reg.gauge(FLEET_ALIVE, "alive, non-draining replicas").set(
            float(alive))
        reg.gauge(FLEET_DRAINING, "replicas draining for scale-down").set(
            float(draining))

    def _start_op(self, direction: str, reason: str, now: float):
        self.policy.note_event(direction, now)
        op = {"direction": direction, "reason": reason,
              "t0": time.monotonic()}
        with self._lock:
            self._op = op
            self._desired += 1 if direction == "up" else -1
            self._desired = max(self.min_replicas,
                                min(self.max_replicas, self._desired))
        worker = threading.Thread(
            target=self._scale_worker, args=(direction, reason),
            name=f"paddle-tpu-scale-{direction}", daemon=True)
        worker.start()

    # -- scale worker --------------------------------------------------------
    def _scale_worker(self, direction: str, reason: str):
        try:
            if direction == "up":
                self._scale_up(reason)
            else:
                self._scale_down(reason)
        except Exception as e:  # noqa: BLE001 — a scale op that died is
            # ABSORBED, never fatal: undo the desired-count move, count
            # it, and queue a retry for the next tick (the crash matrix
            # raises inside both seams on purpose)
            flight.record("autoscaler", f"scale_{direction}_failed",
                          reason=reason, error=f"{type(e).__name__}: {e}")
            registry().counter(
                FLEET_SCALE_EVENTS, "scale events by direction/reason").inc(
                1.0, labels={"direction": f"{direction}_failed",
                             "reason": reason})
            with self._lock:
                self._desired += -1 if direction == "up" else 1
                self._pending = (direction, reason)   # retry next tick
        finally:
            with self._lock:
                self._op = None
            self._wake_ev.set()

    def _scale_up(self, reason: str):
        spare = self._pop_warm()
        if spare is not None:
            name, engine, rev = spare
            flight.record("autoscaler", "scale_up_warm_begin",
                          replica=name, reason=reason)
            t0 = time.monotonic()
            # route-in, not a build: un-park (reverse the shelf drain)
            # and add to the router — reaction is milliseconds, so the
            # cold-build EWMA is NOT fed (it must keep measuring builds)
            undrain = getattr(engine, "undrain", None)
            if undrain is not None:
                undrain()
            self.gateway.router.add_replica(name, engine, revision=rev)
            route_s = time.monotonic() - t0
            with self._lock:
                self._events.append({
                    "t": time.time(), "direction": "up", "reason": reason,
                    "replica": name, "ms": round(route_s * 1e3, 1),
                    "warm": True})
            registry().counter(
                FLEET_SCALE_EVENTS, "scale events by direction/reason").inc(
                1.0, labels={"direction": "up", "reason": reason})
            flight.record("autoscaler", "scale_up_warm", replica=name,
                          reason=reason,
                          route_in_ms=round(route_s * 1e3, 1))
            self._wake_ev.set()          # refill the shelf promptly
            return
        rev, factory = self._current_factory()
        with self._lock:
            self._replica_n += 1
            name = f"{self.name_prefix}-s{self._replica_n}"
        flight.record("autoscaler", "scale_up_begin", replica=name,
                      reason=reason)
        t0 = time.monotonic()
        faults.fault_point("scale.up_build", replica=name)
        engine = factory()
        self.gateway.router.add_replica(name, engine, revision=rev)
        self._await_warm(engine)
        build_s = time.monotonic() - t0
        with self._lock:
            self._builds += 1
            a = 0.5 if self._builds > 1 else 1.0
            self._build_ewma_s = (1 - a) * self._build_ewma_s + a * build_s
            self._events.append({
                "t": time.time(), "direction": "up", "reason": reason,
                "replica": name, "ms": round(build_s * 1e3, 1)})
        registry().counter(
            FLEET_SCALE_EVENTS, "scale events by direction/reason").inc(
            1.0, labels={"direction": "up", "reason": reason})
        flight.record("autoscaler", "scale_up", replica=name,
                      reason=reason, build_ms=round(build_s * 1e3, 1))

    def _current_factory(self) -> tuple:
        """(revision, zero-arg factory) for the next cold build.  While
        a rollout controller is attached, builds follow ITS revision —
        the mid-rollout target, or the fleet's post-upgrade revision —
        so elasticity never resurrects a superseded build; without one,
        the constructor's factory at the fleet's revision."""
        ctl = getattr(self.gateway, "rollout", None)
        if ctl is not None:
            return ctl.revision(), ctl.factory()
        revs = self.gateway.router.revisions()
        return next(iter(revs.values()), "r0"), self.factory

    def _await_warm(self, engine, timeout_s: float = 120.0):
        """Hold the scale-up op open until the new replica is WARM (its
        decode program compiled) — "warm-up completion" is what the
        cold-build EWMA must measure, because that is when shed clients
        can actually be served.  Returns early when the fleet went idle
        (no traffic will warm the replica) or the engine has no health
        surface (router stubs in tests)."""
        health = getattr(engine, "health", None)
        if health is None:
            return
        deadline = time.monotonic() + timeout_s
        while not self._stop_ev.is_set() and time.monotonic() < deadline:
            try:
                h = health()
            except Exception:  # noqa: BLE001 — treat as not warmable
                return
            if h.get("warm") or h.get("dead"):
                return
            ld = engine.load()
            if self.gateway.scheduler.depth() == 0 and \
                    ld["queue_depth"] == 0 and ld["slots_in_use"] == 0:
                return                  # breach evaporated: nothing to warm
            time.sleep(0.05)

    def _pick_victim(self):
        """(name, engine) with the least load among removable replicas
        (alive, not draining, not the last ``min_replicas``).  While a
        rollout is active its target-revision replicas — the canary and
        the surge builds — are PROTECTED: scaling one of them down
        would unwind the upgrade mid-flight."""
        router = self.gateway.router
        loads = router.loads()
        alive = [n for n, ld in loads.items()
                 if ld["alive"] and not ld.get("draining")]
        if len(alive) <= self.min_replicas:
            return None
        ctl = getattr(self.gateway, "rollout", None)
        protected = ctl.protected() if ctl is not None else frozenset()
        candidates = [n for n in alive if n not in protected]
        if not candidates:
            return None
        victim = min(candidates, key=lambda n: (loads[n]["slots_in_use"] +
                                                loads[n]["queue_depth"], n))
        engines = dict(zip(router.names, router.engines))
        eng = engines.get(victim)
        return (victim, eng) if eng is not None else None

    def _scale_down(self, reason: str):
        picked = self._pick_victim()
        if picked is None:
            with self._lock:
                self._desired += 1
            return
        name, eng = picked
        flight.record("autoscaler", "scale_down_begin", replica=name,
                      reason=reason)
        t0 = time.monotonic()
        faults.fault_point("scale.down_drain", replica=name)
        # drain-before-remove, retried until EMPTY: a replica that dies
        # mid-drain is healed by its supervisor (the rebuilt engine is
        # not draining), so we re-issue the drain against the current
        # build — scale-down never kills in-flight work
        attempts = 0
        while not self._stop_ev.is_set():
            attempts += 1
            if eng.drain(self.drain_deadline_s):
                break
            flight.record("autoscaler", "drain_retry", replica=name,
                          attempt=attempts)
            # a drain that returns False INSTANTLY (the replica died
            # and its supervisor is mid-rebuild, or a never-warmed
            # engine is settling) must not spin this worker hot
            self._stop_ev.wait(min(0.05 * attempts, 0.5))
        else:
            with self._lock:
                self._desired += 1
            return                      # shut down mid-drain: leave it
        try:
            self.gateway.router.remove_replica(name)
        except (KeyError, ValueError) as e:
            # raced a concurrent removal or the fleet shrank under us:
            # the drain already emptied the replica, just tear it down
            flight.record("autoscaler", "remove_raced", replica=name,
                          error=f"{type(e).__name__}: {e}")
        try:
            eng.shutdown()              # teardown releases ledger rows
        except Exception:  # noqa: BLE001 — the replica is already empty
            pass
        drain_s = time.monotonic() - t0
        with self._lock:
            self._events.append({
                "t": time.time(), "direction": "down", "reason": reason,
                "replica": name, "ms": round(drain_s * 1e3, 1)})
        registry().counter(
            FLEET_SCALE_EVENTS, "scale events by direction/reason").inc(
            1.0, labels={"direction": "down", "reason": reason})
        flight.record("autoscaler", "scale_down", replica=name,
                      reason=reason, drain_ms=round(drain_s * 1e3, 1),
                      drain_attempts=attempts)

    # -- warm pool (ROADMAP 5c) ----------------------------------------------
    def _maybe_refill_warm(self):
        """Kick the background refill when the shelf is short (one
        refill build at a time; every control-loop tick checks)."""
        if self.warm_pool <= 0:
            return
        with self._lock:
            if self._warm_building or len(self._warm) >= self.warm_pool:
                return
            self._warm_building = True
        threading.Thread(target=self._warm_build_worker,
                         name="paddle-tpu-warm-pool", daemon=True).start()

    def _warm_build_worker(self):
        try:
            rev, factory = self._current_factory()
            with self._lock:
                self._warm_n += 1
                name = f"{self.name_prefix}-w{self._warm_n}"
            t0 = time.monotonic()
            eng = factory()
            self._prewarm(eng)
            try:
                # park: the shelf drain makes load() advertise
                # not-alive, so the spare refuses work until routed in
                eng.drain(0.5)
            except Exception:  # noqa: BLE001 — stubs without drain park as-is
                pass
            with self._lock:
                parked = (not self._stop_ev.is_set() and
                          len(self._warm) < self.warm_pool)
                if parked:
                    self._warm.append((name, eng, rev))
            if not parked:
                try:
                    eng.shutdown()
                except Exception:  # noqa: BLE001 — never routed
                    pass
                return
            flight.record("autoscaler", "warm_park", replica=name,
                          revision=rev,
                          build_ms=round((time.monotonic() - t0) * 1e3, 1))
        except Exception as e:  # noqa: BLE001 — a failed refill is
            # absorbed; the next tick retries it
            flight.record("autoscaler", "warm_build_failed",
                          error=f"{type(e).__name__}: {e}")
        finally:
            with self._lock:
                self._warm_building = False

    @staticmethod
    def _prewarm(eng):
        """Compile the spare's programs BEFORE parking — a spare that
        still owes its cold compile would make the warm route-in a lie.
        Best-effort: stub engines park un-warmed."""
        try:
            h = eng.submit(np.arange(1, 5, dtype=np.int64),
                           max_new_tokens=2)
            h.result(timeout=120)
        except Exception:  # noqa: BLE001 — warmth is an optimisation
            pass

    def _pop_warm(self):
        """The first parked spare at the fleet's CURRENT target
        revision; stale-revision spares found on the way are torn down
        (an old build must never route into an upgraded fleet)."""
        if self.warm_pool <= 0:
            return None
        ctl = getattr(self.gateway, "rollout", None)
        want = ctl.revision() if ctl is not None else None
        picked = None
        stale = []
        with self._lock:
            keep = []
            for item in self._warm:
                if want is not None and item[2] != want:
                    stale.append(item)
                elif picked is None:
                    picked = item
                else:
                    keep.append(item)
            self._warm = keep
        for name, eng, rev in stale:
            flight.record("autoscaler", "warm_drop", replica=name,
                          revision=rev, reason="stale_revision")
            try:
                eng.shutdown()
            except Exception:  # noqa: BLE001 — never routed
                pass
        return picked

    def drop_warm_pool(self, keep_revision: Optional[str] = None,
                       reason: str = "rollout"):
        """Tear down parked spares NOT at ``keep_revision`` (the
        rollout controller calls this after an upgrade, so the shelf
        refills at the new revision; ``None`` drops everything)."""
        with self._lock:
            keep, drop = [], []
            for item in self._warm:
                (keep if (keep_revision is not None and
                          item[2] == keep_revision) else drop).append(item)
            self._warm = keep
        for name, eng, rev in drop:
            flight.record("autoscaler", "warm_drop", replica=name,
                          revision=rev, reason=reason)
            try:
                eng.shutdown()
            except Exception:  # noqa: BLE001 — never routed
                pass
        if drop:
            self._wake_ev.set()          # refill promptly

    # -- operator / gateway surface ------------------------------------------
    def trigger(self, direction: str, reason: str = "manual"):
        """Queue one scale event for the next tick (operator nudge; the
        chaos lane uses it to schedule kills DURING scale events)."""
        if direction not in ("up", "down"):
            raise ValueError("direction must be 'up' or 'down'")
        with self._lock:
            self._pending = (direction, reason)
        self._wake_ev.set()

    def scale_pending(self) -> bool:
        """True while a scale-UP is building or queued — the gateway
        treats this as capacity-on-the-way (no all-dead 503 while the
        only other replica drains)."""
        with self._lock:
            return ((self._op is not None and
                     self._op["direction"] == "up") or
                    (self._pending is not None and
                     self._pending[0] == "up"))

    def expected_ready_s(self) -> Optional[float]:
        """Expected seconds until the in-flight scale-up's replica takes
        traffic (cold-build EWMA minus elapsed build time); None when no
        scale-up is in flight.  The LoadShedder caps 429 ``Retry-After``
        at this, so shed clients come back when capacity arrives."""
        with self._lock:
            if self._op is not None and self._op["direction"] == "up":
                elapsed = time.monotonic() - self._op["t0"]
                return max(0.1, self._build_ewma_s - elapsed)
            if self._pending is not None and self._pending[0] == "up":
                return max(0.1, self._build_ewma_s)
        return None

    @property
    def desired(self) -> int:
        with self._lock:
            return self._desired

    def events(self) -> list:
        with self._lock:
            return list(self._events)

    def fleet_stats(self) -> dict:
        """The ``/debug/fleet`` payload: bounds, desired count, the
        in-flight op, the cold-build EWMA, recent scale events and the
        policy's threshold snapshot."""
        with self._lock:
            op = dict(self._op) if self._op is not None else None
            out = {
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "desired": self._desired,
                "build_ewma_s": round(self._build_ewma_s, 3),
                "builds": self._builds,
                "events": list(self._events),
                "warm_pool": {
                    "size": self.warm_pool,
                    "building": self._warm_building,
                    "parked": [{"replica": n, "revision": r}
                               for n, _, r in self._warm]},
            }
        if op is not None:
            op["elapsed_s"] = round(time.monotonic() - op.pop("t0"), 3)
        out["op"] = op
        out["policy"] = self.policy.snapshot()
        return out


# -- simulation mode ----------------------------------------------------------

class _SimReplica:
    __slots__ = ("name", "state", "ready_at", "active", "born_at")

    def __init__(self, name, state, now, ready_at=0.0):
        self.name = name
        self.state = state            # "building" | "up" | "draining"
        self.ready_at = ready_at
        self.active: list = []        # [(finish_t, ttft_ok)] in-flight
        self.born_at = now


class FleetSim:
    """Virtual-time closed loop: the shedder's latency model against
    virtual replicas, driven by the SAME :class:`ScalePolicy` the live
    autoscaler runs — no devices, no wall-clock sleeping, deterministic
    for a seeded trace.

    Service model (the shed formula, applied literally): a request
    occupies one slot for ``prefill_s + max_tokens * token_s``; TTFT =
    queue wait + ``prefill_s``; admission sheds a deadline-carrying
    request when ``prefill_s + token_s * backlog_tokens / total_slots``
    blows its deadline.  Builds take ``build_s`` of virtual time (a
    building replica burns replica-seconds but serves nothing); a
    draining replica finishes its in-flight work, takes nothing new,
    and leaves the fleet when empty.

    ``run(trace)`` consumes ``tools/load_gen.py`` trace entries
    (dicts with ``t``, ``prompt_len``, ``max_tokens``, optional
    ``deadline_s``, optional ``tenant``/``priority``) and reports SLO
    attainment, replica-seconds, scale events and flap count — the
    bench's attainment-vs-cost curve.

    With ``slo_evaluator`` (a :class:`~paddle_tpu.observability.slo.
    SloEvaluator`), the sim also feeds a keyed
    :class:`~paddle_tpu.observability.journey.TelemetryWindow` in
    virtual time — completions at their virtual finish, sheds at shed
    time — and steps the evaluator at every policy poll: the result
    grows an ``"slo"`` block (transitions + per-poll series), and the
    policy feed carries ``firing_alerts`` exactly like the live loop,
    so burn-rate alerting and alert-driven scaling are benchable
    deterministically.
    """

    def __init__(self, policy: Optional[ScalePolicy] = None, *,
                 min_replicas: int = 1, max_replicas: int = 4,
                 start_replicas: Optional[int] = None,
                 slots_per_replica: int = 4,
                 prefill_s: float = 0.05, token_s: float = 0.01,
                 build_s: float = 2.0, slo_ttft_s: Optional[float] = None,
                 tick_s: float = 0.02, policy_poll_s: float = 0.25,
                 window_s: float = 5.0, slo_evaluator=None,
                 warm_pool: int = 0, route_in_s: float = 0.05):
        self.policy = policy
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.start_replicas = (self.min_replicas if start_replicas is None
                               else int(start_replicas))
        self.slots = int(slots_per_replica)
        self.prefill_s = float(prefill_s)
        self.token_s = float(token_s)
        self.build_s = float(build_s)
        self.slo_ttft_s = float(
            slo_ttft_s if slo_ttft_s is not None else
            (policy.slo_ttft_s if policy is not None else 2.0))
        self.tick_s = float(tick_s)
        self.policy_poll_s = float(policy_poll_s)
        self.window_s = float(window_s)
        self.slo_evaluator = slo_evaluator
        # warm pool (ROADMAP 5c): `warm_pool` pre-built spares sit on
        # the shelf burning replica-seconds; an up decision consumes
        # one — the new replica matures in `route_in_s` instead of
        # `build_s` — and a refill build (build_s) restocks the shelf
        self.warm_pool = int(warm_pool)
        self.route_in_s = float(route_in_s)

    def _est_ttft(self, queue, fleet, now: float) -> float:
        # the shed formula over SERVICE time: a new arrival waits for
        # the queued + in-flight work ahead of it to drain through the
        # fleet's slots (each request holds a slot for prefill +
        # tokens*token_s — counting only token cost would blind the
        # estimate exactly when prefill dominates).  In-flight work
        # counts its RESIDUAL, not its full service.
        backlog_s = sum(r["service"] for r in queue)
        for rep in fleet:
            backlog_s += sum(max(0.0, a[0] - now) for a in rep.active)
        slots = sum(self.slots for rep in fleet if rep.state == "up") or 1
        return self.prefill_s + backlog_s / slots

    def run(self, trace) -> dict:
        import heapq
        trace = sorted(trace, key=lambda e: e["t"])
        n_arrivals = len(trace)
        fleet = [_SimReplica(f"sim{i}", "up", 0.0)
                 for i in range(self.start_replicas)]
        next_name = self.start_replicas
        queue: list = []                 # waiting requests
        done: list = []                  # {t, ttft, wait} completion log
        sheds: list = []                 # shed timestamps
        events: list = []                # scale events {t, direction, reason}
        ev = self.slo_evaluator
        tw = None
        pending_obs: list = []           # heap: completions by finish time
        obs_seq = 0                      # heap tiebreak (dicts don't order)
        slo_transitions: list = []
        slo_series: list = []
        slo_att_series: list = []
        if ev is not None:
            from ..observability.journey import TelemetryWindow
            tw = TelemetryWindow(window_s=max(
                o.slow_window_s for o in ev.objectives))
        t = 0.0
        i = 0                            # trace cursor
        next_poll = self.policy_poll_s
        replica_seconds = 0.0
        peak = len(fleet)
        spares = self.warm_pool          # parked spares, ready now
        refills: list = []               # refill builds, by finish time
        warm_route_ins = 0
        t_end_cap = (trace[-1]["t"] if trace else 0.0) + 300.0
        while t <= t_end_cap:
            # warm-pool refills mature
            while refills and refills[0] <= t:
                refills.pop(0)
                spares += 1
            # arrivals
            while i < len(trace) and trace[i]["t"] <= t:
                e = trace[i]
                i += 1
                service = self.prefill_s + e["max_tokens"] * self.token_s
                deadline = e.get("deadline_s")
                if deadline is not None and \
                        self._est_ttft(queue, fleet, t) > deadline:
                    sheds.append(t)
                    if tw is not None:
                        tw.observe_shed("slo_shed", now=t,
                                        tenant=e.get("tenant"),
                                        priority=e.get("priority"))
                    continue
                queue.append({"t_arr": e["t"], "service": service,
                              "tokens": int(e["max_tokens"]),
                              "tenant": e.get("tenant"),
                              "priority": e.get("priority")})
            # builds mature
            for rep in fleet:
                if rep.state == "building" and rep.ready_at <= t:
                    rep.state = "up"
            # completions
            for rep in fleet:
                if rep.active:
                    rep.active = [a for a in rep.active if a[0] > t]
            # drains finishing: empty draining replicas leave the fleet
            removed = [rep for rep in fleet
                       if rep.state == "draining" and not rep.active]
            if removed:
                fleet = [rep for rep in fleet if rep not in removed]
            # dispatch queue -> least-loaded up replica with a free slot
            while queue:
                ups = [rep for rep in fleet if rep.state == "up" and
                       len(rep.active) < self.slots]
                if not ups:
                    break
                rep = min(ups, key=lambda r: len(r.active))
                req = queue.pop(0)
                wait = t - req["t_arr"]
                ttft = wait + self.prefill_s
                finish = t + req["service"]
                rep.active.append((finish, ttft <= self.slo_ttft_s,
                                   req["service"]))
                done.append({"t": finish, "ttft": ttft, "wait": wait})
                if tw is not None:
                    # the window sees the completion at its virtual
                    # FINISH time, not at dispatch — burn rates must
                    # lag reality exactly like the live loop's do
                    obs_seq += 1
                    heapq.heappush(pending_obs, (finish, obs_seq, {
                        "ttft_s": ttft, "queue_wait_s": wait,
                        "wall_s": wait + req["service"],
                        "tenant": req["tenant"],
                        "priority": req["priority"]}))
            # policy poll (+ SLO evaluator tick at the same cadence)
            if (self.policy is not None or ev is not None) \
                    and t >= next_poll:
                next_poll += self.policy_poll_s
                firing = []
                if ev is not None:
                    while pending_obs and pending_obs[0][0] <= t:
                        finish, _, obs = heapq.heappop(pending_obs)
                        tw.observe_sample(now=finish, **obs)
                    slo_transitions.extend(ev.tick(tw, now=t))
                    firing = ev.firing()
                    slo_series.extend(
                        dict(row, t=round(t, 3)) for row in ev.state())
                    # attainment over the whole SLO period so far (the
                    # trace IS the compliance window) — the burn-rate
                    # alert's job is to lead THIS curve's breach
                    n_done = n_hit = 0
                    for d in done:
                        if d["t"] <= t:
                            n_done += 1
                            n_hit += d["ttft"] <= self.slo_ttft_s
                    slo_att_series.append({
                        "t": round(t, 3),
                        "attainment": round(n_hit / n_done, 4)
                        if n_done else None})
                if self.policy is not None:
                    feed = self._feed(t, queue, fleet, done, sheds)
                    feed["firing_alerts"] = firing
                    decision, reason = self.policy.decide(
                        feed,
                        replicas=sum(1 for r in fleet if r.state == "up"),
                        min_replicas=self.min_replicas,
                        max_replicas=self.max_replicas, now=t)
                    if decision == "up" and \
                            len(fleet) < self.max_replicas:
                        self.policy.note_event("up", t)
                        if spares > 0:
                            # route the parked spare in: reaction is a
                            # route-in, and a refill restocks the shelf
                            spares -= 1
                            warm_route_ins += 1
                            reaction = self.route_in_s
                            refills.append(t + self.build_s)
                            refills.sort()
                        else:
                            reaction = self.build_s
                        fleet.append(_SimReplica(
                            f"sim{next_name}", "building", t,
                            ready_at=t + reaction))
                        next_name += 1
                        events.append({"t": round(t, 3),
                                       "direction": "up",
                                       "reason": reason,
                                       "warm": reaction < self.build_s,
                                       "reaction_s": round(reaction, 4)})
                    elif decision == "down":
                        ups = [r for r in fleet if r.state == "up"]
                        if len(ups) > self.min_replicas:
                            self.policy.note_event("down", t)
                            victim = min(ups,
                                         key=lambda r: len(r.active))
                            victim.state = "draining"
                            events.append({"t": round(t, 3),
                                           "direction": "down",
                                           "reason": reason})
            # spares and in-flight refills burn replica-seconds too —
            # the warm pool's cost side of the bench's attainment curve
            replica_seconds += (len(fleet) + spares +
                                len(refills)) * self.tick_s
            peak = max(peak, len(fleet))
            if i >= len(trace) and not queue and \
                    all(not rep.active for rep in fleet) and \
                    not pending_obs and (ev is None or not ev.firing()):
                break
            t += self.tick_s
        # completions recorded at dispatch may nominally finish past the
        # loop's last tick; they are in `done` already (finish stamped)
        hits = sum(1 for d in done if d["ttft"] <= self.slo_ttft_s)
        ttfts = sorted(d["ttft"] for d in done)
        flaps = self._count_flaps(events)
        slo_block = None
        if ev is not None:
            slo_block = {
                "transitions": slo_transitions,
                "fired": sum(1 for tr in slo_transitions
                             if tr["to"] == "firing"),
                "resolved": sum(1 for tr in slo_transitions
                                if tr["to"] == "resolved"),
                "series": slo_series,
                "attainment_series": slo_att_series,
            }
        warm_block = None
        if self.warm_pool > 0:
            reactions = [e["reaction_s"] for e in events
                         if e.get("warm")]
            warm_block = {
                "pool": self.warm_pool,
                "route_in_s": self.route_in_s,
                "warm_route_ins": warm_route_ins,
                "max_warm_reaction_s": round(max(reactions), 4)
                if reactions else None,
            }
        return {
            "slo": slo_block,
            "warm": warm_block,
            "arrivals": n_arrivals,
            "completed": len(done),
            "shed": len(sheds),
            "slo_attainment": round(hits / n_arrivals, 4) if n_arrivals
            else 1.0,
            "replica_seconds": round(replica_seconds, 2),
            "peak_replicas": peak,
            "final_replicas": len(fleet),
            "events": events,
            "flaps": flaps,
            "duration_s": round(t, 2),
            "ttft_p50_s": round(_pct(ttfts, 0.50), 4) if ttfts else None,
            "ttft_p99_s": round(_pct(ttfts, 0.99), 4) if ttfts else None,
        }

    def _feed(self, t, queue, fleet, done, sheds) -> dict:
        lo = t - self.window_s
        recent = [d for d in done if lo < d["t"] <= t]
        recent_shed = [s for s in sheds if lo < s <= t]
        waits = sorted(d["wait"] for d in recent)
        ttfts = sorted(d["ttft"] for d in recent)
        n = len(recent)
        denom = n + len(recent_shed)
        return {
            "est_ttft_s": self._est_ttft(queue, fleet, t),
            "queue_wait_s": {"p50": _pct(waits, 0.5),
                             "p99": _pct(waits, 0.99), "n": n},
            "ttft_s": {"p50": _pct(ttfts, 0.5),
                       "p99": _pct(ttfts, 0.99), "n": n},
            "requests": n,
            "shed": len(recent_shed),
            "shed_rate": round(len(recent_shed) / denom, 4) if denom
            else 0.0,
            "queue_depth": len(queue),
            "slots_in_use": sum(len(r.active) for r in fleet),
            "total_slots": sum(self.slots for r in fleet
                               if r.state == "up") or 1,
        }

    def _count_flaps(self, events) -> int:
        """up→down (or down→up) direction changes inside one cooldown
        window — the thing hysteresis + per-direction cooldowns exist
        to prevent; the bench gates this at zero."""
        if self.policy is None:
            return 0
        window = min(self.policy.cooldown_up_s,
                     self.policy.cooldown_down_s)
        flaps = 0
        for a, b in zip(events, events[1:]):
            if a["direction"] != b["direction"] and \
                    b["t"] - a["t"] < window:
                flaps += 1
        return flaps


def _pct(vals, q: float) -> float:
    if not vals:
        return 0.0
    if len(vals) == 1:
        return float(vals[0])
    pos = q * (len(vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(vals) - 1)
    frac = pos - lo
    return float(vals[lo] * (1 - frac) + vals[hi] * frac)
