"""PrefixIndex — content-addressed index of KV rows resident in the pool.

Real serving traffic is dominated by requests that share a long common
prefix (the system prompt).  Without this index every such request pays
a full prefill — recomputing K/V for tokens whose cache rows are already
sitting in HBM from the previous request.  The index makes those rows
*addressable by content*: when a request completes, its slot row (which
holds the K/V of ``prompt + generated[:-1]``) is RETAINED instead of
freed; a later request whose prompt starts with a prefix of those tokens
copies the row and prefills only the tail.

Design (host-side only; the engine lock guards every call):

* **Block-aligned content addressing.**  Causality makes the first ``m``
  KV rows of a cached sequence valid for ANY request whose prompt starts
  with those ``m`` tokens — so an entry is useful at every prefix
  length, not just its full content.  Hashing every prefix would cost
  O(n²); instead each entry registers under its prefixes at **block
  boundaries** (``block`` tokens, default 16 — the vLLM block-hash
  arrangement): a dict keyed by the token tuple is the hash table, the
  tuple itself the collision check.  Lookup probes the prompt's block
  boundaries longest-first and returns ``(entry, matched_len)`` —
  O(prompt/block) probes.  The match is capped at ``len(prompt) - 1``:
  the last prompt position is always (re)prefilled because its forward
  produces the first-token logits.
* **Refcounts.**  A hit pins the source entry (``refs += 1``) for the
  lifetime of the hitting request; the eviction sweep only reclaims
  entries with ``refs == 0``, so a row being used as a copy source for
  in-flight work can never be pulled out from under it.
* **LRU eviction.**  Cached rows occupy pool slots.  When admission
  needs slots and the free list is short, the engine asks the index to
  release its least-recently-used unreferenced entries back to the free
  list — cache capacity is exactly the pool slack, no second buffer.

The index belongs to one Engine build: a supervisor rebuild constructs a
fresh engine (new pools, new index), so a crashed build's rows are
dropped wholesale — there is no path by which a stale row survives into
the rebuilt pool (chaos-asserted).
"""
from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Tuple

__all__ = ["PrefixEntry", "PrefixIndex"]


class PrefixEntry:
    """One resident cached prefix.  Dense pool: ``slot`` is the pool row
    caching the K/V of ``tokens`` (``pages`` is None).  Paged pool
    (``Engine(paged_kv=True)``): ``pages`` is the ordered physical page
    list backing those tokens and ``slot`` is None — a cached prefix
    holds pages, not a slot lane, so caching never costs decode
    capacity and a hit shares the pages by reference (COW).  ``ns`` is
    the entry's namespace (the serving engine keys entries by
    ``(adapter, tokens)`` — two adapters' identical prompts produce
    DIFFERENT K/V, so tenants never share cache rows across adapters)."""

    __slots__ = ("slot", "tokens", "refs", "tick", "keys", "pages", "ns")

    def __init__(self, slot: Optional[int], tokens: Tuple[int, ...],
                 tick: int, pages: Optional[List[int]] = None, ns=None):
        self.slot = slot
        self.tokens = tokens
        self.refs = 0
        self.tick = tick          # LRU clock: touched on insert and hit
        self.keys: List[Tuple] = []             # registered prefix keys
        self.pages = pages        # paged mode: physical pages, in order
        self.ns = ns              # namespace: (ns, tokens) is the identity

    @property
    def n(self) -> int:
        return len(self.tokens)

    def __repr__(self):
        return (f"PrefixEntry(slot={self.slot}, n={self.n}, "
                f"refs={self.refs})")


class PrefixIndex:
    """Content-addressed prefix → resident-slot map with refcounts + LRU.

    Purely host-side bookkeeping (like SlotPool); the caller holds the
    engine lock.  No device arrays live here — the entry's ``slot`` is
    the pointer into the engine's pool buffers.
    """

    def __init__(self, block: int = 16):
        if int(block) < 1:
            raise ValueError(f"block must be >= 1, got {block}")
        self.block = int(block)
        self._entries: Dict[Tuple, PrefixEntry] = {}     # (ns, tokens)
        self._by_prefix: Dict[Tuple, PrefixEntry] = {}   # (ns, prefix)
        self._by_slot: Dict[int, PrefixEntry] = {}
        self._clock = itertools.count(1)
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def n_evictable(self) -> int:
        return sum(1 for e in self._entries.values() if e.refs == 0)

    @property
    def n_cached_tokens(self) -> int:
        """Tokens whose KV rows the index keeps resident — the content
        behind the HBM-ledger ``prefix_cache`` sub-owner's bytes."""
        return sum(e.n for e in self._entries.values())

    def _boundaries(self, n: int):
        """Block boundaries <= n, longest first (never 0)."""
        b = (n // self.block) * self.block
        while b >= self.block:
            yield b
            b -= self.block

    def lookup(self, prompt, peek: bool = False,
               ns=None) -> Optional[Tuple[PrefixEntry, int]]:
        """Longest block-aligned cached prefix of ``prompt`` (capped at
        ``len(prompt) - 1``; the last prompt token is always re-prefilled:
        its forward yields the first-token logits).  Returns
        ``(entry, matched_len)`` — the first ``matched_len`` KV rows of
        ``entry.slot`` are exactly the K/V of ``prompt[:matched_len]``.
        Counts a hit/miss and touches the LRU clock; the caller must
        :meth:`acquire` the entry if it uses it.  ``peek=True`` probes
        without counting or touching — the engine uses it to find which
        entries an incoming admission wave would hit, so the eviction
        sweep can spare them.  ``ns`` scopes the probe: only entries
        inserted under the same namespace can match."""
        toks = tuple(int(t) for t in prompt)
        for m in self._boundaries(len(toks) - 1):
            entry = self._by_prefix.get((ns, toks[:m]))
            if entry is not None:
                if not peek:
                    entry.tick = next(self._clock)
                    self.hits += 1
                return entry, m
        if not peek:
            self.misses += 1
        return None

    def insert(self, slot: Optional[int], tokens,
               pages: Optional[List[int]] = None,
               ns=None) -> Optional[PrefixEntry]:
        """Retain ``slot`` (dense) or ``pages`` (paged) as the resident
        K/V for ``tokens`` under namespace ``ns``, registering it under
        every block-boundary prefix.  Returns the new entry, or None
        when nothing would become addressable (duplicate content in the
        same namespace, or shorter than one block) — the caller then
        frees the slot/pages normally instead of retaining a useless
        row."""
        key = tuple(int(t) for t in tokens)
        if len(key) < self.block or (ns, key) in self._entries:
            return None
        entry = PrefixEntry(slot, key, next(self._clock), pages=pages,
                            ns=ns)
        self._entries[(ns, key)] = entry
        if slot is not None:
            self._by_slot[slot] = entry
        for m in self._boundaries(len(key)):
            pk = (ns, key[:m])
            # newest entry wins a shared prefix key: recency is the
            # better eviction survivor, and any matching row is correct
            self._by_prefix[pk] = entry
            entry.keys.append(pk)
        return entry

    def touch(self, entry: PrefixEntry):
        """Count a hit that was resolved earlier via ``lookup(peek=True)``
        under the same lock hold (the paged admission loop peeks first to
        size the page reservation, then commits)."""
        entry.tick = next(self._clock)
        self.hits += 1

    def miss(self):
        """Count a miss resolved via a peek (see :meth:`touch`)."""
        self.misses += 1

    def acquire(self, entry: PrefixEntry):
        entry.refs += 1

    def release(self, entry: PrefixEntry):
        if entry.refs > 0:
            entry.refs -= 1

    def _unlink(self, entry: PrefixEntry):
        del self._entries[(entry.ns, entry.tokens)]
        if entry.slot is not None:
            del self._by_slot[entry.slot]
        for pk in entry.keys:
            if self._by_prefix.get(pk) is entry:
                del self._by_prefix[pk]

    def evict_lru(self, want: int, protect=()) -> List[PrefixEntry]:
        """Drop up to ``want`` least-recently-used entries with
        ``refs == 0`` (referenced rows are copy sources for in-flight
        requests and survive every sweep; so do entries whose ``id`` is
        in ``protect`` — the ones the admission wave being made room for
        is about to hit).  Returns the dropped entries; the caller
        returns their slots to the pool's free list."""
        victims = sorted((e for e in self._entries.values()
                          if e.refs == 0 and id(e) not in protect),
                         key=lambda e: e.tick)[:want]
        for e in victims:
            self._unlink(e)
            self.evictions += 1
        return victims

    def entry_for_slot(self, slot: int) -> Optional[PrefixEntry]:
        return self._by_slot.get(slot)

    def drop_all(self) -> List[PrefixEntry]:
        """Forget every entry (engine shutdown/death); refcounts included
        — the pool the slots point into is going away."""
        out = list(self._entries.values())
        self._entries.clear()
        self._by_prefix.clear()
        self._by_slot.clear()
        return out

    def stats(self) -> dict:
        return {"entries": len(self._entries),
                "evictable": self.n_evictable,
                "hits": self.hits, "misses": self.misses,
                "evictions": self.evictions}

    def __repr__(self):
        return (f"PrefixIndex(block={self.block}, "
                f"entries={len(self._entries)}, hits={self.hits}, "
                f"misses={self.misses}, evictions={self.evictions})")
