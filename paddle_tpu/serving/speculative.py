"""Self-speculative drafting — propose k tokens per pool read.

Decode is HBM-bound: each step reads every parameter plus the live KV
pool to emit ONE token (docs/PERF.md round 5).  Speculative decoding
re-prices that read: draft ``k`` tokens cheaply on the host, then verify
all of them in one batched forward through the same per-slot
static-cache branch the plain decode uses — every position's logits come
back, the longest draft prefix that matches the model's own (greedy)
choices is accepted, and the step emits ``accepted + 1`` tokens for one
pool read.  Greedy output is *token-identical* to the non-speculative
path by construction: an accepted draft is accepted precisely because it
equals the token the model would have emitted.

The default drafter is **prompt-lookup / n-gram**: find the most recent
earlier occurrence of the context's trailing n-gram and propose the
tokens that followed it.  It is free (no draft model, no extra device
work) and strong exactly where speculative decoding pays off —
contexts with self-similar continuations (shared prompts, quoting,
code, the loops small models fall into).  A learned draft model drops
into the same seam: ``Engine(drafter=...)`` takes any callable
``drafter(context_ids, n) -> n proposed ids``.
"""
from __future__ import annotations

import numpy as np

__all__ = ["NgramDrafter"]


class NgramDrafter:
    """Prompt-lookup drafter: propose the continuation of the most
    recent earlier occurrence of the context's trailing n-gram.

    Args:
        max_ngram: longest suffix n-gram to probe (longest first — a
            longer match is a stronger prediction).
        min_ngram: shortest n-gram worth matching; below it the drafter
            pads with the last context token (a cheap "repeat" guess
            that costs nothing when wrong).
    """

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if min_ngram < 1 or max_ngram < min_ngram:
            raise ValueError(f"need 1 <= min_ngram <= max_ngram, got "
                             f"{min_ngram}..{max_ngram}")
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)

    def __call__(self, context, n: int) -> np.ndarray:
        """``context`` (1-D int token ids, prompt + generated so far) →
        ``n`` proposed next tokens (int64)."""
        ctx = np.asarray(context, np.int64).reshape(-1)
        out = np.full(n, ctx[-1] if ctx.size else 0, np.int64)
        if n < 1 or ctx.size < self.min_ngram + 1:
            return out
        for g in range(min(self.max_ngram, ctx.size - 1), self.min_ngram - 1,
                       -1):
            suffix = ctx[-g:]
            # windows of width g ending strictly before the suffix itself
            hay = np.lib.stride_tricks.sliding_window_view(ctx[:-1], g)
            matches = np.nonzero((hay == suffix).all(axis=1))[0]
            if matches.size == 0:
                continue
            start = int(matches[-1]) + g   # continuation of the LAST match
            cont = ctx[start:start + n]
            out[:cont.size] = cont
            if cont.size < n and cont.size:
                out[cont.size:] = cont[-1]
            return out
        return out

    def __repr__(self):
        return (f"NgramDrafter(max_ngram={self.max_ngram}, "
                f"min_ngram={self.min_ngram})")
