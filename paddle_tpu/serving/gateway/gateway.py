"""Gateway core — admission, shedding, dispatch over engine replicas.

The traffic path, socket to slot pool::

    HTTP handler threads (http.py)
        parse -> tenant -> Gateway.admit()
                     |         |-- shed check (LoadShedder: est TTFT vs
                     |         |   deadline) -> 429 + Retry-After
                     |         `-- FairShareScheduler.enqueue (per-tenant
                     |             caps -> structured 429)
                     |  wait/stream on the GatewayRequest
        dispatcher thread (one per gateway)
            pop fair-share winner -> EngineRouter.pick (least loaded,
            skips DEAD replicas) -> Engine.submit(stream=token queue)
            reap finished handles -> release tenant slot, feed the
            shedder's EWMAs, per-tenant TTFT histograms

Thread-shape invariants (the tpu-lint concurrency checker runs over this
package): every handler<->dispatcher handoff crosses on a
``queue.Queue``/``threading.Event`` or inside the scheduler's lock; the
dispatcher's outstanding-request list is a local variable of its loop,
shared with nobody.
"""
from __future__ import annotations

import itertools
import queue
import threading
import time

import numpy as np

from ...observability import capture as capture_mod
from ...observability import flight, registry
from ...observability.journey import TelemetryWindow
from ...testing import faults
from ..engine import (SERVING_REDISPATCHED, EngineDeadError, QueueFullError,
                      RequestInterruptedError)
from .admission import AdmissionError, FairShareScheduler, TenantConfig
from .protocol import PRIORITIES, CompletionRequest, ProtocolError
from .router import EngineRouter, NoEngineAvailableError
from .shed import LoadShedder

__all__ = ["Gateway", "GatewayClosedError", "GatewayRequest"]

# -- metric names (paddle_tpu.observability registry) -------------------------
GATEWAY_REQUESTS = "paddle_tpu_gateway_requests_total"
GATEWAY_QUEUE_DEPTH = "paddle_tpu_gateway_queue_depth"
GATEWAY_INFLIGHT = "paddle_tpu_gateway_inflight"
GATEWAY_TTFT = "paddle_tpu_gateway_ttft_seconds"
GATEWAY_TTFT_EST = "paddle_tpu_gateway_ttft_estimate_seconds"
GATEWAY_SHED = "paddle_tpu_gateway_shed_total"

_ids = itertools.count(1)


class GatewayClosedError(RuntimeError):
    """The gateway shut down with this request still queued (503)."""


class GatewayRequest:
    """One admitted request crossing the handler/dispatcher boundary.

    The handler thread blocks on :attr:`ready` (first dispatch or early
    failure — ``handle``/``error`` are written before the event is set,
    which publishes them) and then on :attr:`done_ev` for the FINAL
    outcome; streamed tokens arrive on :attr:`token_q` from the engine's
    scheduler thread.  The dispatcher's reaper is the single authority
    on the final outcome: an engine death may replace :attr:`handle`
    with a re-dispatched one (safe only while no token has reached the
    client), so handlers never treat a handle failure as final — they
    wait for :meth:`finish`.
    """

    __slots__ = ("id", "creq", "tenant", "priority", "cost", "prompt",
                 "t_enqueue", "t_dispatch", "token_q", "ready", "handle",
                 "error", "engine_name", "deadline", "done_ev",
                 "final_error", "redispatches", "adapter", "journey",
                 "t_queue0", "t_first_token")

    def __init__(self, creq: CompletionRequest, tenant: str, priority: str,
                 prompt: np.ndarray, adapter: str | None = None,
                 journey=None):
        self.id = f"cmpl-{next(_ids)}"
        self.creq = creq
        self.tenant = tenant
        self.priority = priority
        self.prompt = prompt
        self.adapter = adapter       # LoRA adapter name (model= resolved)
        self.journey = journey       # observability Journey (or None)
        self.cost = float(prompt.size + creq.max_tokens)
        now = time.perf_counter()
        self.t_enqueue = now
        self.t_queue0 = now          # current queue-wait window start
        self.t_first_token: float | None = None
        self.t_dispatch: float | None = None
        self.deadline = (None if creq.deadline_s is None
                         else now + creq.deadline_s)
        self.token_q: queue.Queue = queue.Queue()
        self.ready = threading.Event()
        self.done_ev = threading.Event()
        self.handle = None
        self.error: BaseException | None = None
        self.final_error: BaseException | None = None
        self.engine_name: str | None = None
        self.redispatches = 0

    def fail(self, error: BaseException):
        """Final failure before (or instead of) a dispatch."""
        self.error = error
        self.final_error = error
        self.ready.set()
        self.done_ev.set()

    def finish(self, error: BaseException | None = None):
        """Final outcome after a dispatch (reaper only)."""
        self.final_error = error
        self.done_ev.set()

    def dispatched(self, handle, engine_name: str):
        self.handle = handle
        self.engine_name = engine_name
        self.t_dispatch = time.perf_counter()
        self.ready.set()


class Gateway:
    """Multi-tenant front door over one or more serving engines.

    Args:
        engines: Engine replica(s) — the gateway does NOT own them; shut
            them down separately (or use ``start_gateway`` from http.py,
            whose ``close()`` tears the whole stack down).
        tenants: iterable of :class:`TenantConfig` (unknown tenants get
            ``default_tenant``'s policy).
        default_tenant: policy template for unconfigured tenants.
        api_keys: optional {key: tenant} map; when set, requests without a
            known key are 401 (strict mode).
        names: router replica names (default engine0..N-1).
        shedder: optionally pre-seeded :class:`LoadShedder`.
        max_queue_total: global queued-request bound across tenants.
        dispatch_slack: how deep past the slot pool the dispatcher lets an
            engine's own queue grow (small = ordering stays fair-share).
        max_redispatch: gateway-side retry budget for requests whose
            engine died before any token reached the client (engine
            replacements on ANOTHER replica; a supervisor's same-handle
            re-dispatches have their own budget).
        window_s: trailing window of the :class:`TelemetryWindow` feed
            behind :meth:`window_stats` (queue-wait/TTFT/per-token
            percentiles, shed rate, per-phase time shares — the
            closed-loop autoscaler input, ROADMAP item 5).
        model_name: echoed in completion responses.
        start: start the dispatcher thread immediately (tests stage
            queues deterministically with False, then call start()).
    """

    def __init__(self, engines, tenants=None, *,
                 default_tenant: TenantConfig | None = None,
                 api_keys: dict | None = None, names=None,
                 shedder: LoadShedder | None = None,
                 max_queue_total: int | None = None, dispatch_slack: int = 1,
                 max_redispatch: int = 2, window_s: float = 60.0,
                 model_name: str = "paddle-tpu", start: bool = True,
                 capture=None, capture_mode: str | None = None,
                 capture_entries: int | None = None,
                 capture_spill_dir: str | None = None):
        if hasattr(engines, "submit"):
            engines = [engines]
        self.router = EngineRouter(engines, names=names)
        self.scheduler = FairShareScheduler(
            tenants, default=default_tenant, max_queue_total=max_queue_total)
        self.shedder = shedder or LoadShedder()
        self.window = TelemetryWindow(window_s=window_s)
        self.api_keys = dict(api_keys) if api_keys else None
        self.model_name = model_name
        self.dispatch_slack = int(dispatch_slack)
        self.max_redispatch = int(max_redispatch)
        self.tokenizer = next(
            (e.tokenizer for e in self.router.engines
             if e.tokenizer is not None), None)
        # multi-LoRA: `model=` names resolve through the replicas' shared
        # adapter registry (tenant -> adapter is the natural mapping; a
        # request without model=, or naming the base model, runs id 0)
        self.adapter_registry = next(
            (getattr(e, "adapter_registry", None)
             for e in self.router.engines
             if getattr(e, "adapter_registry", None) is not None), None)
        # traffic capture: an explicit instance or any knob builds a
        # gateway-local recorder (tests, spill-to-dir deployments);
        # otherwise every gateway records into the process default.
        # Either way the recorder feeds the capture_tail bundle section.
        if capture is not None:
            self.capture = capture
            capture_mod.install_incident_section(capture)
        elif (capture_mode is not None or capture_entries is not None
              or capture_spill_dir is not None):
            self.capture = capture_mod.TrafficCapture(
                max_entries=capture_entries, mode=capture_mode,
                spill_dir=capture_spill_dir)
            capture_mod.install_incident_section(self.capture)
        else:
            self.capture = capture_mod.get_capture()
        self._stop_ev = threading.Event()
        self._drain_ev = threading.Event()
        self._drain_retry_after_s = 5.0
        self._dispatcher_error: BaseException | None = None
        self._thread: threading.Thread | None = None
        self._fleet_lock = threading.Lock()
        self._autoscaler = None
        self._slo_engine = None
        self._rollout = None
        if start:
            self.start()

    # -- fleet elasticity hooks ----------------------------------------------
    def attach_autoscaler(self, autoscaler):
        """Register the fleet autoscaler (one per gateway): admission
        treats a scale-up in flight as capacity-on-the-way (no all-dead
        503 while the only other replica drains), shed Retry-After is
        capped at the expected warm-up completion, and ``/debug/fleet``
        serves its state."""
        with self._fleet_lock:
            self._autoscaler = autoscaler

    @property
    def autoscaler(self):
        with self._fleet_lock:
            return self._autoscaler

    def attach_slo_engine(self, engine):
        """Register the SLO evaluator (one per gateway): its firing set
        feeds the autoscaler policy input (``firing_alerts``) and
        ``/debug/slo`` serves its state."""
        with self._fleet_lock:
            self._slo_engine = engine

    @property
    def slo_engine(self):
        with self._fleet_lock:
            return self._slo_engine

    def attach_rollout(self, controller):
        """Register the rolling-upgrade controller (ISSUE 20, one per
        gateway): a rollout build in flight counts as
        capacity-on-the-way (no all-dead 503 mid-upgrade), shed
        Retry-After is capped at its expected warm-up completion, the
        reaper feeds it per-engine canary outcomes, and
        ``/debug/fleet`` serves its state."""
        with self._fleet_lock:
            self._rollout = controller

    @property
    def rollout(self):
        with self._fleet_lock:
            return self._rollout

    def _fleet_pending(self) -> bool:
        """Capacity is leaving-but-finishing or on the way: some replica
        is DRAINING (its in-flight work completes; new work must wait,
        not 503), the autoscaler has a scale-up building, or the rollout
        controller is mid-build of a replacement replica."""
        a = self.autoscaler
        if a is not None and a.scale_pending():
            return True
        r = self.rollout
        if r is not None and r.build_pending():
            return True
        return self.router.any_draining()

    def _scale_eta_s(self) -> float | None:
        etas = []
        a = self.autoscaler
        if a is not None:
            eta = a.expected_ready_s()
            if eta is not None:
                etas.append(eta)
        r = self.rollout
        if r is not None:
            eta = r.expected_ready_s()
            if eta is not None:
                etas.append(eta)
        return min(etas) if etas else None

    def fleet_stats(self) -> dict:
        """The ``/debug/fleet`` payload: per-replica state from the
        router plus the autoscaler's view (bounds, desired count,
        in-flight op, recent scale events) when one is attached."""
        loads = self.router.loads()
        revs = self.router.revisions()
        out = {
            "replicas": {
                name: {"alive": ld["alive"],
                       "draining": bool(ld.get("draining")),
                       "restarting": bool(ld.get("restarting")),
                       "slots_in_use": ld["slots_in_use"],
                       "queue_depth": ld["queue_depth"],
                       "max_slots": ld["max_slots"],
                       "revision": revs.get(name, "r0")}
                for name, ld in loads.items()},
            "alive": sum(1 for ld in loads.values()
                         if ld["alive"] and not ld.get("draining")),
            "draining": sum(1 for ld in loads.values()
                            if ld.get("draining")),
            "total_slots": self.router.total_slots(),
        }
        a = self.autoscaler
        out["autoscaler"] = a.fleet_stats() if a is not None else None
        r = self.rollout
        out["rollout"] = r.stats() if r is not None else None
        return out

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        if self._stop_ev.is_set():
            raise GatewayClosedError("gateway is shut down")
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._dispatch_loop, name="paddle-tpu-gateway",
                daemon=True)
            self._thread.start()

    def shutdown(self):
        """Stop dispatching; queued requests fail with
        :class:`GatewayClosedError` (503 at the wire).  Idempotent; does
        not shut the engines down."""
        if self._stop_ev.is_set():
            return
        self._stop_ev.set()
        self.scheduler.close()
        if self._thread is not None:
            self._thread.join(timeout=10)
        err = GatewayClosedError("gateway shut down")
        for item in self.scheduler.drain():
            item.fail(err)
            self._count(item.tenant, "failed")
        flight.record("gateway", "shutdown")

    close = shutdown

    def drain(self, deadline_s: float = 30.0) -> bool:
        """Graceful shutdown, phase one: new admissions are shed with a
        structured 429 + ``Retry-After`` while queued and in-flight work
        runs to completion (the dispatcher keeps feeding the engines).
        Returns True when the gateway went idle before the deadline —
        a ``shutdown()`` then drops nothing."""
        self._drain_retry_after_s = max(1.0, float(deadline_s))
        self._drain_ev.set()
        flight.record("gateway", "drain_begin",
                      deadline_s=float(deadline_s),
                      queued=self.scheduler.depth())
        deadline = time.perf_counter() + float(deadline_s)
        ok = False
        while time.perf_counter() < deadline:
            d = self.scheduler.depths()
            if all(v["queued"] == 0 and v["in_flight"] == 0
                   for v in d.values()):
                ok = True
                break
            time.sleep(0.01)
        flight.record("gateway", "drain_done", drained=ok)
        return ok

    @property
    def draining(self) -> bool:
        return self._drain_ev.is_set()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False

    def _record_capture(self, creq: CompletionRequest, tenant: str,
                        priority: str, outcome: str, journey,
                        prompt=None):
        """One traffic-capture entry per admission outcome (admitted OR
        shed) — diagnostics, never control flow, so it must not raise
        into the handler.  ``prompt`` is the resolved token-id array
        when admission got that far; earlier exits hash the wire form."""
        ids = None
        text = None
        if prompt is not None:
            ids = prompt
        elif isinstance(creq.prompt, (list, tuple)):
            ids = creq.prompt
        else:
            text = creq.prompt
        try:
            self.capture.record(
                tenant=tenant, priority=priority, outcome=outcome,
                prompt=ids, text=text,
                prompt_len=len(text) if ids is None and text else None,
                max_tokens=creq.max_tokens, deadline_s=creq.deadline_s,
                temperature=creq.temperature, top_k=creq.top_k,
                seed=creq.seed, model=creq.model,
                conversation=getattr(creq, "conversation", None),
                journey_id=journey.id if journey is not None else "")
        except Exception:
            pass

    # -- admission (handler threads) -----------------------------------------
    def admit(self, creq: CompletionRequest, tenant: str,
              journey=None) -> GatewayRequest:
        """Validate fit, run the shed check, enqueue under the tenant's
        fair-share caps.  Raises ProtocolError (4xx), AdmissionError
        (429, incl. SLO shed) or GatewayClosedError (503).  ``journey``
        (a :mod:`~paddle_tpu.observability.journey` Journey, usually
        minted by the HTTP handler from ``X-Request-Id``) rides the
        returned item through dispatch into the engine — every layer
        appends its phase records to it."""
        t_admit0 = time.perf_counter()
        if self._stop_ev.is_set():
            raise GatewayClosedError("gateway is shut down")
        if self._dispatcher_error is not None:
            raise GatewayClosedError(
                f"gateway dispatcher died: "
                f"{type(self._dispatcher_error).__name__}: "
                f"{self._dispatcher_error}")
        # tenant + priority class resolve BEFORE any shed exit so every
        # shed is attributed to its key in the telemetry window (per-
        # class SLO attainment is uncomputable otherwise) and the
        # journey carries both even when the request never enqueues
        cfg = self.scheduler.tenant_config(tenant)
        priority = creq.priority or cfg.priority
        if journey is not None:
            journey.annotate(tenant=tenant, priority=priority)
        if self._drain_ev.is_set():
            self._count(tenant, "shed")
            self.window.observe_shed("draining", tenant=tenant,
                                     priority=priority)
            registry().counter(GATEWAY_SHED, "requests shed by reason").inc(
                1.0, labels={"tenant": tenant, "reason": "draining"})
            self._record_capture(creq, tenant, priority, "draining", journey)
            raise AdmissionError(
                "draining", "gateway is draining for shutdown; retry "
                "against another replica",
                retry_after_s=self._drain_retry_after_s, tenant=tenant)
        if not self.router.any_alive() and not self._fleet_pending():
            self._record_capture(creq, tenant, priority, "no_engine", journey)
            raise NoEngineAvailableError(
                "no alive engine replica to serve this request")
        prompt = self._prompt_ids(creq)
        self.eos_for(creq)               # reject a bad stop field up front
        max_len = self.router.min_max_len()
        if prompt.size + creq.max_tokens > max_len:
            raise ProtocolError(
                400, f"prompt ({prompt.size}) + max_tokens "
                f"({creq.max_tokens}) exceeds the serving window "
                f"({max_len})", param="max_tokens", code="context_window")
        item = GatewayRequest(creq, tenant, priority, prompt,
                              adapter=self._resolve_adapter(creq),
                              journey=journey)
        if journey is not None:
            journey.annotate(completion_id=item.id,
                             prompt_tokens=int(prompt.size),
                             max_tokens=creq.max_tokens)
            if getattr(creq, "conversation", None):
                journey.annotate(conversation=creq.conversation)

        backlog = self.scheduler.backlog_cost(priority) + item.cost
        slots = self.router.total_slots()
        decision = self.shedder.decide(creq.deadline_s, backlog, slots)
        reg = registry()
        if decision.est_ttft_s is not None:
            reg.gauge(GATEWAY_TTFT_EST,
                      "estimated TTFT for a request joining now").set(
                decision.est_ttft_s)
        if not decision.admit:
            # scale-aware Retry-After: while a scale-up is building, the
            # static `est - deadline` horizon is wrong — capacity arrives
            # at warm-up completion (cold-build EWMA), so shed clients
            # should return exactly then, not later
            eta = self._scale_eta_s()
            if eta is not None and eta < decision.retry_after_s:
                decision.retry_after_s = max(0.1, round(eta, 2))
            self._count(tenant, "shed")
            self.window.observe_shed("slo_shed", tenant=tenant,
                                     priority=priority)
            reg.counter(GATEWAY_SHED, "requests shed by reason").inc(
                1.0, labels={"tenant": tenant, "reason": "slo_shed"})
            flight.record("gateway", "shed", request=item.id, tenant=tenant,
                          journey=journey.id if journey is not None else "",
                          est_ttft_ms=round(decision.est_ttft_s * 1e3, 1),
                          deadline_ms=round(creq.deadline_s * 1e3, 1),
                          backlog_tokens=round(backlog, 1))
            self._record_capture(creq, tenant, priority, "slo_shed",
                                 journey, prompt=prompt)
            raise AdmissionError(
                "slo_shed", decision.reason,
                retry_after_s=decision.retry_after_s, tenant=tenant,
                est_ttft_s=decision.est_ttft_s)
        try:
            self.scheduler.enqueue(item)
        except AdmissionError as e:
            self._count(tenant, "rejected")
            self.window.observe_shed(e.reason, tenant=tenant,
                                     priority=priority)
            reg.counter(GATEWAY_SHED, "requests shed by reason").inc(
                1.0, labels={"tenant": tenant, "reason": e.reason})
            flight.record("gateway", "shed", request=item.id, tenant=tenant,
                          reason=e.reason)
            self._record_capture(creq, tenant, priority, e.reason,
                                 journey, prompt=prompt)
            raise
        now = time.perf_counter()
        item.t_queue0 = now             # fair-share queue wait starts here
        if journey is not None:
            journey.phase("admit", t_admit0, now - t_admit0,
                          backlog_tokens=round(backlog, 1))
        self._count(tenant, "accepted")
        self._record_capture(creq, tenant, priority, "admitted",
                             journey, prompt=prompt)
        self._depth_gauges()
        flight.record("gateway", "admit", request=item.id, tenant=tenant,
                      priority=priority, prompt_len=int(prompt.size),
                      max_tokens=creq.max_tokens)
        return item

    def _resolve_adapter(self, creq: CompletionRequest) -> str | None:
        """``model=`` → LoRA adapter name through the registry.  Absent
        or the base model's name → None (adapter id 0); unknown names
        are a structured 404, a rank the bank can never hold is a 400 —
        both BEFORE the request queues."""
        name = creq.model
        if not name or name == self.model_name:
            return None
        reg = self.adapter_registry
        if reg is None or name not in reg:
            raise ProtocolError(
                404, f"model {name!r} is not served here (base model "
                f"{self.model_name!r}"
                + (f", adapters: {reg.names()}" if reg is not None else "")
                + ")", param="model", code="model_not_found")
        if reg.get(name).rank > reg.max_rank:
            raise ProtocolError(
                400, f"adapter {name!r} rank {reg.get(name).rank} exceeds "
                f"the serving bank width ({reg.max_rank})", param="model",
                code="adapter_rank")
        return name

    def _prompt_ids(self, creq: CompletionRequest) -> np.ndarray:
        prompt = creq.prompt
        if isinstance(prompt, str):
            if self.tokenizer is None:
                raise ProtocolError(400, "string prompt needs a tokenizer",
                                    param="prompt", code="no_tokenizer")
            prompt = self.tokenizer.encode(prompt)
        ids = np.asarray(prompt, np.int64).reshape(-1)
        if ids.size < 1:
            raise ProtocolError(400, "'prompt' is empty", param="prompt",
                                code="empty_prompt")
        return ids

    def eos_for(self, creq: CompletionRequest):
        """Resolve the request's stop field to an eos token id."""
        stop = creq.stop
        if stop is None:
            return ...                   # engine default
        if isinstance(stop, str):
            if self.tokenizer is None:
                raise ProtocolError(400, "string 'stop' needs a tokenizer",
                                    param="stop", code="no_tokenizer")
            ids = self.tokenizer.encode(stop)
            ids = np.asarray(ids, np.int64).reshape(-1)
            if ids.size != 1:
                raise ProtocolError(
                    400, "'stop' must encode to a single token",
                    param="stop", code="invalid_stop")
            return int(ids[0])
        return int(stop)

    # -- result wait (handler threads) ---------------------------------------
    def result(self, item: GatewayRequest, timeout: float | None = None):
        """Block for the FINAL outcome (the reaper may transparently
        re-dispatch an engine death first); returns (token_ids, finish
        reason).  Failures re-raise for http.py to map."""
        if not item.done_ev.wait(timeout):
            raise TimeoutError(f"request {item.id} did not finish "
                               f"within {timeout}s")
        if item.final_error is not None:
            raise item.final_error
        tokens = item.handle.result(timeout=0)
        eos = item.handle.eos_token_id
        finish = ("stop" if eos is not None and tokens.size and
                  int(tokens[-1]) == eos else "length")
        return tokens, finish

    # -- dispatcher thread ---------------------------------------------------
    def _dispatch_loop(self):
        try:
            self._dispatch_impl()
        except Exception as e:  # noqa: BLE001 — die LOUDLY, not silently
            # dispatcher death must degrade /healthz and fail queued work
            # instead of hanging every admitted handler to its timeout.
            # single None->exc transition; admit()/healthz() read it
            # lock-free like the engine's _dead monitor flag
            self._dispatcher_error = e  # tpu-lint: ok(concurrency)
            flight.record("gateway", "dispatcher_died",
                          error=f"{type(e).__name__}: {e}")
            err = GatewayClosedError(
                f"gateway dispatcher died: {type(e).__name__}: {e}")
            for item in self.scheduler.drain():
                item.fail(err)
                self._count(item.tenant, "failed")
            raise

    def _dispatch_impl(self):
        outstanding: list = []       # local to this thread — never shared
        while True:
            faults.fault_point("gateway.dispatch")
            self._reap(outstanding)
            if self._stop_ev.is_set():
                break
            if not self.router.has_headroom(self.dispatch_slack):
                if not self.router.any_alive() and \
                        not self._fleet_pending():
                    # every replica died with work queued: fail it loudly
                    # instead of letting handlers hang to their timeout
                    # (a DRAINING replica or an in-flight scale-up means
                    # capacity is coming — queued work waits instead)
                    item = self.scheduler.pop(timeout=0.02)
                    if item is not None:
                        self.scheduler.release(item.tenant, item.cost)
                        self._count(item.tenant, "failed")
                        item.fail(NoEngineAvailableError(
                            "every engine replica is dead"))
                    continue
                time.sleep(0.002)
                continue
            item = self.scheduler.pop(timeout=0.02)
            if item is None:
                continue
            if item.deadline is not None and \
                    time.perf_counter() > item.deadline:
                # expired while queued (shed model was cold or wrong):
                # fail it NOW, before it burns a slot
                self.scheduler.release(item.tenant, item.cost)
                self._count(item.tenant, "expired_queued")
                item.fail(AdmissionError(
                    "deadline_queued",
                    f"request {item.id} deadline passed while queued",
                    retry_after_s=0.5, tenant=item.tenant))
                self._depth_gauges()
                continue
            if not self._submit(item):
                continue
            outstanding.append(item)
            self._depth_gauges()
        # drain the reap list so tenants aren't left owing slots
        deadline = time.perf_counter() + 5.0
        while outstanding and time.perf_counter() < deadline:
            self._reap(outstanding)
            if outstanding:
                time.sleep(0.01)
        err = GatewayClosedError("gateway shut down mid-request")
        for item in outstanding:     # still running past the grace window
            self.scheduler.release(item.tenant, item.cost)
            self._count(item.tenant, "failed")
            item.finish(err)

    def _submit(self, item: GatewayRequest) -> bool:
        """Route one popped item to a replica.  True when submitted;
        False when it was requeued or failed (accounting settled)."""
        creq = item.creq
        t_pick0 = time.perf_counter()
        remaining = (None if item.deadline is None
                     else max(0.05, item.deadline - time.perf_counter()))
        tried: list = []
        while True:
            try:
                name, engine = self.router.pick(exclude=tried,
                                                adapter=item.adapter)
            except NoEngineAvailableError as e:
                if not tried and self._fleet_pending():
                    # nothing pickable RIGHT NOW but a replica is
                    # draining out or a scale-up is building: park the
                    # item at the head of its queue — never redispatch
                    # onto a replica that is leaving, never 503 work
                    # that arriving capacity will absorb
                    self.scheduler.requeue(item)
                    time.sleep(0.002)
                    return False
                self.scheduler.release(item.tenant, item.cost)
                self._count(item.tenant, "failed")
                item.fail(e)
                return False
            try:
                handle = engine.submit(
                    item.prompt, max_new_tokens=creq.max_tokens,
                    eos_token_id=self.eos_for(creq),
                    temperature=creq.temperature, top_k=creq.top_k,
                    seed=creq.seed, deadline_s=remaining,
                    stream=self._stream_for(item), adapter=item.adapter,
                    journey=item.journey,
                    conversation=getattr(creq, "conversation", None))
            except QueueFullError:
                tried.append(name)
                if len(tried) >= len(self.router.names):
                    # every replica is briefly full: put the item back at
                    # the head of its tenant queue and let headroom gating
                    # retry — fair-share order is preserved
                    self.scheduler.requeue(item)
                    time.sleep(0.002)
                    return False
                continue
            except EngineDeadError:
                tried.append(name)
                flight.record("gateway", "failover", request=item.id,
                              engine=name)
                if len(tried) >= len(self.router.names):
                    if self.router.any_alive():
                        # a replica is mid-restart (supervised) or the
                        # death raced the pick: park the item back at the
                        # head of its queue and let the headroom gate
                        # retry once the fleet settles
                        self.scheduler.requeue(item)
                        time.sleep(0.002)
                        return False
                    self.scheduler.release(item.tenant, item.cost)
                    self._count(item.tenant, "failed")
                    item.fail(NoEngineAvailableError(
                        "every engine replica is dead"))
                    return False
                continue
            except Exception as e:  # noqa: BLE001 — surface to the caller
                self.scheduler.release(item.tenant, item.cost)
                self._count(item.tenant, "failed")
                item.fail(e)
                return False
            item.dispatched(handle, name)
            j = item.journey
            if j is not None:
                # queue = fair-share wait (enqueue/requeue -> this pop);
                # route = router pick + engine handoff.  t_queue0 resets
                # after each dispatch so a redispatch attributes only its
                # own wait.
                j.phase("queue", item.t_queue0, t_pick0 - item.t_queue0,
                        tenant=item.tenant)
                j.phase("route", t_pick0, item.t_dispatch - t_pick0,
                        engine=name)
                j.annotate(engine=name)
            item.t_queue0 = item.t_dispatch
            flight.record("gateway", "dispatch", request=item.id,
                          tenant=item.tenant, engine=name,
                          queue_wait_ms=round(
                              1e3 * (item.t_dispatch - item.t_enqueue), 2))
            return True

    def _stream_for(self, item: GatewayRequest):
        """The engine-side token callback: forwards into the item's
        token queue, and on the FIRST token feeds the shedder's prefill
        EWMA — at the prefill-completion journey boundary, not at handle
        reap.  (Reap-time feeding left ``est_ttft`` stale for the whole
        lifetime of long-running requests: a burst of them could blow
        every deadline before the model learned a thing.)"""
        t_sub = time.perf_counter()     # races dispatched(): close enough

        def _stream(tok, _item=item, _t_sub=t_sub):
            if _item.t_first_token is None:
                _item.t_first_token = time.perf_counter()
                self.shedder.observe_prefill(
                    _item.t_first_token - (_item.t_dispatch or _t_sub))
            _item.token_q.put(tok)
        return _stream

    def _reap(self, outstanding: list):
        """Retire finished engine handles: release the tenant's
        concurrency unit, feed the shedder, record per-tenant TTFT —
        and re-dispatch handles whose engine died before any token
        reached the client (bounded by ``max_redispatch``)."""
        done = [it for it in outstanding if it.handle.done()]
        if not done:
            return
        reg = registry()
        for item in done:
            outstanding.remove(item)
            err = item.handle.exception(timeout=0)
            if err is not None and self._redispatchable(item, err):
                item.redispatches += 1
                self._flush_tokens(item)
                item.t_first_token = None   # zero tokens reached the client
                from_engine = item.engine_name or ""
                self._note_outcome(from_engine, ok=False)
                t_r0 = time.perf_counter()
                reg.counter(
                    SERVING_REDISPATCHED,
                    "requests re-dispatched after an engine death").inc(
                    1.0, labels={"layer": "gateway"})
                flight.record("gateway", "redispatch", request=item.id,
                              attempt=item.redispatches,
                              from_engine=from_engine,
                              error=type(err).__name__)
                if item.journey is not None:
                    # the cross-replica hop, on the SAME journey id: the
                    # phases before it came from from_engine, the ones
                    # after from the survivor replica
                    item.journey.phase(
                        "redispatch", t_r0, time.perf_counter() - t_r0,
                        attempt=item.redispatches, from_engine=from_engine,
                        error=type(err).__name__)
                item.t_queue0 = time.perf_counter()
                if self._submit(item):
                    # new handle on another replica; tenant accounting is
                    # still owed — the item stays in flight
                    outstanding.append(item)
                # on False the item was either requeued (the main loop
                # pops and re-submits it) or permanently failed — both
                # settle the accounting inside _submit
                continue
            self.scheduler.release(item.tenant, item.cost)
            if err is None:
                self._count(item.tenant, "completed")
                # token latencies only: the prefill EWMA was already fed
                # at prefill completion (first streamed token), so a
                # burst of long decodes can no longer starve est_ttft
                self.shedder.observe_tokens(
                    item.handle.token_latencies_s)
                gw_ttft = None
                if item.handle.ttft_s is not None:
                    gw_ttft = (item.t_dispatch - item.t_enqueue) + \
                        item.handle.ttft_s
                    reg.histogram(
                        GATEWAY_TTFT,
                        "enqueue -> first token, per tenant").observe(
                        gw_ttft, labels={"tenant": item.tenant})
                self._note_outcome(item.engine_name, ok=True,
                                   ttft_s=gw_ttft)
                item.finish(None)
            else:
                # engine-side failure after dispatch (deadline inside the
                # engine, cancellation, unrecoverable engine death): the
                # reaper makes it final; handlers see it via result()
                outcome = type(err).__name__
                self._count(item.tenant, "expired_engine"
                            if "Deadline" in outcome else "failed")
                self._note_outcome(item.engine_name, ok=False)
                item.finish(err)
        self._depth_gauges()

    def _note_outcome(self, engine, ok: bool, ttft_s=None):
        """Feed the rollout controller's per-engine canary window (the
        reaper is the only place outcomes carry an engine name) —
        diagnostics, never control flow, so it must not raise into the
        dispatcher."""
        ctl = self.rollout
        if ctl is not None and engine:
            try:
                ctl.note_outcome(engine, ok, ttft_s)
            except Exception:  # noqa: BLE001 — a hook, not the data path
                pass

    def _redispatchable(self, item: GatewayRequest,
                        err: BaseException) -> bool:
        """The retry-safety rule: re-dispatch iff no token can have
        reached the client.  ``EngineDeadError`` means zero tokens were
        emitted at all; ``RequestInterruptedError`` means tokens were
        emitted but — for a NON-streaming request — they only ever
        reached the gateway's internal queue, which is flushed before
        the retry."""
        if item.redispatches >= self.max_redispatch:
            return False
        if self._stop_ev.is_set():
            return False
        if isinstance(err, EngineDeadError):
            return not item.handle.tokens    # engine guarantees zero
        if isinstance(err, RequestInterruptedError):
            return not item.creq.stream
        return False

    @staticmethod
    def _flush_tokens(item: GatewayRequest):
        while not item.token_q.empty():
            try:
                item.token_q.get_nowait()
            except queue.Empty:              # pragma: no cover - racing reap
                break

    # -- metrics helpers -----------------------------------------------------
    def _count(self, tenant: str, outcome: str):
        registry().counter(GATEWAY_REQUESTS,
                           "gateway requests by tenant and outcome").inc(
            1.0, labels={"tenant": tenant, "outcome": outcome})

    def _depth_gauges(self):
        reg = registry()
        for tenant, d in self.scheduler.depths().items():
            reg.gauge(GATEWAY_QUEUE_DEPTH,
                      "queued requests per tenant").set(
                float(d["queued"]), labels={"tenant": tenant})
            reg.gauge(GATEWAY_INFLIGHT,
                      "dispatched, unfinished requests per tenant").set(
                float(d["in_flight"]), labels={"tenant": tenant})

    # -- journeys / windowed feed --------------------------------------------
    def finish_journey(self, item: GatewayRequest, outcome: str = "ok"):
        """Close the item's journey (the HTTP handler calls this once
        the response — including the streamed tail — is on the wire, so
        the timeline covers the full client-observed window) and fold it
        into the rolling :class:`TelemetryWindow`."""
        j = item.journey
        if j is None:
            return
        handle = item.handle
        if handle is not None:
            j.annotate(tokens=len(handle.tokens),
                       redispatches=item.redispatches)
        j.finish(outcome)
        self.window.observe_journey(j)

    def window_stats(self) -> dict:
        """The trailing-window telemetry feed (queue-wait/TTFT/per-token
        p50+p99, shed rate, per-phase time shares, redispatch + rebuild
        counts) plus instantaneous load (queue depth, TTFT estimate) —
        the exact closed-loop input a trace-driven autoscaler consumes.
        Also refreshes the ``paddle_tpu_gateway_window_*`` gauges, so a
        ``/metrics`` scrape exports what this returns."""
        snap = self.window.snapshot()
        snap["queue_depth"] = self.scheduler.depth()
        shed_snap = self.shedder.snapshot()
        snap["est_ttft_s"] = self.shedder.estimate_ttft(
            self.scheduler.backlog_cost("batch"),
            self.router.total_slots())
        snap["shedder_observations"] = shed_snap["observations"]
        reg = registry()
        for key in ("ttft_s", "queue_wait_s", "token_s"):
            for q in ("p50", "p99"):
                reg.gauge(f"paddle_tpu_gateway_window_{key[:-2]}_seconds",
                          f"windowed {key[:-2]} percentiles").set(
                    snap[key][q], labels={"q": q})
        reg.gauge("paddle_tpu_gateway_window_shed_rate",
                  "shed fraction over the trailing window").set(
            snap["shed_rate"])
        reg.gauge("paddle_tpu_gateway_window_requests",
                  "journeys finished in the trailing window").set(
            float(snap["requests"]))
        reg.gauge("paddle_tpu_gateway_window_redispatches",
                  "redispatch phases in the trailing window").set(
            float(snap["redispatches"]))
        reg.gauge("paddle_tpu_gateway_window_rebuilds",
                  "supervisor rebuild phases in the trailing window").set(
            float(snap["rebuilds"]))
        for phase, share in snap["phase_share"].items():
            reg.gauge("paddle_tpu_gateway_window_phase_share",
                      "per-phase share of attributed request time").set(
                share, labels={"phase": phase})
        return snap

    # -- introspection -------------------------------------------------------
    def stats(self) -> dict:
        return {
            "tenants": self.scheduler.depths(),
            "engines": self.router.loads(),
            "shedder": self.shedder.snapshot(),
            "window": self.window.snapshot(),
            "closed": self._stop_ev.is_set(),
            "draining": self._drain_ev.is_set(),
            "dispatcher_alive": self.dispatcher_alive(),
        }

    def dispatcher_alive(self) -> bool:
        """False once the dispatcher thread died (or was never started):
        admitted work would hang, so /healthz degrades instead."""
        return (self._dispatcher_error is None and
                self._thread is not None and self._thread.is_alive())

    def healthz(self) -> dict:
        loads = self.router.loads()
        alive = [n for n, ld in loads.items() if ld["alive"]]
        dispatcher_ok = (self.dispatcher_alive() or
                         # not started yet (start=False tests): not dead
                         (self._thread is None and
                          self._dispatcher_error is None and
                          not self._stop_ev.is_set()))
        out = {
            "alive": (bool(alive) and not self._stop_ev.is_set() and
                      not self._drain_ev.is_set() and dispatcher_ok),
            "draining": self._drain_ev.is_set(),
            "dispatcher_alive": dispatcher_ok,
            "engines": {n: {"alive": ld["alive"],
                            "slots_in_use": ld["slots_in_use"],
                            "queue_depth": ld["queue_depth"],
                            "restarting": bool(ld.get("restarting"))}
                        for n, ld in loads.items()},
            "queued": self.scheduler.depth(),
            "priorities": sorted(PRIORITIES),
        }
        if self._dispatcher_error is not None:
            out["dispatcher_error"] = (
                f"{type(self._dispatcher_error).__name__}: "
                f"{self._dispatcher_error}")
        return out
