"""paddle_tpu.serving.gateway — the multi-tenant HTTP front door.

The traffic layer between the wire and the continuous-batching engine
(ROADMAP item 3): an OpenAI-compatible completions server (stdlib-only
HTTP), priority classes + per-tenant weighted fair-share admission
replacing the engine's single FIFO, telemetry-driven load shedding
(estimated TTFT vs. request deadline -> early structured 429 with
``Retry-After``), and a least-loaded router over N engine replicas that
fails over away from DEAD engines.

    from paddle_tpu.serving import Engine
    from paddle_tpu.serving.gateway import TenantConfig, start_gateway

    stack = start_gateway(
        [Engine(model, max_slots=8, max_len=512)],
        tenants=[TenantConfig("prod", priority="interactive", weight=4.0),
                 TenantConfig("batch", priority="batch", max_queue=64)],
        own_engines=True)
    print("listening on", stack.address)   # POST /v1/completions
    ...
    stack.close()

See docs/serving.md (gateway section) for endpoints, the admission
policy knobs, the shed formula and router behavior.
"""
from .admission import (  # noqa: F401
    AdmissionError,
    FairShareScheduler,
    TenantConfig,
)
from .gateway import (  # noqa: F401
    Gateway,
    GatewayClosedError,
    GatewayRequest,
)
from .http import (  # noqa: F401
    GatewayHTTPServer,
    GatewayStack,
    start_gateway,
)
from .protocol import (  # noqa: F401
    PRIORITIES,
    CompletionRequest,
    ProtocolError,
    parse_completion_request,
    tenant_from_headers,
)
from .router import EngineRouter, NoEngineAvailableError  # noqa: F401
from .shed import LoadShedder, ShedDecision  # noqa: F401

__all__ = [
    "AdmissionError", "CompletionRequest", "EngineRouter",
    "FairShareScheduler", "Gateway", "GatewayClosedError",
    "GatewayHTTPServer", "GatewayRequest", "GatewayStack", "LoadShedder",
    "NoEngineAvailableError", "PRIORITIES", "ProtocolError", "ShedDecision",
    "TenantConfig", "parse_completion_request", "start_gateway",
    "tenant_from_headers",
]
