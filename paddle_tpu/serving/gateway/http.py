"""HTTP front door — stdlib ``http.server`` over the Gateway core.

Endpoints:

* ``POST /v1/completions`` — OpenAI-compatible completions; with
  ``"stream": true`` the response is ``text/event-stream`` carried over
  chunked transfer encoding, one SSE ``data:`` event per token and a
  final ``data: [DONE]``.
* ``POST /v1/chat/completions`` — the conversation-first door
  (docs/serving.md "KV tiering & conversations"): ``messages`` flatten
  to one prompt, an optional ``conversation`` id namespaces the prefix
  cache per (adapter, conversation) so a returning user's turn N+1
  costs tail-prefill only.  Same admission / streaming / journey
  machinery as completions; responses frame as
  ``chat.completion[.chunk]``.
* ``GET /healthz`` — liveness JSON (200 while any replica is alive,
  503 otherwise).
* ``GET /metrics`` — the process-wide Prometheus exposition (serving +
  gateway series from the paddle_tpu.observability registry); scraping
  it refreshes the ``paddle_tpu_gateway_window_*`` gauges from the
  rolling :class:`~paddle_tpu.observability.journey.TelemetryWindow`
  AND the ``paddle_tpu_device_memory_bytes`` backend allocator gauges
  (``steps.record_memory_stats``), so a pure-serving process exports
  device memory without a train loop.
* ``GET /debug/requests?last=N&tenant=&outcome=`` — the newest N
  finished request journeys as JSON timelines (phase-level latency
  attribution; docs/observability.md "Request journeys").  ``tenant=``
  and ``outcome=`` filter the whole ring before the ``last`` tail, so a
  busy multi-tenant ring stays navigable.
* ``GET /debug/requests/<id>`` — one journey by id (live or finished).
* ``GET /debug/capture?last=N&tenant=&outcome=&conversation=`` — the
  traffic-capture ring: one entry per request the gateway saw, admitted
  or shed, with arrival offset, tenant/priority, lengths, sampling
  params, conversation id and the journey id (docs/observability.md
  "Traffic capture & replay").
* ``GET /debug/window`` — ``Gateway.window_stats()`` as JSON (the
  autoscaler feed: windowed TTFT/queue-wait/per-token percentiles,
  shed rate, phase shares).
* ``GET /debug/fleet`` — ``Gateway.fleet_stats()`` as JSON: per-replica
  alive/draining/restarting state and, with an
  :class:`~paddle_tpu.serving.autoscaler.Autoscaler` attached, the
  fleet bounds, desired count, in-flight scale op, cold-build EWMA and
  recent scale events.
* ``GET /debug/perf`` — the perfscope roofline table as JSON: per
  compiled program, dispatch/sample counts, sampled device time, MFU
  and HBM-bandwidth fractions (docs/observability.md "Device
  perfscope").
* ``GET /debug/memory`` — the HBM ownership ledger as JSON: per-owner
  device bytes, the backend allocator's ``bytes_in_use``, and the
  unattributed remainder.

Every completion handler mints a request **journey** — adopting the
client's ``X-Request-Id`` header when present — threads it through
admission, dispatch and the engine, echoes the id back as an
``X-Request-Id`` response header (and in the SSE finish event), and
finishes the journey when the response is fully on the wire, so the
timeline partitions the client-observed wall time.

One OS thread per in-flight HTTP request (``ThreadingHTTPServer``): the
handler parses and admits, then *blocks* on the gateway item while the
single dispatcher thread feeds the engines — a deliberate shape, because
request concurrency is already bounded by the admission layer's queue +
concurrency caps, so the thread count is too.

429 responses (queue caps and SLO sheds) carry a ``Retry-After`` header
and the OpenAI error envelope with a machine-readable ``code``.
"""
from __future__ import annotations

import json
import signal
import threading
import time
from concurrent.futures import CancelledError
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from queue import Empty

from ...observability import flight, registry
from ...observability import journey as journey_mod
from ..engine import (DeadlineExceededError, EngineClosedError,
                      EngineDeadError, RequestInterruptedError)
from .admission import AdmissionError
from .gateway import Gateway, GatewayClosedError
from .protocol import (SSE_DONE, ProtocolError, chat_chunk_body,
                       chat_completion_body, chunk_body, completion_body,
                       error_body, parse_chat_request,
                       parse_completion_request, sse_event,
                       tenant_from_headers)
from .router import NoEngineAvailableError

__all__ = ["GatewayHTTPServer", "start_gateway", "GatewayStack"]

GATEWAY_HTTP = "paddle_tpu_gateway_http_responses_total"

_JSON = "application/json"
# streamed responses poll the token queue at this period so an engine-side
# failure/deadline mid-stream is noticed promptly
_STREAM_POLL_S = 0.05


class GatewayHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer bound to a Gateway instance."""

    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, address, gateway: Gateway,
                 request_timeout_s: float = 600.0):
        self.gateway = gateway
        self.request_timeout_s = float(request_timeout_s)
        super().__init__(address, _Handler)

    @property
    def port(self) -> int:
        return self.server_address[1]


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "paddle-tpu-gateway/1.0"

    # requests land in the metrics/flight layers; stderr stays quiet
    def log_message(self, format, *args):  # noqa: A002
        pass

    @property
    def gateway(self) -> Gateway:
        return self.server.gateway

    # -- plumbing ------------------------------------------------------------
    def _send_json(self, status: int, payload: dict, headers=()):
        body = json.dumps(payload).encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", _JSON)
        self.send_header("Content-Length", str(len(body)))
        for k, v in headers:
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)
        registry().counter(GATEWAY_HTTP, "gateway HTTP responses by code"
                           ).inc(1.0, labels={"code": status})

    @staticmethod
    def _error_wire(err: Exception):
        """(status, body, extra_headers, outcome-code) for one mapped
        error — the journey finishes with the same code the wire
        carries."""
        if isinstance(err, ProtocolError):
            return err.status, err.body(), [], (err.code or "protocol")
        if isinstance(err, AdmissionError):
            body = error_body(str(err), etype="rate_limit_exceeded",
                              code=err.reason)
            if err.est_ttft_s is not None:
                body["error"]["est_ttft_ms"] = round(err.est_ttft_s * 1e3, 1)
            return err.status, body, [
                ("Retry-After", str(max(1, round(err.retry_after_s))))], \
                err.reason
        if isinstance(err, DeadlineExceededError):
            return 504, error_body(str(err), etype="timeout_error",
                                   code="deadline_exceeded"), [], \
                "deadline_exceeded"
        if isinstance(err, RequestInterruptedError):
            # the engine died mid-generation and the retry budget could
            # not absorb it; tokens may have been produced, none are
            # delivered — the client decides whether to re-send
            return 503, error_body(str(err), etype="server_error",
                                   code="interrupted"), [], "interrupted"
        if isinstance(err, (NoEngineAvailableError, GatewayClosedError,
                            EngineClosedError, EngineDeadError)):
            return 503, error_body(str(err), etype="server_error",
                                   code="unavailable"), [], "unavailable"
        if isinstance(err, CancelledError):
            return 500, error_body("request was cancelled",
                                   etype="server_error",
                                   code="cancelled"), [], "cancelled"
        if isinstance(err, TimeoutError):
            return 504, error_body(str(err), etype="timeout_error",
                                   code="timeout"), [], "timeout"
        return 500, error_body(f"{type(err).__name__}: {err}",
                               etype="server_error",
                               code="internal"), [], "internal"

    def _send_error_obj(self, err: Exception, request_id: str | None = None):
        status, body, headers, _ = self._error_wire(err)
        if request_id:
            headers = list(headers) + [("X-Request-Id", request_id)]
        self._send_json(status, body, headers=headers)

    # -- GET -----------------------------------------------------------------
    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        try:
            path, _, query = self.path.partition("?")
            if path == "/healthz":
                health = self.gateway.healthz()
                self._send_json(200 if health["alive"] else 503, health)
            elif path == "/metrics":
                # a scrape also refreshes the windowed-feed gauges so
                # paddle_tpu_gateway_window_* export current values, and
                # the backend device-memory gauges (pure-serving
                # processes have no train loop to call this)
                try:
                    self.gateway.window_stats()
                except Exception:  # noqa: BLE001 — never break a scrape
                    pass
                try:
                    from ...observability import steps as steps_mod
                    steps_mod.record_memory_stats()
                except Exception:  # noqa: BLE001 — never break a scrape
                    pass
                text = registry().to_prometheus_text().encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(text)))
                self.end_headers()
                self.wfile.write(text)
                registry().counter(
                    GATEWAY_HTTP, "gateway HTTP responses by code").inc(
                    1.0, labels={"code": 200})
            elif path == "/debug/window":
                self._send_json(200, self.gateway.window_stats())
            elif path == "/debug/fleet":
                self._send_json(200, self.gateway.fleet_stats())
            elif path == "/debug/perf":
                from ...observability import perfscope
                self._send_json(200, perfscope.perf_report())
            elif path == "/debug/memory":
                from ...observability import perfscope
                self._send_json(200, perfscope.memory_report())
            elif path == "/debug/slo":
                slo = self.gateway.slo_engine
                if slo is None:
                    self._send_json(404, error_body(
                        "no SLO engine attached to this gateway",
                        code="no_slo_engine"))
                else:
                    self._send_json(200, slo.debug_state())
            elif path == "/debug/incidents" or \
                    path.startswith("/debug/incidents/"):
                slo = self.gateway.slo_engine
                if slo is None:
                    self._send_json(404, error_body(
                        "no SLO engine attached to this gateway",
                        code="no_slo_engine"))
                elif path == "/debug/incidents":
                    self._send_json(200, {
                        "incidents": slo.store.list()})
                else:
                    inc_id = path[len("/debug/incidents/"):]
                    bundle = slo.store.get(inc_id)
                    if bundle is None:
                        self._send_json(404, error_body(
                            f"no incident {inc_id!r} (ring holds "
                            f"{len(slo.store.list())})",
                            code="incident_not_found"))
                    else:
                        self._send_json(200, bundle)
            elif path == "/debug/capture":
                last = 64
                tenant = outcome = conversation = None
                for part in query.split("&"):
                    if part.startswith("last="):
                        try:
                            last = max(0, int(part[5:]))
                        except ValueError:
                            pass
                    elif part.startswith("tenant="):
                        tenant = part[7:]
                    elif part.startswith("outcome="):
                        outcome = part[8:]
                    elif part.startswith("conversation="):
                        conversation = part[13:]
                self._send_json(200, self.gateway.capture.debug_state(
                    last=last, tenant=tenant, outcome=outcome,
                    conversation=conversation))
            elif path == "/debug/requests":
                last = 32
                tenant = outcome = None
                for part in query.split("&"):
                    if part.startswith("last="):
                        try:
                            last = max(0, int(part[5:]))
                        except ValueError:
                            pass
                    elif part.startswith("tenant="):
                        tenant = part[7:]
                    elif part.startswith("outcome="):
                        outcome = part[8:]
                if tenant is None and outcome is None:
                    requests = journey_mod.recent(last)
                else:
                    # filter over the WHOLE ring, then tail: on a busy
                    # multi-tenant ring the newest N unfiltered entries
                    # may hold none of the tenant you're hunting
                    requests = [
                        j for j in journey_mod.recent(10 ** 9)
                        if (tenant is None
                            or j.attrs.get("tenant") == tenant)
                        and (outcome is None or j.outcome == outcome)
                    ][-last:] if last else []
                self._send_json(200, {
                    "requests": [j.timeline() for j in requests],
                    "active": [j.id for j in journey_mod.active()],
                })
            elif path.startswith("/debug/requests/"):
                jid = path[len("/debug/requests/"):]
                j = journey_mod.get(jid)
                if j is None:
                    self._send_json(404, error_body(
                        f"no journey {jid!r} (ring holds the newest "
                        f"{len(journey_mod.recent(10 ** 9))})",
                        code="journey_not_found"))
                else:
                    self._send_json(200, j.timeline())
            else:
                self._send_json(404, error_body(
                    f"no such endpoint: {self.path}", code="not_found"))
        except (BrokenPipeError, ConnectionResetError):
            pass

    # -- POST ----------------------------------------------------------------
    def do_POST(self):  # noqa: N802
        try:
            if self.path not in ("/v1/completions", "/v1/chat/completions"):
                self._send_json(404, error_body(
                    f"no such endpoint: {self.path}", code="not_found"))
                return
            parse = (parse_chat_request
                     if self.path == "/v1/chat/completions"
                     else parse_completion_request)
            gw = self.gateway
            # journey start == client-observed request start; the id is
            # adopted from the client's X-Request-Id when present and
            # echoed back on every response (header + SSE finish event)
            j = journey_mod.adopt_or_begin(
                self.headers.get("X-Request-Id"))
            try:
                try:
                    tenant = tenant_from_headers(self.headers, gw.api_keys)
                    length = int(self.headers.get("Content-Length") or 0)
                    raw = self.rfile.read(length)
                    creq = parse(
                        raw, has_tokenizer=gw.tokenizer is not None)
                    j.phase("parse", j.t0, time.perf_counter() - j.t0,
                            body_bytes=len(raw))
                    item = gw.admit(creq, tenant, journey=j)
                except (ProtocolError, AdmissionError, GatewayClosedError,
                        NoEngineAvailableError) as e:
                    outcome = self._error_wire(e)[3]
                    self._send_error_obj(e, request_id=j.id)
                    j.finish(outcome)
                    return
                if creq.stream:
                    self._stream_completion(gw, item)
                else:
                    self._blocking_completion(gw, item)
            finally:
                # a torn socket (or an unexpected handler error) must
                # not leak a live journey in the active table
                if not j.done:
                    j.finish("aborted")
        except (BrokenPipeError, ConnectionResetError):
            pass

    def _model_name(self, creq) -> str:
        return creq.model or self.gateway.model_name

    def _body_for(self, item, text, token_ids, finish, prompt_tokens,
                  request_id=None) -> dict:
        """Final-response envelope: ``chat.completion`` for the chat
        door, ``text_completion`` otherwise."""
        creq = item.creq
        if getattr(creq, "chat", False):
            return chat_completion_body(
                item.id, self._model_name(creq), text, token_ids, finish,
                prompt_tokens, request_id=request_id,
                conversation=creq.conversation)
        return completion_body(
            item.id, self._model_name(creq), text, token_ids, finish,
            prompt_tokens, request_id=request_id)

    def _chunk_for(self, item, text, token_ids, finish,
                   request_id=None) -> dict:
        """One SSE delta: ``chat.completion.chunk`` or the completions
        chunk, matching the door the request came through."""
        creq = item.creq
        if getattr(creq, "chat", False):
            return chat_chunk_body(
                item.id, self._model_name(creq), text, token_ids, finish,
                request_id=request_id, conversation=creq.conversation)
        return chunk_body(item.id, self._model_name(creq), text,
                          token_ids, finish, request_id=request_id)

    def _text(self, tokens) -> str:
        tok = self.gateway.tokenizer
        if tok is None:
            return ""
        return tok.decode([int(t) for t in tokens])

    def _blocking_completion(self, gw: Gateway, item):
        j = item.journey
        try:
            tokens, finish = gw.result(
                item, timeout=self.server.request_timeout_s)
        except Exception as e:  # noqa: BLE001 — mapped to wire errors
            self._send_error_obj(e, request_id=j.id if j else None)
            if j is not None:
                gw.finish_journey(item, self._error_wire(e)[3])
            return
        t_r0 = time.perf_counter()
        body = self._body_for(
            item, self._text(tokens),
            [int(t) for t in tokens], finish, int(item.prompt.size),
            request_id=j.id if j else None)
        self._send_json(200, body, headers=[
            ("X-Paddle-Tpu-Engine", item.engine_name or "")]
            + ([("X-Request-Id", j.id)] if j else []))
        if j is not None:
            j.phase("respond", t_r0, time.perf_counter() - t_r0,
                    tokens=len(tokens))
            gw.finish_journey(item, "ok")

    # -- streaming -----------------------------------------------------------
    def _write_chunk(self, data: bytes):
        self.wfile.write(f"{len(data):x}\r\n".encode("ascii"))
        self.wfile.write(data)
        self.wfile.write(b"\r\n")

    def _end_chunks(self):
        self.wfile.write(b"0\r\n\r\n")

    def _stream_completion(self, gw: Gateway, item):
        j = item.journey
        # wait for dispatch (or early failure) before committing to 200 —
        # sheds and routing failures still map to clean HTTP errors
        if not item.ready.wait(self.server.request_timeout_s):
            e = TimeoutError(f"request {item.id} was not dispatched in time")
            self._send_error_obj(e, request_id=j.id if j else None)
            if j is not None:
                gw.finish_journey(item, "timeout")
            return
        if item.error is not None:
            self._send_error_obj(item.error,
                                 request_id=j.id if j else None)
            if j is not None:
                gw.finish_journey(item, self._error_wire(item.error)[3])
            return
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Transfer-Encoding", "chunked")
        self.send_header("X-Paddle-Tpu-Engine", item.engine_name or "")
        if j is not None:
            self.send_header("X-Request-Id", j.id)
        self.end_headers()
        registry().counter(GATEWAY_HTTP, "gateway HTTP responses by code"
                           ).inc(1.0, labels={"code": 200})
        sent = 0
        outcome = "ok"
        try:
            # final outcome comes from item.done_ev / item.final_error,
            # never the raw handle: a supervisor or the gateway reaper
            # may transparently replace the handle while re-dispatching
            # a zero-token engine death
            while True:
                try:
                    tok = item.token_q.get(timeout=_STREAM_POLL_S)
                except Empty:
                    if item.done_ev.is_set():
                        break
                    continue
                sent += 1
                self._write_chunk(sse_event(self._chunk_for(
                    item, self._text([tok]), [int(tok)], None)))
            t_done = time.perf_counter()
            # drain tokens that raced the done check
            while not item.token_q.empty():
                tok = item.token_q.get_nowait()
                sent += 1
                self._write_chunk(sse_event(self._chunk_for(
                    item, self._text([tok]), [int(tok)], None)))
            err = item.final_error
            if err is None:
                handle = item.handle
                eos = handle.eos_token_id
                toks = handle.tokens
                finish = ("stop" if eos is not None and toks and
                          toks[-1] == eos else "length")
                self._write_chunk(sse_event(self._chunk_for(
                    item, "", [], finish,
                    request_id=j.id if j else None)))
            else:
                outcome = ("stream_interrupted"
                           if isinstance(err, RequestInterruptedError)
                           else "stream_aborted")
                payload = {
                    "id": item.id,
                    "error": error_body(
                        f"{type(err).__name__}: {err}",
                        etype="server_error", code=outcome)["error"]}
                if j is not None:
                    payload["request_id"] = j.id
                self._write_chunk(sse_event(payload))
            self._write_chunk(SSE_DONE)
            self._end_chunks()
            if j is not None:
                # token writes overlap decode (already attributed); the
                # post-completion flush + finish frames are the stream's
                # own cost
                j.phase("stream", t_done, time.perf_counter() - t_done,
                        tokens_sent=sent)
        except (BrokenPipeError, ConnectionResetError):
            # client went away mid-stream: free the slot immediately
            outcome = "client_disconnect"
            item.handle.cancel()
        if j is not None:
            gw.finish_journey(item, outcome)


# -- convenience stack --------------------------------------------------------

class GatewayStack:
    """Gateway + HTTP server + serving thread, torn down in order.

    Graceful shutdown (the serving analogue of
    ``framework/preemption.py``): :meth:`install_sigterm_drain` converts
    SIGTERM into shed-new-traffic-with-``Retry-After`` -> drain -> clean
    exit — the signal handler only sets an Event; a waiter thread runs
    the actual drain (flight events, locks and socket teardown are not
    async-signal-safe)."""

    def __init__(self, gateway: Gateway, server: GatewayHTTPServer,
                 thread: threading.Thread, own_engines: bool = False):
        self.gateway = gateway
        self.server = server
        self.thread = thread
        self.own_engines = own_engines
        self.slo_engine = None          # set by start_gateway(slo_*)
        self._lock = threading.Lock()
        self._sigterm_ev = threading.Event()
        self._terminated_ev = threading.Event()
        self._drain_deadline_s = 30.0
        self._drain_result: bool | None = None
        self._waiter: threading.Thread | None = None
        self._prev_sigterm = None

    @property
    def port(self) -> int:
        return self.server.port

    @property
    def address(self) -> str:
        host, port = self.server.server_address[:2]
        return f"http://{host}:{port}"

    def drain(self, deadline_s: float = 30.0) -> bool:
        """Graceful teardown: the HTTP listener keeps answering (new
        completions get 429 + ``Retry-After``) while the gateway runs its
        queued and in-flight work dry, then the owned engines drain their
        decode work, then everything closes.  Returns True when nothing
        was dropped."""
        t0 = time.perf_counter()
        ok = self.gateway.drain(deadline_s)
        if self.own_engines:
            for eng in self.gateway.router.engines:
                remaining = max(
                    0.5, deadline_s - (time.perf_counter() - t0))
                ok = eng.drain(remaining) and ok
        self.close()
        return ok

    def install_sigterm_drain(self, deadline_s: float = 30.0):
        """Arm the SIGTERM -> drain -> clean-exit path.  Call from the
        main thread (signal installation is impossible elsewhere)."""
        with self._lock:
            self._drain_deadline_s = float(deadline_s)
        ev = self._sigterm_ev

        def _handler(sig, frame):
            # async-signal-safe by construction: ONLY flips the Event;
            # the waiter thread does the lock/IO-heavy drain
            ev.set()

        prev = signal.getsignal(signal.SIGTERM)
        signal.signal(signal.SIGTERM, _handler)
        with self._lock:
            self._prev_sigterm = prev
        self._waiter = threading.Thread(
            target=self._drain_on_signal, daemon=True,
            name="paddle-tpu-gateway-drain")
        self._waiter.start()

    def _drain_on_signal(self):
        self._sigterm_ev.wait()
        if self._terminated_ev.is_set():
            return                    # already closed normally
        with self._lock:
            deadline_s = self._drain_deadline_s
        flight.record("gateway", "sigterm_drain", deadline_s=deadline_s)
        ok = self.drain(deadline_s)
        with self._lock:
            self._drain_result = ok

    @property
    def drain_result(self) -> bool | None:
        """Outcome of the signal-triggered drain (None before one ran)."""
        with self._lock:
            return self._drain_result

    def wait_terminated(self, timeout: float | None = None) -> bool:
        """Block until the stack is fully closed (normal close() or the
        SIGTERM drain path)."""
        return self._terminated_ev.wait(timeout)

    def close(self):
        """Stop accepting, fail queued work, (optionally) stop engines."""
        # the SLO evaluator thread polls gateway window state: stop it
        # FIRST so no tick races the teardown below
        if self.slo_engine is not None:
            self.slo_engine.shutdown()
        self.server.shutdown()
        self.server.server_close()
        self.gateway.shutdown()
        if self.own_engines:
            for eng in self.gateway.router.engines:
                eng.shutdown()
        self.thread.join(timeout=10)
        with self._lock:
            prev, self._prev_sigterm = self._prev_sigterm, None
        if prev is not None:
            try:
                signal.signal(signal.SIGTERM, prev)
            except (ValueError, OSError):   # not the main thread
                pass
        self._terminated_ev.set()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def start_gateway(engines, host: str = "127.0.0.1", port: int = 0, *,
                  own_engines: bool = False, request_timeout_s: float = 600.0,
                  slo_objectives=None, slo_tick_s: float = 1.0,
                  slo_incident_dir: str | None = None,
                  slo_max_incidents: int = 32,
                  **gateway_kwargs) -> GatewayStack:
    """Boot the full front door: Gateway core + threaded HTTP server on
    ``host:port`` (port 0 = ephemeral; read ``stack.port``).  Extra
    keyword args go to :class:`Gateway`.

    ``slo_objectives`` (a list of :class:`~paddle_tpu.observability.slo.
    SloObjective`) attaches an :class:`~paddle_tpu.observability.slo.
    SloEngine` evaluating them every ``slo_tick_s`` — burn-rate alerts
    on ``/debug/slo``, incident bundles (ring-bounded at
    ``slo_max_incidents`` under ``slo_incident_dir``) on
    ``/debug/incidents``.

    Traffic capture rides the same passthrough: ``capture_mode=``
    (``shape``/``full``), ``capture_entries=`` and
    ``capture_spill_dir=`` build a gateway-local
    :class:`~paddle_tpu.observability.capture.TrafficCapture` (or pass
    ``capture=`` an instance); with none set the gateway records into
    the process default.  Either way ``GET /debug/capture`` serves the
    ring and incident bundles gain the ``capture_tail`` section."""
    gateway = (engines if isinstance(engines, Gateway)
               else Gateway(engines, **gateway_kwargs))
    server = GatewayHTTPServer((host, port), gateway,
                               request_timeout_s=request_timeout_s)
    thread = threading.Thread(target=server.serve_forever,
                              name="paddle-tpu-gateway-http", daemon=True)
    thread.start()
    stack = GatewayStack(gateway, server, thread, own_engines=own_engines)
    if slo_objectives:
        from ...observability.slo import SloEngine
        stack.slo_engine = SloEngine(
            gateway, slo_objectives, tick_s=slo_tick_s,
            incident_dir=slo_incident_dir,
            max_incidents=slo_max_incidents)
    return stack
