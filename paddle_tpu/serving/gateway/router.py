"""Router — spread tenants across N engine replicas, fail over from DEAD.

Replica scale-out for the serving path: the gateway dispatches each
admitted request to the **least-loaded alive** engine, load being the
O(1) ``Engine.load()`` snapshot (slot occupancy + engine-side queue
depth).  An engine whose scheduler crashed reports ``alive: False``
(PR 5's ``EngineDeadError`` semantics) and is simply never picked again —
the remaining replicas absorb its traffic; with every replica dead the
router raises :class:`NoEngineAvailableError` (HTTP 503).
"""
from __future__ import annotations

from ...observability import registry

__all__ = ["NoEngineAvailableError", "EngineRouter"]

GATEWAY_ENGINE_SLOTS = "paddle_tpu_gateway_engine_slots_in_use"
GATEWAY_ENGINES_ALIVE = "paddle_tpu_gateway_engines_alive"


class NoEngineAvailableError(RuntimeError):
    """Every replica is dead or shut down — the gateway answers 503."""


class EngineRouter:
    """Least-loaded routing over a fixed set of engine replicas."""

    def __init__(self, engines, names=None):
        engines = list(engines)
        if not engines:
            raise ValueError("router needs at least one engine")
        if names is None:
            names = [f"engine{i}" for i in range(len(engines))]
        if len(names) != len(engines) or len(set(names)) != len(names):
            raise ValueError("names must be unique, one per engine")
        self._engines = list(zip(list(names), engines))

    @property
    def engines(self) -> list:
        return [e for _, e in self._engines]

    @property
    def names(self) -> list:
        return [n for n, _ in self._engines]

    def loads(self) -> dict:
        """{name: Engine.load() snapshot} for every replica; also refreshes
        the per-engine occupancy gauges."""
        reg = registry()
        out = {}
        alive = 0
        for name, eng in self._engines:
            ld = eng.load()
            out[name] = ld
            alive += bool(ld["alive"])
            reg.gauge(GATEWAY_ENGINE_SLOTS,
                      "per-replica slots owned by requests").set(
                float(ld["slots_in_use"]), labels={"engine": name})
        reg.gauge(GATEWAY_ENGINES_ALIVE, "replicas able to take work").set(
            float(alive))
        return out

    def pick(self, exclude=()) -> tuple:
        """(name, engine) of the least-loaded alive replica (slot
        occupancy first, engine queue depth as the tiebreak); raises
        :class:`NoEngineAvailableError` when none qualifies."""
        best = None
        best_key = None
        for name, eng in self._engines:
            if name in exclude:
                continue
            ld = eng.load()
            if not ld["alive"]:
                continue
            key = (ld["slots_in_use"] + ld["queue_depth"],
                   ld["queue_depth"], name)
            if best_key is None or key < best_key:
                best, best_key = (name, eng), key
        if best is None:
            raise NoEngineAvailableError(
                "no alive engine replica (all dead, excluded, or shut down)")
        return best

    def any_alive(self) -> bool:
        return any(eng.load()["alive"] for _, eng in self._engines)

    def has_headroom(self, slack: int = 1) -> bool:
        """True when some alive replica can take one more request without
        queuing deeper than `slack` behind its slot pool — the dispatcher
        gate that keeps ordering decisions IN the gateway's fair-share
        queues instead of an engine FIFO."""
        for _, eng in self._engines:
            ld = eng.load()
            if ld["alive"] and \
                    ld["slots_in_use"] + ld["queue_depth"] < \
                    ld["max_slots"] + slack:
                return True
        return False

    def total_slots(self) -> int:
        """Aggregate decode parallelism of the alive replicas (the shed
        formula's drain rate denominator)."""
        total = 0
        for _, eng in self._engines:
            ld = eng.load()
            if ld["alive"]:
                total += ld["max_slots"]
        return total or 1

    def min_max_len(self) -> int:
        """Tightest per-request length bound across alive replicas (admission
        validates prompt+max_tokens against this)."""
        lens = [e.max_len for _, e in self._engines
                if e.load()["alive"]]
        return min(lens) if lens else min(e.max_len
                                          for _, e in self._engines)
