"""Router — spread tenants across N engine replicas, fail over from DEAD.

Replica scale-out for the serving path: the gateway dispatches each
admitted request to the **least-loaded alive** engine, load being the
O(1) ``Engine.load()`` snapshot (slot occupancy + engine-side queue
depth).  An engine whose scheduler crashed reports ``alive: False``
(PR 5's ``EngineDeadError`` semantics) and is simply never picked again —
the remaining replicas absorb its traffic; with every replica dead the
router raises :class:`NoEngineAvailableError` (HTTP 503).

Membership is DYNAMIC (the autoscaler's substrate, ROADMAP item 5):
:meth:`add_replica` / :meth:`remove_replica` mutate the replica set
under the router's lock, safe against a dispatcher mid-``pick`` and the
reaper's cross-replica redispatch — both iterate a snapshot.  A replica
whose ``load()`` reports ``draining`` is a THIRD state: not pickable
(no new work, so parked zero-token requests never redispatch onto a
replica that is leaving) but NOT dead — :meth:`any_draining` lets the
gateway keep queued work parked instead of 503-ing while the only other
capacity is mid-cold-build.  Removing a replica deletes its per-engine
gauge series (``paddle_tpu_gateway_engine_slots_in_use{engine=...}``)
instead of freezing them at the last value.

Each replica also carries a **revision** label (ISSUE 20): the rollout
controller tags the replicas it builds with the target revision, so
``/debug/fleet`` and ``paddle_tpu_fleet_replicas_alive{revision=...}``
show exactly which builds are serving at any instant of an upgrade —
and the no-mixed-revision-steady-state invariant is assertable.  When
two alive replicas both have headroom, :meth:`pick` prefers the one
whose adapter bank already holds the request's LoRA adapter
(``adapter=``, the locality tiebreak): residency beats least-loaded
once cold loads dominate TTFT, and with no adapter (or no resident
replica with room) the ordering is exactly the pre-locality one.
"""
from __future__ import annotations

import threading

from ...observability import registry
from ..autoscaler import FLEET_ALIVE

__all__ = ["NoEngineAvailableError", "EngineRouter"]

GATEWAY_ENGINE_SLOTS = "paddle_tpu_gateway_engine_slots_in_use"
GATEWAY_ENGINES_ALIVE = "paddle_tpu_gateway_engines_alive"


class NoEngineAvailableError(RuntimeError):
    """Every replica is dead or shut down — the gateway answers 503."""


class EngineRouter:
    """Least-loaded routing over a dynamic set of engine replicas."""

    def __init__(self, engines, names=None, revision: str = "r0"):
        engines = list(engines)
        if not engines:
            raise ValueError("router needs at least one engine")
        if names is None:
            names = [f"engine{i}" for i in range(len(engines))]
        if len(names) != len(engines) or len(set(names)) != len(names):
            raise ValueError("names must be unique, one per engine")
        self._lock = threading.Lock()
        self._engines = list(zip(list(names), engines))
        self._revisions = {n: str(revision) for n in names}

    def _snapshot(self) -> list:
        with self._lock:
            return list(self._engines)

    # -- membership (autoscaler control thread vs dispatcher/reaper) ---------
    def add_replica(self, name: str, engine, revision: str = "r0"):
        """Add one replica under the router's lock; the dispatcher's next
        ``pick``/``has_headroom`` sees it immediately.  ``revision``
        tags the build (the rollout controller's label; scale-ups tag
        the fleet's current revision)."""
        name = str(name)
        with self._lock:
            if any(n == name for n, _ in self._engines):
                raise ValueError(f"replica name {name!r} already routed")
            self._engines.append((name, engine))
            self._revisions[name] = str(revision)

    def remove_replica(self, name: str):
        """Remove one replica (returns its engine) and DELETE its
        per-engine gauge series — a removed replica must vanish from the
        dashboard, not freeze at its last occupancy.  Raises KeyError on
        an unknown name; refuses to empty the router."""
        with self._lock:
            idx = next((i for i, (n, _) in enumerate(self._engines)
                        if n == name), None)
            if idx is None:
                raise KeyError(f"no replica named {name!r}")
            if len(self._engines) == 1:
                raise ValueError("refusing to remove the last replica")
            _, eng = self._engines.pop(idx)
            self._revisions.pop(name, None)
        registry().gauge(GATEWAY_ENGINE_SLOTS,
                         "per-replica slots owned by requests").remove(
            labels={"engine": name})
        return eng

    def revisions(self) -> dict:
        """{replica name: revision label} for the current membership."""
        with self._lock:
            return dict(self._revisions)

    def revision_of(self, name: str) -> str:
        with self._lock:
            return self._revisions.get(name, "r0")

    @property
    def engines(self) -> list:
        return [e for _, e in self._snapshot()]

    @property
    def names(self) -> list:
        return [n for n, _ in self._snapshot()]

    def loads(self) -> dict:
        """{name: Engine.load() snapshot} for every replica; also refreshes
        the per-engine occupancy gauges (and drops series for replicas
        that left the router since the last refresh)."""
        reg = registry()
        out = {}
        alive = 0
        current = self._snapshot()
        revs = self.revisions()
        gauge = reg.gauge(GATEWAY_ENGINE_SLOTS,
                          "per-replica slots owned by requests")
        by_rev: dict = {}
        for name, eng in current:
            ld = eng.load()
            out[name] = ld
            alive += bool(ld["alive"])
            gauge.set(float(ld["slots_in_use"]), labels={"engine": name})
            if ld["alive"] and not ld.get("draining"):
                rev = revs.get(name, "r0")
                by_rev[rev] = by_rev.get(rev, 0) + 1
        # sweep series whose engine is no longer routed (a remove_replica
        # racing this refresh can re-export a stale series for one poll)
        routed = {name for name, _ in current}
        for labels, _ in gauge.series():
            name = labels.get("engine")
            if name is not None and name not in routed:
                gauge.remove(labels={"engine": name})
        reg.gauge(GATEWAY_ENGINES_ALIVE, "replicas able to take work").set(
            float(alive))
        # the revision-labelled fleet view (ISSUE 20): which builds are
        # serving right now — mid-rollout both revisions export, at the
        # steady state exactly one does (stale revisions are swept, the
        # autoscaler's unlabelled series is left alone)
        alive_g = reg.gauge(FLEET_ALIVE, "alive, non-draining replicas")
        for rev, n in by_rev.items():
            alive_g.set(float(n), labels={"revision": rev})
        for labels, _ in alive_g.series():
            rev = labels.get("revision")
            if rev is not None and rev not in by_rev:
                alive_g.remove(labels={"revision": rev})
        return out

    def pick(self, exclude=(), adapter: str | None = None) -> tuple:
        """(name, engine) of the least-loaded alive replica (slot
        occupancy first, engine queue depth as the tiebreak); raises
        :class:`NoEngineAvailableError` when none qualifies.  Draining
        replicas are never picked — new work (including redispatched
        parked work) must not land on a replica that is leaving.

        ``adapter`` is the locality tiebreak (ROADMAP 5d): a replica
        whose bank already holds the request's LoRA adapter AND has a
        free slot wins over a colder least-loaded one — the dispatch
        skips the admission-time cold load.  Residency never overrides
        backpressure: a resident replica with its slot pool full falls
        back into the ordinary least-loaded order."""
        best = None
        best_key = None
        for name, eng in self._snapshot():
            if name in exclude:
                continue
            ld = eng.load()
            if not ld["alive"] or ld.get("draining"):
                continue
            local = False
            if adapter is not None:
                probe = getattr(eng, "adapter_resident", None)
                if probe is not None:
                    try:
                        local = (bool(probe(adapter)) and
                                 ld["slots_in_use"] + ld["queue_depth"]
                                 < ld["max_slots"])
                    except Exception:  # noqa: BLE001 — locality is a hint
                        local = False
            key = (0 if local else 1,
                   ld["slots_in_use"] + ld["queue_depth"],
                   ld["queue_depth"], name)
            if best_key is None or key < best_key:
                best, best_key = (name, eng), key
        if best is None:
            raise NoEngineAvailableError(
                "no alive engine replica (all dead, excluded, or shut down)")
        return best

    def any_alive(self) -> bool:
        return any(eng.load()["alive"] for _, eng in self._snapshot())

    def any_draining(self) -> bool:
        """True while some replica is draining — a third state between
        alive and dead: it takes no new work but its in-flight work is
        finishing, so the gateway parks queued work instead of failing
        it (no spurious 503 while the only other replica is
        mid-cold-build)."""
        return any(eng.load().get("draining")
                   for _, eng in self._snapshot())

    def has_headroom(self, slack: int = 1) -> bool:
        """True when some alive replica can take one more request without
        queuing deeper than `slack` behind its slot pool — the dispatcher
        gate that keeps ordering decisions IN the gateway's fair-share
        queues instead of an engine FIFO."""
        for _, eng in self._snapshot():
            ld = eng.load()
            if ld["alive"] and not ld.get("draining") and \
                    ld["slots_in_use"] + ld["queue_depth"] < \
                    ld["max_slots"] + slack:
                return True
        return False

    def total_slots(self) -> int:
        """Aggregate decode parallelism of the alive replicas (the shed
        formula's drain rate denominator)."""
        total = 0
        for _, eng in self._snapshot():
            ld = eng.load()
            if ld["alive"] and not ld.get("draining"):
                total += ld["max_slots"]
        return total or 1

    def min_max_len(self) -> int:
        """Tightest per-request length bound across alive replicas (admission
        validates prompt+max_tokens against this)."""
        engines = self._snapshot()
        lens = [e.max_len for _, e in engines if e.load()["alive"]]
        return min(lens) if lens else min(e.max_len for _, e in engines)
