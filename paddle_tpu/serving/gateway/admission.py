"""Admission — priority classes and per-tenant weighted fair-share queues.

This layer replaces the engine's single FIFO as the traffic-facing queue:
every tenant gets its own bounded FIFO, tenants inside one priority class
share capacity by *weighted fair queuing* (start-time virtual clocks over
estimated token cost), and priority classes are strict — an
``interactive`` item is always dispatched before a ``standard`` one,
which beats ``batch``.  One greedy tenant can therefore fill only its own
queue (structured 429 beyond its cap), never another tenant's latency.

The scheduler is a passive, lock+condition protected structure: HTTP
handler threads ``enqueue()``, the gateway's single dispatcher thread
``pop()``s runnable work and ``release()``s a tenant's concurrency unit
when its request finishes.  Virtual time bookkeeping:

* each pop advances the tenant's clock by ``cost / weight`` where cost is
  the request's estimated token work (prompt + max_tokens) — a tenant
  sending few small requests outpaces one sending many large ones at
  equal weight;
* a tenant going idle -> active fast-forwards its clock to the tier's
  minimum active clock, so idleness banks no credit (standard SFQ
  behavior).
"""
from __future__ import annotations

import threading
import time
from collections import deque

from .protocol import PRIORITIES

__all__ = ["AdmissionError", "TenantConfig", "FairShareScheduler"]


class AdmissionError(Exception):
    """Structured 429: the admission layer refused the request.  Carries
    the machine-readable reason (``tenant_queue_full`` /
    ``tenant_concurrency`` / ``gateway_queue_full`` / ``slo_shed``) and a
    ``Retry-After`` hint in seconds."""

    status = 429

    def __init__(self, reason: str, message: str, *,
                 retry_after_s: float = 1.0, tenant: str | None = None,
                 est_ttft_s: float | None = None):
        super().__init__(message)
        self.reason = reason
        self.retry_after_s = max(0.0, float(retry_after_s))
        self.tenant = tenant
        self.est_ttft_s = est_ttft_s


class TenantConfig:
    """Per-tenant admission policy.  ``weight`` shares capacity inside the
    priority class; ``max_queue`` bounds the tenant's own FIFO (429
    beyond); ``max_concurrency`` caps the tenant's in-flight requests
    (queued work waits, other tenants proceed)."""

    __slots__ = ("name", "weight", "priority", "max_queue",
                 "max_concurrency")

    def __init__(self, name: str, *, weight: float = 1.0,
                 priority: str = "standard", max_queue: int = 16,
                 max_concurrency: int | None = None):
        if priority not in PRIORITIES:
            raise ValueError(f"unknown priority {priority!r} "
                             f"(one of {sorted(PRIORITIES)})")
        if float(weight) <= 0:
            raise ValueError("weight must be > 0")
        if int(max_queue) < 1:
            raise ValueError("max_queue must be >= 1")
        self.name = name
        self.weight = float(weight)
        self.priority = priority
        self.max_queue = int(max_queue)
        self.max_concurrency = (None if max_concurrency is None
                                else int(max_concurrency))


class _TenantState:
    __slots__ = ("cfg", "q", "vtime", "in_flight", "inflight_cost",
                 "enqueued_total", "rejected_total")

    def __init__(self, cfg: TenantConfig):
        self.cfg = cfg
        self.q: deque = deque()
        self.vtime = 0.0
        self.in_flight = 0
        self.inflight_cost = 0.0
        self.enqueued_total = 0
        self.rejected_total = 0


class FairShareScheduler:
    """Priority tiers of weighted-fair per-tenant queues (see module
    docstring).  Items need ``tenant`` (str), ``cost`` (float tokens) and
    ``priority`` (a PRIORITIES key) attributes."""

    def __init__(self, tenants=None, *, default: TenantConfig | None = None,
                 max_queue_total: int | None = None):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._default = default or TenantConfig("default")
        self._tenants: dict[str, _TenantState] = {}
        self._closed = False
        self.max_queue_total = max_queue_total
        for cfg in (tenants or ()):
            self._tenants[cfg.name] = _TenantState(cfg)

    # -- configuration -------------------------------------------------------
    def configure(self, cfg: TenantConfig):
        """Add/replace one tenant's policy (existing queue is kept)."""
        with self._lock:
            st = self._tenants.get(cfg.name)
            if st is None:
                self._tenants[cfg.name] = _TenantState(cfg)
            else:
                st.cfg = cfg

    def _state_locked(self, name: str) -> _TenantState:
        st = self._tenants.get(name)
        if st is None:
            d = self._default
            st = self._tenants[name] = _TenantState(TenantConfig(
                name, weight=d.weight, priority=d.priority,
                max_queue=d.max_queue, max_concurrency=d.max_concurrency))
        return st

    def tenant_config(self, name: str) -> TenantConfig:
        with self._lock:
            return self._state_locked(name).cfg

    # -- producer side (HTTP handler threads) --------------------------------
    def enqueue(self, item):
        """Queue one work item under its tenant's caps; raises
        :class:`AdmissionError` (429) at the tenant/gateway bound."""
        with self._lock:
            if self._closed:
                raise AdmissionError(
                    "gateway_closed", "gateway is shutting down",
                    retry_after_s=5.0, tenant=item.tenant)
            st = self._state_locked(item.tenant)
            if len(st.q) >= st.cfg.max_queue:
                st.rejected_total += 1
                raise AdmissionError(
                    "tenant_queue_full",
                    f"tenant {item.tenant!r} queue is full "
                    f"({st.cfg.max_queue}); retry later",
                    retry_after_s=self._drain_eta_locked(st),
                    tenant=item.tenant)
            if self.max_queue_total is not None and \
                    self._depth_locked() >= self.max_queue_total:
                st.rejected_total += 1
                raise AdmissionError(
                    "gateway_queue_full",
                    f"gateway queue is full ({self.max_queue_total})",
                    retry_after_s=1.0, tenant=item.tenant)
            if not st.q and st.in_flight == 0:
                # idle -> active: no banked credit from the idle period
                active = [t.vtime for t in self._tenants.values()
                          if t is not st and (t.q or t.in_flight)]
                if active:
                    st.vtime = max(st.vtime, min(active))
            st.q.append(item)
            st.enqueued_total += 1
            self._cv.notify()

    def _drain_eta_locked(self, st: _TenantState) -> float:
        # crude Retry-After for a full tenant queue: one queue-slot's
        # worth of this tenant's round-share; the shed layer gives the
        # telemetry-driven estimate, this is just a floor
        return max(0.25, 0.05 * len(st.q))

    # -- consumer side (the gateway dispatcher thread) -----------------------
    def pop(self, timeout: float | None = None):
        """Next runnable item by (priority tier, fair-share clock), or
        None on timeout/close.  Increments the tenant's in-flight count —
        pair every pop with :meth:`release` (or :meth:`requeue`)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                if self._closed:
                    return None
                st = self._runnable_locked()
                if st is not None:
                    item = st.q.popleft()
                    st.vtime += item.cost / st.cfg.weight
                    st.in_flight += 1
                    st.inflight_cost += item.cost
                    return item
                if deadline is None:
                    self._cv.wait()
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                    self._cv.wait(remaining)

    def _runnable_locked(self) -> _TenantState | None:
        best, best_key = None, None
        for name in sorted(self._tenants):
            st = self._tenants[name]
            if not st.q:
                continue
            cap = st.cfg.max_concurrency
            if cap is not None and st.in_flight >= cap:
                continue
            # tier comes from the item at the head of the tenant's FIFO,
            # so a per-request priority override is honored without
            # reordering the tenant's own queue
            key = (PRIORITIES[st.q[0].priority], st.vtime, name)
            if best_key is None or key < best_key:
                best, best_key = st, key
        return best

    def requeue(self, item):
        """Put a popped item back at the FRONT of its tenant queue and
        roll back the pop's accounting (dispatch found no engine room)."""
        with self._lock:
            st = self._state_locked(item.tenant)
            st.q.appendleft(item)
            st.vtime -= item.cost / st.cfg.weight
            st.in_flight -= 1
            st.inflight_cost -= item.cost
            self._cv.notify()

    def release(self, tenant: str, cost: float):
        """A popped item finished on the engine side: free the tenant's
        concurrency unit and retire its in-flight cost."""
        with self._lock:
            st = self._state_locked(tenant)
            st.in_flight = max(0, st.in_flight - 1)
            st.inflight_cost = max(0.0, st.inflight_cost - float(cost))
            self._cv.notify()

    # -- introspection -------------------------------------------------------
    def _depth_locked(self) -> int:
        return sum(len(st.q) for st in self._tenants.values())

    def depth(self) -> int:
        with self._lock:
            return self._depth_locked()

    def depths(self) -> dict:
        """{tenant: {queued, in_flight, vtime, enqueued, rejected}}."""
        with self._lock:
            return {name: {"queued": len(st.q), "in_flight": st.in_flight,
                           "vtime": round(st.vtime, 3),
                           "enqueued": st.enqueued_total,
                           "rejected": st.rejected_total}
                    for name, st in self._tenants.items()}

    def backlog_cost(self, priority: str) -> float:
        """Token-cost of work that would run BEFORE a new request of
        `priority`: queued items at the same or higher class plus ALL
        in-flight cost (the shed layer's queue-ahead term)."""
        tier = PRIORITIES[priority]
        with self._lock:
            total = 0.0
            for st in self._tenants.values():
                total += st.inflight_cost
                total += sum(i.cost for i in st.q
                             if PRIORITIES[i.priority] <= tier)
            return total

    # -- shutdown ------------------------------------------------------------
    def drain(self) -> list:
        """Remove and return every queued item (shutdown: the gateway
        fails them with 503)."""
        with self._lock:
            out = []
            for st in self._tenants.values():
                out.extend(st.q)
                st.q.clear()
            return out

    def close(self):
        with self._lock:
            self._closed = True
            self._cv.notify_all()
