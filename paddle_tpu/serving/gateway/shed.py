"""Load shedding — telemetry-driven reject-early with Retry-After.

The engine already *measures* what an admission decision needs: every
finished request carries its TTFT and per-token decode latencies (the
series behind ``paddle_tpu_serving_ttft_seconds`` /
``_token_seconds``).  This module folds those observations into two EWMAs
and turns "queue depth" into "estimated time-to-first-token":

    est_ttft = prefill_ewma + token_ewma * backlog_tokens / total_slots

where ``backlog_tokens`` is the token-cost of work that would run before
the new request (queued at same-or-higher priority + all in-flight; see
``FairShareScheduler.backlog_cost``) and ``total_slots`` is the router's
aggregate decode parallelism — the pool retires ~``total_slots`` tokens
per decode step, so backlog drains at ``total_slots / token_ewma``
tokens/s.

A request carrying ``deadline_ms`` whose estimate blows the deadline is
rejected AT ADMISSION with a structured 429 + ``Retry-After`` — the
polite failure — instead of riding the queue just to deadline-expire
inside the engine after burning a slot (the rude one).  With no
observations yet (cold start) everything is admitted: the first requests
teach the model.
"""
from __future__ import annotations

import threading

__all__ = ["ShedDecision", "LoadShedder"]


class ShedDecision:
    __slots__ = ("admit", "est_ttft_s", "retry_after_s", "reason")

    def __init__(self, admit: bool, est_ttft_s: float | None,
                 retry_after_s: float = 0.0, reason: str = ""):
        self.admit = admit
        self.est_ttft_s = est_ttft_s
        self.retry_after_s = retry_after_s
        self.reason = reason


class LoadShedder:
    """EWMA latency model + the shed decision.  Thread-safe: handler
    threads call :meth:`decide`, the dispatcher calls :meth:`observe`."""

    def __init__(self, alpha: float = 0.2, *,
                 margin: float = 1.0):
        if not 0 < alpha <= 1:
            raise ValueError("alpha must be in (0, 1]")
        self._alpha = float(alpha)
        # margin scales the estimate before comparing to the deadline:
        # >1 sheds earlier (pessimistic), <1 later
        self.margin = float(margin)
        self._lock = threading.Lock()
        self._prefill_s: float | None = None
        self._token_s: float | None = None
        self._observations = 0

    # -- model updates -------------------------------------------------------
    def seed(self, prefill_s: float, token_s: float):
        """Prime the EWMAs (bench warmup / tests); later observations
        still blend in."""
        with self._lock:
            self._prefill_s = float(prefill_s)
            self._token_s = float(token_s)
            self._observations += 1

    def observe(self, ttft_s: float | None, token_latencies_s):
        """Fold one request's engine-side latency telemetry in.

        The two EWMAs have different natural feeding points, and feeding
        both at handle reap was a real bug: a burst of long-running
        requests finished nothing for their whole decode, so
        ``est_ttft`` ran on stale (or cold) numbers exactly when the
        shed decision mattered most.  The gateway therefore feeds the
        prefill EWMA at PREFILL COMPLETION (:meth:`observe_prefill`,
        fired when a request's first token streams — the journey phase
        boundary) and the token EWMA at reap
        (:meth:`observe_tokens`, when the per-token series is
        complete).  This combined form remains for tests/seeding."""
        toks = [t for t in (token_latencies_s or ()) if t > 0]
        with self._lock:
            a = self._alpha
            if ttft_s is not None and ttft_s > 0:
                self._prefill_s = (ttft_s if self._prefill_s is None else
                                   (1 - a) * self._prefill_s + a * ttft_s)
            if toks:
                mean = sum(toks) / len(toks)
                self._token_s = (mean if self._token_s is None else
                                 (1 - a) * self._token_s + a * mean)
            self._observations += 1

    def observe_prefill(self, ttft_s: float | None):
        """Feed the prefill EWMA the moment a request's first token
        exists — long-running requests update the model mid-flight
        instead of only at completion (the stale-estimate fix)."""
        self.observe(ttft_s, None)

    def observe_tokens(self, token_latencies_s):
        """Feed the token EWMA a finished request's per-token series."""
        self.observe(None, token_latencies_s)

    # -- estimates -----------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {"prefill_s": self._prefill_s, "token_s": self._token_s,
                    "observations": self._observations}

    def estimate_ttft(self, backlog_tokens: float,
                      total_slots: int) -> float | None:
        """Estimated TTFT for a request joining now; None while cold."""
        with self._lock:
            prefill, token = self._prefill_s, self._token_s
        if token is None:
            return None
        return (prefill or 0.0) + \
            token * float(backlog_tokens) / max(1, int(total_slots))

    def decide(self, deadline_s: float | None, backlog_tokens: float,
               total_slots: int) -> ShedDecision:
        """Admit unless the request names a deadline the estimate blows.
        Retry-After = how long until the backlog drains enough for the
        same request to fit its deadline."""
        est = self.estimate_ttft(backlog_tokens, total_slots)
        if deadline_s is None or est is None:
            return ShedDecision(True, est)
        if est * self.margin <= deadline_s:
            return ShedDecision(True, est)
        retry = max(0.1, round(est * self.margin - deadline_s, 2))
        return ShedDecision(
            False, est, retry_after_s=retry,
            reason=(f"estimated TTFT {est * 1e3:.0f}ms exceeds deadline "
                    f"{deadline_s * 1e3:.0f}ms "
                    f"(backlog {backlog_tokens:.0f} tokens over "
                    f"{total_slots} slots)"))
