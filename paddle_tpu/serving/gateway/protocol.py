"""Wire protocol — OpenAI-compatible completions parsing and framing.

Pure functions over bytes/dicts: no engine, no threads, no jax.  The HTTP
layer (http.py) calls :func:`parse_completion_request` on the raw body and
maps :class:`ProtocolError` to a structured 4xx; the response builders
emit the OpenAI completions JSON shape so stock clients
(``openai.Completion``-era, ``curl`` recipes, load generators) speak to
the gateway unchanged.

Extensions beyond the OpenAI schema (all optional, ignored by stock
clients): ``top_k`` (the engine's sampler knob), ``seed`` (per-request
sampling seed), ``deadline_ms`` (end-to-end SLO — the shed layer rejects
early when the TTFT estimate blows it), ``priority``
(``interactive | standard | batch``), and integer ``stop`` (an eos token
id; the engine is tokenizer-optional so string stop sequences are only
accepted when a tokenizer is attached).
"""
from __future__ import annotations

import json
import time

__all__ = ["ProtocolError", "CompletionRequest", "PRIORITIES",
           "parse_completion_request", "parse_chat_request",
           "tenant_from_headers",
           "completion_body", "chunk_body", "chat_completion_body",
           "chat_chunk_body", "sse_event", "SSE_DONE",
           "error_body"]

# priority classes, strictly ordered: a lower value preempts a higher one
# in the fair-share scheduler (admission.py)
PRIORITIES = {"interactive": 0, "standard": 1, "batch": 2}

_MAX_BODY_BYTES = 1 << 20          # 1 MiB request-body cap (413 beyond)


class ProtocolError(Exception):
    """A request the wire layer rejects — carries the HTTP status and the
    OpenAI-style error object fields."""

    def __init__(self, status: int, message: str, *, code: str | None = None,
                 etype: str = "invalid_request_error",
                 param: str | None = None):
        super().__init__(message)
        self.status = int(status)
        self.code = code
        self.etype = etype
        self.param = param

    def body(self) -> dict:
        return error_body(str(self), etype=self.etype, code=self.code,
                          param=self.param)


def error_body(message: str, *, etype: str = "invalid_request_error",
               code: str | None = None, param: str | None = None) -> dict:
    """The OpenAI error envelope: ``{"error": {...}}``."""
    return {"error": {"message": message, "type": etype,
                      "param": param, "code": code}}


class CompletionRequest:
    """Validated /v1/completions payload (wire form; the gateway resolves
    string prompts to ids with the engine's tokenizer)."""

    __slots__ = ("prompt", "max_tokens", "temperature", "top_k", "seed",
                 "stream", "stop", "deadline_s", "priority", "model",
                 "conversation", "chat")

    def __init__(self, prompt, max_tokens, temperature, top_k, seed,
                 stream, stop, deadline_s, priority, model,
                 conversation=None, chat=False):
        self.prompt = prompt              # str | list[int]
        self.max_tokens = max_tokens
        self.temperature = temperature
        self.top_k = top_k
        self.seed = seed
        self.stream = stream
        self.stop = stop                  # int eos id | str | None
        self.deadline_s = deadline_s
        self.priority = priority          # key of PRIORITIES | None
        self.model = model
        self.conversation = conversation  # prefix-cache namespace id
        self.chat = chat                  # respond in chat.completion shape


def _field(payload: dict, name: str, types, default, *, validate=None):
    v = payload.get(name, default)
    if v is default:
        return default
    if not isinstance(v, types) or isinstance(v, bool) and bool not in (
            types if isinstance(types, tuple) else (types,)):
        raise ProtocolError(
            400, f"'{name}' must be of type "
            f"{getattr(types, '__name__', types)}", param=name,
            code="invalid_type")
    if validate is not None and not validate(v):
        raise ProtocolError(400, f"'{name}' is out of range", param=name,
                            code="out_of_range")
    return v


def parse_completion_request(raw: bytes, *, has_tokenizer: bool
                             ) -> CompletionRequest:
    """bytes -> validated CompletionRequest; raises ProtocolError (400/413)
    on anything malformed.  Unknown fields are ignored (OpenAI-tolerant)."""
    if len(raw) > _MAX_BODY_BYTES:
        raise ProtocolError(413, "request body exceeds 1 MiB",
                            code="body_too_large")
    try:
        payload = json.loads(raw.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ProtocolError(400, f"request body is not valid JSON: {e}",
                            code="invalid_json") from e
    if not isinstance(payload, dict):
        raise ProtocolError(400, "request body must be a JSON object",
                            code="invalid_json")

    prompt = payload.get("prompt")
    if prompt is None:
        raise ProtocolError(400, "'prompt' is required", param="prompt",
                            code="missing_field")
    if isinstance(prompt, str):
        if not has_tokenizer:
            raise ProtocolError(
                400, "string prompts need a tokenizer on the serving side; "
                "send a list of token ids", param="prompt",
                code="no_tokenizer")
        if not prompt:
            raise ProtocolError(400, "'prompt' is empty", param="prompt",
                                code="empty_prompt")
    elif isinstance(prompt, list):
        if not prompt or not all(
                isinstance(t, int) and not isinstance(t, bool) and t >= 0
                for t in prompt):
            raise ProtocolError(
                400, "'prompt' must be a non-empty list of non-negative "
                "token ids (or a string, with a tokenizer)",
                param="prompt", code="invalid_prompt")
    else:
        raise ProtocolError(400, "'prompt' must be a string or a list of "
                            "token ids", param="prompt", code="invalid_type")

    max_tokens = _field(payload, "max_tokens", int, 16,
                        validate=lambda v: 1 <= v <= 1 << 20)
    temperature = _field(payload, "temperature", (int, float), 0.0,
                         validate=lambda v: v >= 0)
    top_k = _field(payload, "top_k", int, 0, validate=lambda v: v >= 0)
    seed = _field(payload, "seed", int, 0)
    stream = _field(payload, "stream", bool, False)
    model = _field(payload, "model", str, None)

    stop = payload.get("stop")
    if stop is not None:
        if isinstance(stop, list) and len(stop) == 1:
            stop = stop[0]
        if isinstance(stop, bool) or not isinstance(stop, (int, str)):
            raise ProtocolError(
                400, "'stop' must be a token id (int) or, with a "
                "tokenizer, a string", param="stop", code="invalid_type")
        if isinstance(stop, str) and not has_tokenizer:
            raise ProtocolError(
                400, "string 'stop' needs a tokenizer on the serving side",
                param="stop", code="no_tokenizer")

    deadline_ms = _field(payload, "deadline_ms", (int, float), None,
                         validate=lambda v: v > 0)
    priority = payload.get("priority")
    if priority is not None and priority not in PRIORITIES:
        raise ProtocolError(
            400, f"'priority' must be one of {sorted(PRIORITIES)}",
            param="priority", code="invalid_priority")
    conversation = _field(payload, "conversation", str, None,
                          validate=lambda v: 0 < len(v) <= 256)

    return CompletionRequest(
        prompt=prompt, max_tokens=int(max_tokens),
        temperature=float(temperature), top_k=int(top_k), seed=int(seed),
        stream=bool(stream), stop=stop,
        deadline_s=None if deadline_ms is None else float(deadline_ms) / 1e3,
        priority=priority, model=model, conversation=conversation)


def parse_chat_request(raw: bytes, *, has_tokenizer: bool
                       ) -> CompletionRequest:
    """bytes -> validated /v1/chat/completions request.

    The chat surface is the conversation-first door (docs/serving.md "KV
    tiering & conversations"): ``messages`` flatten to one prompt and an
    optional ``conversation`` id namespaces the prefix cache so turn
    N+1 of the same conversation re-uses turn N's KV.  Flattening is
    deliberately trivial — role header + content per message — because
    the engine is tokenizer-optional: string contents need a tokenizer
    (they flatten to one string), while integer-list contents
    concatenate tokenizer-free (the load generator / capture-replay
    form).  Mixing the two in one request is a 400.  Everything else
    (sampling, deadline, priority, stream) parses exactly like
    /v1/completions; the returned request carries ``chat=True`` so the
    HTTP layer frames responses as ``chat.completion[.chunk]``.
    """
    if len(raw) > _MAX_BODY_BYTES:
        raise ProtocolError(413, "request body exceeds 1 MiB",
                            code="body_too_large")
    try:
        payload = json.loads(raw.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ProtocolError(400, f"request body is not valid JSON: {e}",
                            code="invalid_json") from e
    if not isinstance(payload, dict):
        raise ProtocolError(400, "request body must be a JSON object",
                            code="invalid_json")
    msgs = payload.get("messages")
    if not isinstance(msgs, list) or not msgs:
        raise ProtocolError(400, "'messages' must be a non-empty list",
                            param="messages", code="missing_field")
    parts, kinds = [], set()
    for i, m in enumerate(msgs):
        if not isinstance(m, dict) or not isinstance(m.get("role"), str) \
                or not m["role"]:
            raise ProtocolError(
                400, f"messages[{i}] needs a string 'role'",
                param="messages", code="invalid_message")
        content = m.get("content")
        if isinstance(content, str) and content:
            kinds.add("str")
            parts.append((m["role"], content))
        elif (isinstance(content, list) and content and all(
                isinstance(t, int) and not isinstance(t, bool) and t >= 0
                for t in content)):
            kinds.add("ids")
            parts.append((m["role"], content))
        else:
            raise ProtocolError(
                400, f"messages[{i}].content must be a non-empty string "
                "or a non-empty list of non-negative token ids",
                param="messages", code="invalid_message")
    if len(kinds) > 1:
        raise ProtocolError(
            400, "messages must be all-string or all-token-ids, not "
            "mixed", param="messages", code="invalid_message")
    if "str" in kinds:
        if not has_tokenizer:
            raise ProtocolError(
                400, "string message contents need a tokenizer on the "
                "serving side; send token-id lists", param="messages",
                code="no_tokenizer")
        # deterministic flattening: identical histories produce the
        # IDENTICAL prompt string, byte for byte — that equality is what
        # the prefix cache keys on, so format drift = cache miss
        prompt = "".join(f"<|{role}|>{content}\n"
                         for role, content in parts) + "<|assistant|>"
    else:
        prompt = [t for _, content in parts for t in content]

    body = dict(payload)
    body["prompt"] = prompt
    body.pop("messages", None)
    creq = parse_completion_request(
        json.dumps(body).encode("utf-8"), has_tokenizer=has_tokenizer)
    creq.chat = True
    return creq


def tenant_from_headers(headers, api_keys: dict | None = None) -> str:
    """Resolve the tenant identity for one request.

    With an ``api_keys`` map ({key: tenant}) the gateway is in strict
    mode: an unknown/missing key is a 401.  Without one, the bearer
    token / ``X-Api-Key`` / ``X-Tenant`` header names the tenant directly
    (first match wins) and unauthenticated requests fall into the
    ``anonymous`` tenant — every tenant still gets its own fair-share
    queue either way.
    """
    auth = headers.get("Authorization") or ""
    key = auth[7:].strip() if auth.startswith("Bearer ") else \
        (headers.get("X-Api-Key") or "").strip()
    if api_keys is not None:
        tenant = api_keys.get(key)
        if not key or tenant is None:
            raise ProtocolError(
                401, "missing or unknown API key",
                etype="authentication_error", code="invalid_api_key")
        return tenant
    return (headers.get("X-Tenant") or "").strip() or key or "anonymous"


# -- response builders --------------------------------------------------------

def _choice(text: str, token_ids, finish_reason):
    return {"text": text, "index": 0, "logprobs": None,
            "finish_reason": finish_reason, "token_ids": list(token_ids)}


def completion_body(req_id: str, model: str, text: str, token_ids,
                    finish_reason: str, prompt_tokens: int,
                    request_id: str | None = None) -> dict:
    """``request_id`` is the journey id (adopted ``X-Request-Id``) —
    echoed in the body next to the response header so log pipelines can
    correlate without header access."""
    n = len(token_ids)
    out = {
        "id": req_id, "object": "text_completion",
        "created": int(time.time()), "model": model,
        "choices": [_choice(text, token_ids, finish_reason)],
        "usage": {"prompt_tokens": int(prompt_tokens),
                  "completion_tokens": n,
                  "total_tokens": int(prompt_tokens) + n},
    }
    if request_id is not None:
        out["request_id"] = request_id
    return out


def chunk_body(req_id: str, model: str, text: str, token_ids,
               finish_reason: str | None,
               request_id: str | None = None) -> dict:
    """One streamed delta (an SSE ``data:`` payload).  The finish event
    (``finish_reason`` set) carries ``request_id`` — the journey id a
    client quotes at ``GET /debug/requests/<id>``."""
    out = {"id": req_id, "object": "text_completion",
           "created": int(time.time()), "model": model,
           "choices": [_choice(text, token_ids, finish_reason)]}
    if request_id is not None:
        out["request_id"] = request_id
    return out


def chat_completion_body(req_id: str, model: str, text: str, token_ids,
                         finish_reason: str, prompt_tokens: int,
                         request_id: str | None = None,
                         conversation: str | None = None) -> dict:
    """The ``chat.completion`` envelope: the completion payload framed
    as one assistant message.  ``conversation`` is echoed so a client
    can confirm which prefix-cache namespace served it."""
    n = len(token_ids)
    out = {
        "id": req_id, "object": "chat.completion",
        "created": int(time.time()), "model": model,
        "choices": [{"index": 0, "logprobs": None,
                     "finish_reason": finish_reason,
                     "message": {"role": "assistant", "content": text,
                                 "token_ids": list(token_ids)}}],
        "usage": {"prompt_tokens": int(prompt_tokens),
                  "completion_tokens": n,
                  "total_tokens": int(prompt_tokens) + n},
    }
    if request_id is not None:
        out["request_id"] = request_id
    if conversation is not None:
        out["conversation"] = conversation
    return out


def chat_chunk_body(req_id: str, model: str, text: str, token_ids,
                    finish_reason: str | None,
                    request_id: str | None = None,
                    conversation: str | None = None) -> dict:
    """One streamed ``chat.completion.chunk`` delta."""
    out = {"id": req_id, "object": "chat.completion.chunk",
           "created": int(time.time()), "model": model,
           "choices": [{"index": 0, "finish_reason": finish_reason,
                        "delta": ({"role": "assistant", "content": text,
                                   "token_ids": list(token_ids)}
                                  if finish_reason is None or token_ids
                                  else {})}]}
    if request_id is not None:
        out["request_id"] = request_id
    if conversation is not None:
        out["conversation"] = conversation
    return out


def sse_event(payload: dict) -> bytes:
    return b"data: " + json.dumps(payload).encode("utf-8") + b"\n\n"


SSE_DONE = b"data: [DONE]\n\n"
