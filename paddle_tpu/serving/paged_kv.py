"""PageAllocator — host-side bookkeeping for the block-granular KV pool.

The dense slot pool provisions ``[max_slots+1, max_len, ...]`` HBM rows:
every slot pays for the worst-case sequence whether or not it uses it,
and the prefix cache can only share whole rows by device copy.  The
paged pool (ROADMAP item 3, the vLLM PagedAttention arrangement) stores
K/V in fixed-size **pages** of ``page_size`` positions —
``[num_pages, page_size, heads, head_dim]`` per layer — and each slot
carries an int32 **page table** mapping its virtual positions onto
physical pages.  HBM then scales with the tokens actually resident:

* short requests hold few pages, so a heavy-tail traffic mix fits many
  more concurrent sequences in the same bytes;
* a sequence grows past the dense pool's compiled ``max_len`` by simply
  owning more table entries (the decode program's shapes depend on
  ``num_pages`` and the table width, not on a per-slot row length);
* a prefix-cache hit shares the cached pages **by reference** —
  refcount++ per page instead of a bitwise device row copy — with
  copy-on-write when a writer's frontier lands inside a shared page.

This class owns the *index* side only: the free list and per-page
refcounts.  Purely host-side and engine-lock-protected by the caller;
no device arrays live here (the page id is the pointer into the
engine's pool buffers).  Pages are refcounted because one physical page
can back several readers at once — a prefix-cache entry plus any number
of in-flight requests that hit on it; a page returns to the free list
only when its last reference is dropped.
"""
from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional

__all__ = ["PageAllocator"]


class PageAllocator:
    """Fixed pool of ``num_pages`` KV pages with refcounted alloc/free.

    ``alloc(n)`` is all-or-nothing: it returns ``n`` page ids or None
    when fewer than ``n`` are free (admission leaves the request queued
    — page exhaustion is backpressure, never a partial allocation to
    unwind).  ``share`` adds a reference to a resident page (prefix
    sharing); ``deref`` drops one and frees the page at refcount 0.
    Double ``deref`` of a free page raises (the double-free guard).
    """

    def __init__(self, num_pages: int, page_size: int):
        if int(num_pages) < 1:
            raise ValueError(f"num_pages must be >= 1, got {num_pages}")
        if int(page_size) < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self._free: deque = deque(range(self.num_pages))
        self._refs: Dict[int, int] = {}
        self.alloc_total = 0
        self.share_total = 0
        self.free_total = 0
        # device bytes one physical page occupies across every layer's
        # K/V (+ scale) pool buffers — set by the engine after it builds
        # the pools; 0 until then.  Pure accounting (the HBM-ledger
        # prefix_cache sub-owner multiplies cached pages by this).
        self.bytes_per_page = 0

    # -- allocation ----------------------------------------------------------
    def alloc(self, n: int = 1) -> Optional[List[int]]:
        """Claim ``n`` pages (refcount 1 each); None when fewer than ``n``
        are free — all-or-nothing, so the caller never holds a partial
        grant it would have to unwind."""
        n = int(n)
        if n < 0:
            raise ValueError(f"cannot alloc {n} pages")
        if n > len(self._free):
            return None
        pages = [self._free.popleft() for _ in range(n)]
        for p in pages:
            self._refs[p] = 1
        self.alloc_total += n
        return pages

    def share(self, page: int) -> int:
        """Add a reference to a resident page (a prefix-cache hit mapping
        the page into another slot's table); returns the new refcount.
        KeyError on a page that is not allocated."""
        self._refs[page] += 1          # KeyError: page is free
        self.share_total += 1
        return self._refs[page]

    def deref(self, page: int) -> bool:
        """Drop one reference; returns True when this was the last one
        and the page went back to the free list.  KeyError on a page
        that is not allocated (double-free guard)."""
        refs = self._refs[page] - 1    # KeyError: already free
        if refs > 0:
            self._refs[page] = refs
            return False
        del self._refs[page]
        self._free.append(page)
        self.free_total += 1
        return True

    def refs(self, page: int) -> int:
        """Current refcount (0 for a free page)."""
        return self._refs.get(page, 0)

    # -- introspection -------------------------------------------------------
    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_used(self) -> int:
        return self.num_pages - len(self._free)

    @property
    def used_bytes(self) -> int:
        """Pool bytes backing allocated pages (``bytes_per_page`` must
        have been set by the pool owner)."""
        return self.n_used * self.bytes_per_page

    def check(self) -> None:
        """Internal-consistency assert (chaos/teardown leak check): every
        tracked page has refs >= 1, and tracked + free partitions the
        pool exactly.  Raises AssertionError on a leak or a corruption."""
        assert all(r >= 1 for r in self._refs.values()), self._refs
        assert len(self._refs) + len(self._free) == self.num_pages, (
            len(self._refs), len(self._free), self.num_pages)
        assert not (set(self._refs) & set(self._free))

    def __repr__(self):
        return (f"PageAllocator(num_pages={self.num_pages}, "
                f"page_size={self.page_size}, free={self.n_free}, "
                f"used={self.n_used}, allocs={self.alloc_total}, "
                f"shares={self.share_total})")
