"""EngineSupervisor — detect a dead engine, rebuild it, re-dispatch the
work that is still duplication-safe.

PR 5 gave the *training* tier preemption safety; this module is the
serving analogue.  An :class:`~paddle_tpu.serving.engine.Engine` whose
scheduler crashes (or whose decode stalls inside an XLA call) is
fail-stop by design — restarting the loop over an already-failed pool
would serve garbage.  The supervisor therefore restarts *around* it:

* **detect** — a monitor thread polls ``Engine.health()``; ``dead: True``
  is a crash, and a frozen ``progress_age_s`` with work pending past
  ``stall_timeout_s`` is a decode stall (the engine is then
  :meth:`~paddle_tpu.serving.engine.Engine.abandon`-ed, which classifies
  its requests exactly like a crash).
* **rebuild** — the old engine is torn down and a FRESH engine + slot
  pool is built from the same model/config via the caller's ``factory``;
  each build compiles its own single decode signature (asserted by the
  chaos lane through the retrace sentinel).
* **re-dispatch** — the dying engine offers its zero-tokens-emitted
  requests (queued or active) to the supervisor through the engine's
  ``redispatch_hook``; the supervisor parks them and re-enqueues the
  SAME handles into the rebuilt engine, so callers blocked on
  ``result()`` never notice.  Requests that already streamed tokens are
  never replayed — they fail with the typed ``RequestInterruptedError``
  (the retry-safety rule: retryable iff nothing reached a consumer).

Restart attempts are budgeted (``max_restarts`` per
``restart_window_s``); past the budget the supervisor gives up, fails
the parked requests with ``EngineDeadError`` and advertises not-alive so
a router stops picking the replica.

The supervisor is Engine-shaped (``submit/load/health/drain/shutdown``
proxy to the CURRENT engine), so an ``EngineRouter`` can hold one
wherever it held an engine::

    sup = EngineSupervisor(lambda: Engine(model, max_slots=8),
                           name="engine0", stall_timeout_s=30.0)
    stack = start_gateway([sup], own_engines=True)

During the death->rebuild window ``load()`` advertises the replica as
alive-with-zero-headroom, so the gateway's dispatcher *waits* for the
rebuild instead of failing queued work fast (the all-dead 503 path is
reserved for replicas that are genuinely gone).
"""
from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from ..observability import flight, registry
from ..testing import faults
from .engine import (Engine, EngineClosedError, EngineDeadError,
                     EngineStalledError, QueueFullError)

__all__ = ["EngineSupervisor", "SERVING_RESTARTS"]

SERVING_RESTARTS = "paddle_tpu_serving_engine_restarts_total"


class EngineSupervisor:
    """Self-healing wrapper around one Engine replica (see module doc).

    Args:
        factory: zero-arg callable returning a fresh :class:`Engine`
            built from the same model/config — called once at
            construction and once per restart.
        name: replica name used in metrics/flight events.
        max_restarts: restart budget inside ``restart_window_s``; one
            more death past it makes the supervisor give up.
        restart_window_s: sliding window for the restart budget.
        poll_interval_s: monitor thread poll period.
        stall_timeout_s: declare a stall (and abandon the engine) when
            the scheduler makes no progress for this long with work
            pending; None disables stall detection (crashes are still
            caught).  Only armed once the build is WARM (decode program
            compiled) — cold engines legitimately sit in multi-second
            compiles — so the bound only has to exceed a steady-state
            dispatch.  Read per poll: operators may set/clear it at
            runtime.
        max_redispatch: per-request cap on supervisor re-dispatches; a
            request dying more often than this fails with
            ``EngineDeadError`` instead of looping forever.
    """

    def __init__(self, factory: Callable[[], Engine], *,
                 name: str = "engine", max_restarts: int = 3,
                 restart_window_s: float = 60.0,
                 poll_interval_s: float = 0.05,
                 stall_timeout_s: Optional[float] = None,
                 max_redispatch: int = 2):
        self.factory = factory
        self.name = str(name)
        self.max_restarts = int(max_restarts)
        self.restart_window_s = float(restart_window_s)
        self.poll_interval_s = float(poll_interval_s)
        self.stall_timeout_s = (None if stall_timeout_s is None
                                else float(stall_timeout_s))
        self.max_redispatch = int(max_redispatch)
        self._lock = threading.Lock()
        self._stop_ev = threading.Event()
        self._wake_ev = threading.Event()
        self._parked: List = []
        self._restart_times: List[float] = []
        self._failed: Optional[BaseException] = None
        self._restarting = False
        self._restarts = 0
        self._redispatched = 0
        self._build_stats: List[dict] = []
        self._engine = self._attach(factory())
        self._thread = threading.Thread(
            target=self._watch, name=f"paddle-tpu-supervisor-{self.name}",
            daemon=True)
        self._thread.start()

    def _attach(self, eng: Engine) -> Engine:
        eng.redispatch_hook = self._take_requests
        return eng

    # -- redispatch hook (runs on the dying engine's thread) -----------------
    def _take_requests(self, requests, cause):
        """Engine death callback: take ownership of the zero-token
        requests still inside the re-dispatch budget; they are parked
        until the rebuilt engine exists."""
        taken = []
        now = time.perf_counter()
        with self._lock:
            if self._stop_ev.is_set() or self._failed is not None:
                return taken
            for req in requests:
                if req.redispatches < self.max_redispatch:
                    req._park_t0 = now    # journey rebuild-phase start
                    taken.append(req)
            self._parked.extend(taken)
        if taken:
            flight.record("supervisor", "park", engine=self.name,
                          n=len(taken), error=type(cause).__name__,
                          requests=",".join(str(r.request_id)
                                            for r in taken))
        self._wake_ev.set()
        return taken

    # -- monitor thread ------------------------------------------------------
    def _watch(self):
        while not self._stop_ev.is_set():
            with self._lock:
                eng = self._engine
            h = eng.health()
            if h["dead"]:
                self._restart(eng)
            elif (self.stall_timeout_s is not None and h["alive"] and
                  h["scheduler_running"] and h["warm"] and
                  (h["active_slots"] or h["queue_depth"]) and
                  h["progress_age_s"] > self.stall_timeout_s):
                flight.record("supervisor", "stall", engine=self.name,
                              progress_age_s=round(h["progress_age_s"], 3))
                eng.abandon(EngineStalledError(
                    f"engine {self.name!r}: no scheduler progress for "
                    f"{h['progress_age_s']:.2f}s with work pending "
                    f"(stall_timeout_s={self.stall_timeout_s})"))
                self._restart(eng)
            self._wake_ev.wait(self.poll_interval_s)
            self._wake_ev.clear()

    def _restart(self, old: Engine):
        """Tear down the dead engine, rebuild, re-enqueue parked work."""
        now = time.monotonic()
        with self._lock:
            if self._failed is not None or self._stop_ev.is_set():
                return
            self._restart_times = [
                t for t in self._restart_times
                if now - t < self.restart_window_s]
            over_budget = len(self._restart_times) >= self.max_restarts
            if over_budget:
                self._failed = RuntimeError(
                    f"supervisor {self.name!r}: restart budget exhausted "
                    f"({self.max_restarts} restarts in "
                    f"{self.restart_window_s:g}s)")
                parked, self._parked = self._parked, []
            else:
                self._restart_times.append(now)
                self._restarting = True
                if not getattr(old, "_supervisor_retired", False):
                    old._supervisor_retired = True
                    self._build_stats.append(old.compile_stats())
        if over_budget:
            cause = old._dead or self._failed
            flight.record("supervisor", "giveup", engine=self.name,
                          failed_requests=len(parked),
                          requests=",".join(str(r.request_id)
                                            for r in parked),
                          error=f"{type(cause).__name__}: {cause}")
            for req in parked:
                req._finish(EngineDeadError(cause))
            return
        flight.record("supervisor", "teardown", engine=self.name,
                      error=(None if old._dead is None
                             else f"{type(old._dead).__name__}: "
                                  f"{old._dead}"))
        try:
            old.shutdown()
        except Exception:  # noqa: BLE001 — the old engine is expendable
            pass
        try:
            faults.fault_point("serving.rebuild", engine=self.name)
            new = self._attach(self.factory())
        except Exception as e:  # noqa: BLE001 — retry on the next poll
            flight.record("supervisor", "rebuild_failed", engine=self.name,
                          error=f"{type(e).__name__}: {e}")
            with self._lock:
                self._restarting = False
            return          # the monitor sees the engine still dead and
            #                 tries again; the budget bounds the retries
        with self._lock:
            self._engine = new
            parked, self._parked = self._parked, []
            self._restarting = False
            self._restarts += 1
            restarts = self._restarts
        requeued = 0
        requeued_ids = []
        for req in parked:
            try:
                new.resubmit(req)
                requeued += 1
                requeued_ids.append(req.request_id)
                if req.journey is not None:
                    # the death -> rebuilt-engine window, attributed: the
                    # SAME journey id keeps accumulating phases on the
                    # fresh build (chaos-asserted continuity)
                    t_park = getattr(req, "_park_t0",
                                     time.perf_counter())
                    req.journey.phase(
                        "rebuild", t_park,
                        time.perf_counter() - t_park,
                        engine=self.name, restart=restarts)
            except Exception as e:  # noqa: BLE001 — never strand a handle
                req._finish(e if isinstance(e, EngineDeadError)
                            else EngineDeadError(e))
        with self._lock:
            self._redispatched += requeued
        try:
            new.start()
        except Exception:  # noqa: BLE001 — died instantly; next poll retries
            pass
        registry().counter(
            SERVING_RESTARTS,
            "engine rebuilds performed by a supervisor").inc(
            1.0, labels={"engine": self.name})
        flight.record("supervisor", "restart", engine=self.name,
                      restarts=restarts, redispatched=requeued,
                      requests=",".join(map(str, requeued_ids)))

    # -- engine-shaped surface -----------------------------------------------
    @property
    def engine(self) -> Engine:
        """The CURRENT engine build (changes across restarts)."""
        with self._lock:
            return self._engine

    @property
    def tokenizer(self):
        return self.engine.tokenizer

    @property
    def adapter_registry(self):
        """The PERSISTENT adapter registry (same object across rebuilds:
        the factory hands it to every build; only residency is fresh)."""
        return self.engine.adapter_registry

    @property
    def max_len(self) -> int:
        return self.engine.max_len

    @property
    def max_slots(self) -> int:
        return self.engine.max_slots

    @property
    def restarts(self) -> int:
        with self._lock:
            return self._restarts

    @property
    def redispatched(self) -> int:
        with self._lock:
            return self._redispatched

    @property
    def failed(self) -> Optional[BaseException]:
        with self._lock:
            return self._failed

    def builds(self) -> List[dict]:
        """compile_stats() of every RETIRED build plus the current one —
        the chaos lane asserts each build compiled exactly one decode
        signature."""
        with self._lock:
            eng = self._engine
            out = list(self._build_stats)
        out.append(eng.compile_stats())
        return out

    def submit(self, *args, **kwargs):
        with self._lock:
            eng, failed = self._engine, self._failed
        if failed is not None:
            raise EngineDeadError(failed)
        try:
            return eng.submit(*args, **kwargs)
        except EngineDeadError:
            if self.failed is not None:
                raise
            # between death and rebuild: this is backpressure, not a
            # permanent failure — callers retry exactly like a full queue
            raise QueueFullError(
                f"engine {self.name!r} is restarting; retry shortly") \
                from None

    def load(self) -> dict:
        with self._lock:
            eng, failed, stopped = (self._engine, self._failed,
                                    self._stop_ev.is_set())
        ld = eng.load()
        if failed is not None or stopped:
            ld["alive"] = False
        elif not ld["alive"] and not ld["draining"] and eng.health()["dead"]:
            # dead-but-supervised: the rebuild is imminent — advertise
            # alive with zero headroom so routers WAIT instead of
            # declaring the replica gone
            ld.update(alive=True, restarting=True,
                      slots_in_use=ld["max_slots"],
                      queue_depth=ld["max_queue"])
        return ld

    def health(self) -> dict:
        with self._lock:
            eng, failed, restarting = (self._engine, self._failed,
                                       self._restarting)
            restarts, stopped = self._restarts, self._stop_ev.is_set()
        h = eng.health()
        h["supervised"] = True
        h["restarts"] = restarts
        h["restarting"] = restarting or (
            h["dead"] and failed is None and not stopped)
        if failed is not None:
            h["alive"] = False
            h["supervisor_failed"] = f"{type(failed).__name__}: {failed}"
        return h

    def stats(self) -> dict:
        return self.engine.stats()

    def compile_stats(self) -> dict:
        return self.engine.compile_stats()

    def queue_depth(self) -> int:
        return self.engine.queue_depth()

    def slots_in_use(self) -> int:
        return self.engine.slots_in_use()

    def adapter_resident(self, name: str) -> bool:
        return self.engine.adapter_resident(name)

    def join(self, timeout: Optional[float] = None) -> bool:
        return self.engine.join(timeout)

    def drain(self, deadline_s: float = 30.0) -> bool:
        """Drain the current engine (no restarts happen past this point:
        drain is the graceful end of the replica's life)."""
        return self.engine.drain(deadline_s)

    def undrain(self):
        """Warm-pool route-in: un-park the current engine (see
        :meth:`Engine.undrain`)."""
        return self.engine.undrain()

    def shutdown(self):
        """Stop supervising and shut the current engine down; parked
        requests (mid-rebuild) fail with EngineClosedError."""
        self._stop_ev.set()
        self._wake_ev.set()
        with self._lock:
            eng = self._engine
            parked, self._parked = self._parked, []
        err = EngineClosedError("supervisor shut down")
        for req in parked:
            req._finish(err)
        eng.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=10)

    close = shutdown

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False

    def __repr__(self):
        with self._lock:
            state = ("failed" if self._failed is not None else
                     "restarting" if self._restarting else "ok")
        return (f"EngineSupervisor(name={self.name!r}, state={state}, "
                f"restarts={self.restarts})")
