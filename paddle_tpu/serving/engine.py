"""Continuous-batching serving engine over the static KV-cache decode path.

The GPT flagship already has the fast half of a serving stack: a
single-program decode step with donated fixed-shape cache buffers
(models/gpt.py static cache; the AnalysisPredictor zero-copy run analog).
What it lacked is the request level — this module adds it, in the shape
production LLM servers (vLLM/Orca-style continuous batching) converged on:

* a **slot pool**: ONE set of ``[max_slots+1, max_len, heads, head_dim]``
  per-layer cache buffers; each in-flight request owns a slot row, freed on
  completion and recycled for the next request (SlotPool).  Row max_slots
  is a scratch slot that absorbs prefill padding writes.
* a **scheduler loop** (daemon thread): each iteration sweeps
  cancellations/deadlines, admits queued requests into free slots with ONE
  batched prefill (prompts padded to a power-of-two bucket, so compile
  count stays logarithmic), then runs ONE batched decode step for ALL
  active slots — fixed shapes, so after the first iteration the decode is
  a single compiled program forever, regardless of request churn
  (asserted via the retrace sentinel's signature count).
* a **request/response API**: ``submit() -> RequestHandle`` (Future-style:
  ``result`` / ``done`` / ``cancel`` / ``exception``), per-token streaming
  callbacks, a bounded admission queue that rejects with
  :class:`QueueFullError` when full (backpressure), and per-request
  deadlines.
* **observability**: spans + flight events for admit/prefill/decode/evict,
  gauges for active slots and queue depth, histograms for time-to-first-
  token and per-token latency — all through the paddle_tpu.observability
  registry, live from request one.

Per-slot cache positions ride the models' static-cache protocol with a
VECTOR length: ``caches = [(k_buf, v_buf, lengths[B])]`` makes each row
write its new keys at its own offset and attend under a per-row validity
mask (models/gpt.py per-slot branch).

Thread-safety: the engine runs the model from its scheduler thread via the
functional state swap; do not run the same model's eager forward
concurrently with in-flight requests.
"""
from __future__ import annotations

import atexit
import itertools
import os
import threading
import time
import weakref
from collections import deque
from concurrent.futures import CancelledError
from typing import Callable, Optional

import numpy as np

from ..core.tensor import Tensor
from ..observability import flight, registry, span
from ..observability import watchdog as _watchdog
from ..observability.retrace import instrument_jit
from ..testing import faults
from .slot_pool import SlotPool

__all__ = ["Engine", "RequestHandle", "QueueFullError",
           "DeadlineExceededError", "EngineClosedError", "EngineDeadError",
           "EngineDrainingError", "EngineStalledError",
           "RequestInterruptedError"]

# -- metric names (paddle_tpu.observability registry) -------------------------
SERVING_ACTIVE_SLOTS = "paddle_tpu_serving_active_slots"
SERVING_QUEUE_DEPTH = "paddle_tpu_serving_queue_depth"
SERVING_REQUESTS = "paddle_tpu_serving_requests_total"
SERVING_TOKENS = "paddle_tpu_serving_tokens_total"
SERVING_TTFT = "paddle_tpu_serving_ttft_seconds"
SERVING_TOKEN_LATENCY = "paddle_tpu_serving_token_seconds"
SERVING_BATCH_SECONDS = "paddle_tpu_serving_batch_seconds"
SERVING_REDISPATCHED = "paddle_tpu_serving_requests_redispatched_total"
SERVING_INTERRUPTED = "paddle_tpu_serving_requests_interrupted_total"


class QueueFullError(RuntimeError):
    """Admission queue is at capacity — backpressure; retry later."""


class DeadlineExceededError(TimeoutError):
    """The request's deadline passed before it finished."""


class EngineClosedError(RuntimeError):
    """The engine was shut down with this request still in flight."""


class EngineDrainingError(EngineClosedError):
    """The engine is draining: no new admissions, in-flight work finishes
    (the graceful-shutdown analogue of QueueFullError — retry elsewhere)."""


class EngineDeadError(RuntimeError):
    """The scheduler thread crashed: the engine is permanently dead and
    rejects new work, naming the original exception — restarting the loop
    over an already-failed pool would serve garbage.  A request that had
    emitted ZERO tokens when the engine died also fails with this type
    (unless a supervisor re-dispatches it): the caller knows nothing
    reached any consumer, so a retry is duplication-safe."""

    def __init__(self, cause: BaseException):
        super().__init__(
            f"serving scheduler died: {type(cause).__name__}: {cause}")
        self.cause = cause


class EngineStalledError(RuntimeError):
    """The scheduler stopped making progress with work pending (decode
    hang): a supervisor declared the engine dead via :meth:`Engine.abandon`
    — the stuck thread cannot be killed, but the engine stops accepting
    work and its requests are classified exactly like a crash."""


class RequestInterruptedError(RuntimeError):
    """The engine died AFTER this request streamed token(s): replaying it
    elsewhere would duplicate tokens already delivered, so instead of a
    silent re-run the caller gets this typed error naming how far the
    stream got and the underlying engine failure."""

    def __init__(self, request_id: int, tokens_streamed: int,
                 cause: BaseException):
        super().__init__(
            f"request {request_id} interrupted after {tokens_streamed} "
            f"streamed token(s): {type(cause).__name__}: {cause}")
        self.request_id = request_id
        self.tokens_streamed = tokens_streamed
        self.cause = cause


_ids = itertools.count(1)


class RequestHandle:
    """Future-style handle for one submitted request.

    ``result(timeout)`` blocks for the generated token ids (raises the
    request's error instead — CancelledError / DeadlineExceededError /
    EngineClosedError).  ``tokens`` is the stream-so-far; ``ttft_s`` and
    ``token_latencies_s`` carry the latency telemetry the serving bench
    aggregates into p50/p99.
    """

    def __init__(self, engine, prompt, max_new_tokens, eos_token_id,
                 temperature, top_k, seed, deadline_s, stream):
        self.request_id = next(_ids)
        self.redispatches = 0        # times re-enqueued after an engine death
        self.prompt = prompt
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self._rng = np.random.RandomState(seed)
        self._stream = stream
        self._engine = engine
        self._state = "queued"            # queued|active|done
        self._torn = False                # torn off a dead/abandoned engine
        self._cancel_requested = False
        self._done = threading.Event()
        self._error: Optional[BaseException] = None
        self._tokens: list[int] = []
        self.slot: Optional[int] = None
        now = time.perf_counter()
        self.t_submit = now
        self.t_admit: Optional[float] = None
        self._t_last_token = now
        self.ttft_s: Optional[float] = None
        self.token_latencies_s: list[float] = []
        self.deadline = None if deadline_s is None else now + float(deadline_s)

    # -- future surface ------------------------------------------------------
    def done(self) -> bool:
        return self._done.is_set()

    def cancel(self) -> bool:
        """Request cancellation; returns False if already finished.  A
        queued request is failed immediately; an active one is evicted on
        the scheduler's next sweep."""
        return self._engine._request_cancel(self)

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id} not finished within {timeout}s")
        if self._error is not None:
            raise self._error
        return np.asarray(self._tokens, np.int64)

    def exception(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id} not finished within {timeout}s")
        return self._error

    @property
    def tokens(self) -> list[int]:
        """Generated token ids so far (streaming view)."""
        return list(self._tokens)

    @property
    def generated(self) -> list[int]:
        return list(self._tokens)

    def text(self) -> str:
        """Decode the generated tokens (requires the engine's tokenizer)."""
        tok = self._engine.tokenizer
        if tok is None:
            raise ValueError("engine has no tokenizer")
        return tok.decode(self.tokens)

    # -- engine internals ----------------------------------------------------
    def _finish(self, error: Optional[BaseException] = None):
        self._state = "done"
        # readers (result/exception) block on the _done Event before
        # touching _error, so the Event publishes the write
        self._error = error  # tpu-lint: ok(concurrency)
        self._done.set()

    def _emit(self, token: int):
        if self._done.is_set() or self._torn:
            # the request was torn off a dead/abandoned engine while a
            # stuck dispatch was still in flight: never stream past the
            # interruption point (a parked zero-token handle must STAY
            # zero-token or its re-dispatch would duplicate output)
            return
        self._tokens.append(int(token))
        if self._stream is not None:
            try:
                self._stream(int(token))
            except Exception:
                pass  # a broken stream consumer must not kill the batch

    def __repr__(self):
        return (f"RequestHandle(id={self.request_id}, state={self._state}, "
                f"slot={self.slot}, tokens={len(self._tokens)})")


def _sample_row(logits_row: np.ndarray, temperature: float, top_k: int,
                rng) -> int:
    """Sample one token from one row of last-position logits (host side —
    per-request temperature/top_k/rng; greedy at temperature 0)."""
    logits = np.asarray(logits_row, np.float32)
    if temperature == 0.0:
        return int(logits.argmax())
    logits = logits / max(temperature, 1e-6)
    if top_k:
        kth = np.partition(logits, -top_k)[-top_k]
        logits = np.where(logits < kth, -1e30, logits)
    logits = logits - logits.max()
    p = np.exp(logits)
    p /= p.sum()
    return int(rng.choice(len(p), p=p))


def _bucket(n: int, lo: int, hi: int) -> int:
    """Smallest power of two >= n, clamped to [lo, hi] — prompt padding
    buckets keep the prefill compile count logarithmic in max_len."""
    b = lo
    while b < n:
        b *= 2
    return min(b, hi)


class Engine:
    """Continuous-batching inference engine over a cached decoder model.

    Args:
        model: a Layer with the GPT-style cached forward
            ``model(ids, caches=..., use_cache=True) -> (logits, caches)``
            (e.g. ``GPTForPretraining``); when it exposes ``.gpt`` +
            ``.lm_head`` the head runs only on the last position.
        tokenizer: optional — lets ``submit`` accept strings (``encode``)
            and handles expose ``text()`` (``decode``).
        max_slots: concurrent requests sharing the batched decode step.
        max_len: per-slot cache length; every request needs
            ``len(prompt) + max_new_tokens <= max_len``.
        max_queue: admission-queue bound; submits beyond it raise
            :class:`QueueFullError` (default ``2 * max_slots``).
        prefill_batch: new slots admitted per batched prefill call
            (default ``min(4, max_slots)``).
        eos_token_id: default end-of-sequence id for requests.
        auto_start: start the scheduler thread on first submit (tests set
            False to stage a queue deterministically, then call start()).
        admission_hook: optional ``hook(request, load)`` called by
            ``submit`` after validation, BEFORE the request enters the
            queue, with the would-be :class:`RequestHandle` and a
            :meth:`load` snapshot.  Raising any exception rejects the
            request (counted as ``rejected``) and propagates to the
            caller — the seam an external admission layer (the serving
            gateway) uses to shed load without reaching into engine
            internals.
        redispatch_hook: optional ``hook(requests, cause) -> taken`` called
            from the dying scheduler thread when the engine fails, with the
            zero-tokens-emitted requests (queued or active) and the
            original exception; it returns the subset it takes ownership
            of (an :class:`EngineSupervisor` parks them for re-dispatch
            into the rebuilt engine — SAME handles, so callers never
            notice).  Requests not taken fail with
            :class:`EngineDeadError`; requests that already streamed
            tokens always fail with :class:`RequestInterruptedError` and
            are never offered to the hook.
        decode_timeout_s: arm the PR 2 step watchdog around every batched
            prefill/decode dispatch (default: the
            ``PADDLE_TPU_DECODE_TIMEOUT_S`` env var): a stalled XLA call
            produces a crash-dump bundle naming the stuck phase instead
            of a silent hang, and :meth:`health` exposes the progress age
            a supervisor uses for stall detection.
    """

    def __init__(self, model, tokenizer=None, max_slots: int = 8,
                 max_len: int = 256, max_queue: Optional[int] = None,
                 prefill_batch: Optional[int] = None, eos_token_id=None,
                 auto_start: bool = True,
                 admission_hook: Optional[Callable] = None,
                 redispatch_hook: Optional[Callable] = None,
                 decode_timeout_s: Optional[float] = None):
        self.model = model
        self.tokenizer = tokenizer
        self.max_slots = int(max_slots)
        self.max_len = int(max_len)
        if self.max_slots < 1 or self.max_len < 2:
            raise ValueError("need max_slots >= 1 and max_len >= 2")
        cfg = getattr(getattr(model, "gpt", model), "config", None)
        limit = getattr(cfg, "max_position_embeddings", None)
        if limit is not None and self.max_len > int(limit):
            raise ValueError(
                f"max_len={self.max_len} exceeds the model's "
                f"max_position_embeddings={limit}")
        self.max_queue = (2 * self.max_slots if max_queue is None
                          else int(max_queue))
        self.prefill_batch = (min(4, self.max_slots) if prefill_batch is None
                              else max(1, min(int(prefill_batch),
                                              self.max_slots)))
        self.eos_token_id = eos_token_id
        self._auto_start = bool(auto_start)
        self.admission_hook = admission_hook
        self.redispatch_hook = redispatch_hook
        if decode_timeout_s is None:
            raw = os.environ.get("PADDLE_TPU_DECODE_TIMEOUT_S", "")
            try:
                decode_timeout_s = float(raw)
            except ValueError:
                decode_timeout_s = None
        self._decode_timeout_s = (decode_timeout_s
                                  if decode_timeout_s and
                                  decode_timeout_s > 0 else None)

        self._pool = SlotPool(self.max_slots)
        self._queue: deque = deque()
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = False
        self._draining = False
        self._dead: Optional[BaseException] = None
        self._last_progress = time.perf_counter()
        self._thread: Optional[threading.Thread] = None
        self._built = False
        self._values = None
        self._kpools = self._vpools = None
        n_rows = self.max_slots + 1           # + scratch row
        self._ids = np.zeros((n_rows, 1), np.int64)
        self._lengths = np.zeros(n_rows, np.int32)
        self._active = np.zeros(n_rows, bool)
        self._counts = {"submitted": 0, "completed": 0, "rejected": 0,
                        "cancelled": 0, "deadline_expired": 0, "failed": 0,
                        "decode_steps": 0, "prefill_batches": 0,
                        "tokens": 0, "resubmitted": 0, "redispatched": 0,
                        "interrupted": 0}
        self._was_training = model.training
        model.eval()
        # interpreter exit with a live scheduler thread mid-XLA-call
        # aborts the process; the weakref keeps the hook from pinning the
        # engine alive
        ref = weakref.ref(self)
        atexit.register(lambda: (lambda e: e and e.shutdown())(ref()))

    # -- request API ---------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int = 16, eos_token_id=...,
               temperature: float = 0.0, top_k: int = 0, seed: int = 0,
               deadline_s: Optional[float] = None,
               stream: Optional[Callable[[int], None]] = None
               ) -> RequestHandle:
        """Queue one request; returns a Future-style handle.  Raises
        :class:`QueueFullError` when the bounded admission queue is at
        capacity (backpressure: the caller sheds load or retries) and
        ValueError when the request cannot fit a slot."""
        # lock-free monitor-flag reads: _dead/_stop/_draining make single
        # benign transitions; at worst a racing submit lands one sweep
        # late and fails through the death classification instead
        if self._dead is not None:  # tpu-lint: ok(concurrency)
            raise EngineDeadError(self._dead) from self._dead
        if self._stop:
            raise EngineClosedError("engine is shut down")
        if self._draining:
            raise EngineDrainingError(
                "engine is draining; no new admissions")
        if isinstance(prompt, str):
            if self.tokenizer is None:
                raise ValueError("string prompt needs a tokenizer")
            prompt = self.tokenizer.encode(prompt)
        ids = np.asarray(
            prompt._value if isinstance(prompt, Tensor) else prompt
        ).astype(np.int64).reshape(-1)
        if ids.size < 1:
            raise ValueError("empty prompt")
        if int(max_new_tokens) < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if ids.size + int(max_new_tokens) > self.max_len:
            raise ValueError(
                f"prompt ({ids.size}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_len={self.max_len}")
        eos = self.eos_token_id if eos_token_id is ... else eos_token_id
        req = RequestHandle(self, ids, max_new_tokens, eos, temperature,
                            top_k, seed, deadline_s, stream)
        hook = self.admission_hook
        if hook is not None:
            try:
                hook(req, self.load())
            except Exception:
                with self._lock:
                    self._counts["rejected"] += 1
                flight.record("serving", "reject", request=req.request_id,
                              reason="admission_hook")
                registry().counter(
                    SERVING_REQUESTS, "serving requests by outcome").inc(
                    1.0, labels={"outcome": "rejected"})
                raise
        with self._lock:
            if len(self._queue) >= self.max_queue:
                self._counts["rejected"] += 1
                self._gauges_locked()
                flight.record("serving", "reject", request=req.request_id,
                              queue_depth=len(self._queue),
                              max_queue=self.max_queue)
                registry().counter(
                    SERVING_REQUESTS, "serving requests by outcome").inc(
                    1.0, labels={"outcome": "rejected"})
                raise QueueFullError(
                    f"admission queue full ({self.max_queue}); retry later")
            self._queue.append(req)
            self._counts["submitted"] += 1
            self._gauges_locked()
        registry().counter(SERVING_REQUESTS,
                           "serving requests by outcome").inc(
            1.0, labels={"outcome": "submitted"})
        if self._auto_start:
            self.start()
        self._wake.set()
        return req

    def resubmit(self, req: RequestHandle) -> RequestHandle:
        """Re-enqueue a handle taken off a dead engine (the supervisor's
        re-dispatch path): the SAME handle object rides into this
        engine's queue, so a caller blocked on ``result()`` never notices
        the failover.  Only zero-token handles are accepted — re-running
        a request that already streamed tokens would silently duplicate
        delivered output.  Bypasses the admission hook and the queue
        bound (the request was admitted once already)."""
        if req._tokens:
            raise ValueError(
                f"request {req.request_id} already streamed "
                f"{len(req._tokens)} token(s); re-dispatch would "
                f"duplicate them")
        if self._dead is not None:
            raise EngineDeadError(self._dead) from self._dead
        if self._stop:
            raise EngineClosedError("engine is shut down")
        req._engine = self
        req._state = "queued"
        req._torn = False       # live again: this engine may emit for it
        req.slot = None
        req.redispatches += 1
        with self._lock:
            self._queue.append(req)
            self._counts["resubmitted"] += 1
            self._gauges_locked()
        flight.record("serving", "resubmit", request=req.request_id,
                      redispatches=req.redispatches)
        registry().counter(
            SERVING_REDISPATCHED,
            "requests re-dispatched after an engine death").inc(
            1.0, labels={"layer": "supervisor"})
        if self._auto_start:
            self.start()
        self._wake.set()
        return req

    def start(self):
        """Start the scheduler thread (idempotent)."""
        if self._dead is not None:
            raise EngineDeadError(self._dead) from self._dead
        if self._stop:
            raise EngineClosedError("engine is shut down")
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, name="paddle-tpu-serving", daemon=True)
            self._thread.start()

    def join(self, timeout: Optional[float] = None) -> bool:
        """Block until queue and slots are empty; False on timeout."""
        deadline = None if timeout is None else time.perf_counter() + timeout
        while True:
            with self._lock:
                idle = not self._queue and self._pool.n_active == 0
            if idle:
                return True
            if deadline is not None and time.perf_counter() > deadline:
                return False
            time.sleep(0.005)

    def drain(self, deadline_s: float = 30.0) -> bool:
        """Graceful shutdown, phase one: stop admission (new submits
        raise :class:`EngineDrainingError` and ``load()`` advertises
        not-alive so routers stop picking this replica) while the
        scheduler keeps finishing every queued and in-flight request.
        Returns True when all of them completed before the deadline —
        the engine is then idle and a ``shutdown()`` drops nothing."""
        with self._lock:
            self._draining = True
            depth, active = len(self._queue), self._pool.n_active
        flight.record("serving", "drain_begin", queue_depth=depth,
                      active_slots=active, deadline_s=float(deadline_s))
        if (depth or active) and self._dead is None and not self._stop:
            self.start()        # pending work with no scheduler: run it out
        ok = self.join(timeout=deadline_s) and self._dead is None
        flight.record("serving", "drain_done", drained=ok)
        return ok

    def abandon(self, cause: Optional[BaseException] = None):
        """A supervisor declares this engine dead from OUTSIDE the
        scheduler thread (decode stall: the thread is stuck inside an
        XLA call and cannot be killed).  The engine stops accepting work
        and its requests are classified exactly as a scheduler crash —
        zero-token requests are offered to the redispatch hook, streamed
        ones get :class:`RequestInterruptedError`.  Idempotent; a no-op
        on an engine that is already dead or shut down."""
        if self._dead is not None or self._stop:
            return
        self._fail_as_dead(cause or EngineStalledError(
            "engine abandoned by its supervisor"))
        self._wake.set()        # a parked scheduler wakes up and exits

    def shutdown(self):
        """Stop the scheduler; in-flight and queued requests fail with
        EngineClosedError.  Restores the model's train/eval mode."""
        if self._stop:
            return
        # monitor flag: single False->True transition, polled by the
        # scheduler loop; a stale read costs one extra 20 ms iteration
        self._stop = True  # tpu-lint: ok(concurrency)
        self._wake.set()
        if self._thread is not None:
            # a DEAD engine's thread is exiting (or, after abandon(),
            # permanently stuck in an XLA call) — don't wait long for it
            self._thread.join(timeout=30 if self._dead is None else 2)
        err = EngineClosedError("engine shut down")
        with self._lock:
            pending = list(self._queue) + list(self._pool.active().values())
            self._queue.clear()
            for slot in list(self._pool.active()):
                self._pool.free(slot)
            self._active[:] = False
            self._gauges_locked()
        for req in pending:
            req._finish(err)
        if self._was_training:
            self.model.train()

    close = shutdown

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False

    # -- introspection -------------------------------------------------------
    def queue_depth(self) -> int:
        """Unadmitted queued requests right now (O(1), one lock hop)."""
        with self._lock:
            return len(self._queue)

    def slots_in_use(self) -> int:
        """Slots currently owned by in-flight requests (O(1) — the pool
        keeps the count; no slot-array scan)."""
        with self._lock:
            return self._pool.n_active

    def load(self) -> dict:
        """One-lock-hop load snapshot for external admission/routing
        (queue depth, slot occupancy, capacity, liveness).  Every field
        comes from O(1) counters — safe to poll per-request from a
        gateway without perturbing the scheduler."""
        with self._lock:
            return {
                "queue_depth": len(self._queue),
                "slots_in_use": self._pool.n_active,
                "max_slots": self.max_slots,
                "max_queue": self.max_queue,
                "max_len": self.max_len,
                "alive": (self._dead is None and not self._stop and
                          not self._draining),
                "draining": self._draining,
            }

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._counts)
            out["active_slots"] = self._pool.n_active
            out["queue_depth"] = len(self._queue)
            out["slot_allocs"] = self._pool.alloc_total
            out["slot_reuses"] = self._pool.reuse_total
        out.update(self.compile_stats())
        return out

    def compile_stats(self) -> dict:
        """Distinct jit signatures per entry point (retrace sentinel
        counters; decode must stay at 1 — THE continuous-batching
        invariant)."""
        pf = getattr(self, "_prefill_fn", None)
        dc = getattr(self, "_decode_fn", None)
        return {
            "prefill_compiles": len(pf._signatures) if pf is not None else 0,
            "decode_compiles": len(dc._signatures) if dc is not None else 0,
        }

    # -- jitted pieces -------------------------------------------------------
    def _build(self):
        import jax
        import jax.numpy as jnp

        from ..nn.functional_call import _swapped_state, state_values

        model = self.model
        n_rows, L = self.max_slots + 1, self.max_len
        self._values = state_values(model)

        def _kv_struct():
            def f(vals, ii):
                with _swapped_state(model, vals):
                    _, caches = model(Tensor(ii, _internal=True),
                                      use_cache=True)
                return [(k._value, v._value) for k, v in caches]
            return jax.eval_shape(f, self._values,
                                  jnp.zeros((1, 1), jnp.int64))

        kv = _kv_struct()
        self._kpools = [jnp.zeros((n_rows, L) + tuple(k.shape[2:]), k.dtype)
                        for k, _ in kv]
        self._vpools = [jnp.zeros((n_rows, L) + tuple(v.shape[2:]), v.dtype)
                        for _, v in kv]

        def _fwd_last(ids_t, caches_t, gather_idx=None):
            """(per-row logits at the last real position, new caches); when
            the model exposes trunk + head, the vocab matmul runs on ONLY
            the gathered positions."""
            inner = getattr(model, "gpt", None)
            head = getattr(model, "lm_head", None)
            if inner is not None and callable(head):
                x, new_caches = inner(ids_t, caches=caches_t, use_cache=True)
                h = x._value
                h_last = (h[:, -1] if gather_idx is None
                          else h[jnp.arange(h.shape[0]), gather_idx])
                logits = head(Tensor(h_last[:, None],
                                     _internal=True))._value[:, 0]
            else:
                lg, new_caches = model(ids_t, caches=caches_t,
                                       use_cache=True)
                lg = lg._value
                logits = (lg[:, -1] if gather_idx is None
                          else lg[jnp.arange(lg.shape[0]), gather_idx])
            return logits, new_caches

        def prefill(values, ids, kpools, vpools, slot_idx, prompt_lens):
            # the per-request caches are BUILT inside this jit with a
            # python-int length 0 (static prefill: the prompt keeps the
            # causal flash path), then the filled rows scatter into the
            # pool at each request's slot; padding rows target the scratch
            # slot
            n = ids.shape[0]
            caches_t = [
                (Tensor(jnp.zeros((n, L) + tuple(kp.shape[2:]), kp.dtype),
                        _internal=True),
                 Tensor(jnp.zeros((n, L) + tuple(vp.shape[2:]), vp.dtype),
                        _internal=True), 0)
                for kp, vp in zip(kpools, vpools)]
            with _swapped_state(model, values):
                logits, new_caches = _fwd_last(
                    Tensor(ids, _internal=True), caches_t,
                    gather_idx=prompt_lens - 1)
            kpools = [kp.at[slot_idx].set(c[0]._value)
                      for kp, c in zip(kpools, new_caches)]
            vpools = [vp.at[slot_idx].set(c[1]._value)
                      for vp, c in zip(vpools, new_caches)]
            return logits, kpools, vpools

        def decode(values, ids, kpools, vpools, lengths, active):
            # ONE batched step over every slot row (+ scratch): vector
            # lengths route the per-slot static-cache branch; inactive
            # rows compute garbage that is never read and their lengths
            # stay put
            caches_t = [(Tensor(kp, _internal=True),
                         Tensor(vp, _internal=True), lengths)
                        for kp, vp in zip(kpools, vpools)]
            with _swapped_state(model, values):
                logits, new_caches = _fwd_last(
                    Tensor(ids, _internal=True), caches_t)
            kpools = [c[0]._value for c in new_caches]
            vpools = [c[1]._value for c in new_caches]
            new_lengths = jnp.where(active, lengths + 1, lengths)
            return logits, kpools, vpools, new_lengths

        # cache pools are donated: prefill/decode update HBM in place (no
        # donation on CPU — it only warns there)
        donate = (2, 3) if jax.default_backend() != "cpu" else ()
        self._prefill_fn = instrument_jit(
            jax.jit(prefill, donate_argnums=donate), "serving.prefill")
        self._decode_fn = instrument_jit(
            jax.jit(decode, donate_argnums=donate), "serving.decode")
        with self._lock:
            self._built = True

    # -- scheduler loop ------------------------------------------------------
    def _loop(self):
        while not self._stop and self._dead is None:
            try:
                did = self._step_once()
            except Exception as e:  # noqa: BLE001 — fail loudly, not hang
                self._fail_as_dead(e)
                raise
            with self._lock:
                # progress heartbeat: freezes while a dispatch is stuck
                # inside XLA (the supervisor's stall detector reads the
                # age via health())
                self._last_progress = time.perf_counter()
            if not did:
                self._wake.wait(0.02)
                self._wake.clear()

    def _fail_as_dead(self, cause: BaseException):
        """Death path, from the dying scheduler thread (crash) or a
        supervisor (:meth:`abandon` on a stall): mark the engine DEAD —
        a later submit() must not restart the loop over an already-failed
        pool — then classify the in-flight work by what already reached a
        consumer: requests with ZERO streamed tokens are duplication-safe
        and are offered to the redispatch hook (untaken ones fail with
        EngineDeadError); requests that streamed tokens fail with the
        typed RequestInterruptedError, never a silent replay."""
        with self._lock:
            if self._dead is not None:      # lost the race: already dead
                return
            # single None->exc transition; racing lock-free readers at
            # worst see the engine alive one sweep late
            self._dead = cause  # tpu-lint: ok(concurrency)
            queued = list(self._queue)
            active = list(self._pool.active().values())
            self._queue.clear()
            for slot in list(self._pool.active()):
                self._pool.free(slot)
            self._active[:] = False
            for r in queued + active:
                # freeze the token streams FIRST: after abandon() a
                # stuck dispatch may still come back and try to emit
                r._torn = True
        flight.record("serving", "scheduler_error",
                      error=f"{type(cause).__name__}: {cause}",
                      queued=len(queued), active=len(active))
        fresh = [r for r in queued + active if not r._tokens]
        streamed = [r for r in active if r._tokens]
        taken_ids: set = set()
        hook = self.redispatch_hook
        if hook is not None and fresh:
            try:
                taken_ids = {id(r) for r in hook(list(fresh), cause)}
            except Exception:  # noqa: BLE001
                taken_ids = set()   # a broken hook must not mask the death
        lost = [r for r in fresh if id(r) not in taken_ids]
        with self._lock:
            self._counts["failed"] += len(lost) + len(streamed)
            self._counts["redispatched"] += len(taken_ids)
            self._counts["interrupted"] += len(streamed)
            self._gauges_locked()
        for r in lost:
            r._finish(EngineDeadError(cause))
        reg = registry()
        for r in streamed:
            flight.record("serving", "interrupted", request=r.request_id,
                          tokens=len(r._tokens))
            reg.counter(SERVING_INTERRUPTED,
                        "requests failed mid-stream by an engine death"
                        ).inc(1.0)
            r._finish(RequestInterruptedError(
                r.request_id, len(r._tokens), cause))
        if taken_ids:
            flight.record("serving", "handoff", n=len(taken_ids))

    def _step_once(self) -> bool:
        """One scheduler iteration: sweep, admit (batched prefill), one
        batched decode step.  Returns whether any work happened."""
        faults.fault_point("serving.scheduler")
        self._sweep()
        did = self._admit()
        did = self._decode_step() or did
        return did

    def health(self) -> dict:
        """Liveness snapshot: ``alive`` is True only while the engine can
        still take and make progress on requests.  ``progress_age_s`` is
        the time since the scheduler last completed an iteration — with
        work pending, a growing age means the thread is stuck inside a
        dispatch (the supervisor's stall signal)."""
        with self._lock:
            active, depth = self._pool.n_active, len(self._queue)
            progress_age = time.perf_counter() - self._last_progress
            built = self._built
        return {
            "alive": (self._dead is None and not self._stop and
                      not self._draining),
            "dead": self._dead is not None,
            "draining": self._draining,
            "error": (None if self._dead is None
                      else f"{type(self._dead).__name__}: {self._dead}"),
            "stopped": self._stop,
            "scheduler_running": (self._thread is not None and
                                  self._thread.is_alive()),
            "active_slots": active,
            "queue_depth": depth,
            "progress_age_s": progress_age,
            # warm = the decode program exists: dispatches are now
            # bounded, so a frozen progress age means a genuine stall
            # (cold engines legitimately sit in multi-second compiles)
            "warm": built and
            self.compile_stats()["decode_compiles"] >= 1,
        }

    def _sweep(self):
        """Evict cancelled / past-deadline requests (queued and active)."""
        now = time.perf_counter()
        to_finish = []
        with self._lock:
            for req in list(self._queue):
                if req._cancel_requested or (req.deadline is not None and
                                             now > req.deadline):
                    self._queue.remove(req)
                    outcome = ("cancelled" if req._cancel_requested
                               else "deadline_expired")
                    self._evicted_counters_locked(req, outcome)
                    to_finish.append((req, outcome))
            for slot, req in self._pool.active().items():
                if req._cancel_requested or (req.deadline is not None and
                                             now > req.deadline):
                    outcome = ("cancelled" if req._cancel_requested
                               else "deadline_expired")
                    self._evict_locked(req, outcome)
                    to_finish.append((req, outcome))
            self._gauges_locked()
        for req, outcome in to_finish:
            err = (CancelledError() if outcome == "cancelled" else
                   DeadlineExceededError(
                       f"request {req.request_id} missed its deadline"))
            req._finish(err)

    def _request_cancel(self, req: RequestHandle) -> bool:
        if req.done():
            return False
        req._cancel_requested = True
        with self._lock:
            if req in self._queue:       # not yet admitted: fail right away
                self._queue.remove(req)
                self._evicted_counters_locked(req, "cancelled")
                self._gauges_locked()
                req._finish(CancelledError())
                return True
        self._wake.set()                 # active: next sweep evicts
        return True

    def _admit(self) -> bool:
        with self._lock:
            n = min(self._pool.n_free, self.prefill_batch, len(self._queue))
            batch = [self._queue.popleft() for _ in range(n)]
            for req in batch:
                req.slot = self._pool.alloc(req)
                req._state = "active"
                req.t_admit = time.perf_counter()
            self._gauges_locked()
        if not batch:
            return False
        if not self._built:
            with span("serving.build"):
                self._build()

        import jax.numpy as jnp
        bucket = _bucket(max(r.prompt.size for r in batch),
                         min(8, self.max_len), self.max_len)
        ids = np.zeros((self.prefill_batch, bucket), np.int64)
        slot_idx = np.full(self.prefill_batch, self.max_slots, np.int32)
        plens = np.ones(self.prefill_batch, np.int32)
        for i, req in enumerate(batch):
            ids[i, :req.prompt.size] = req.prompt
            slot_idx[i] = req.slot
            plens[i] = req.prompt.size
            flight.record("serving", "admit", request=req.request_id,
                          slot=req.slot, prompt_len=int(req.prompt.size),
                          queue_wait_ms=round(
                              1e3 * (req.t_admit - req.t_submit), 3))
        t0 = time.perf_counter()
        faults.fault_point("serving.prefill", n=len(batch))
        if self._decode_timeout_s is not None:
            _watchdog.arm("serving.prefill", self._decode_timeout_s)
        try:
            with span("serving.prefill", n=len(batch), bucket=bucket):
                logits, self._kpools, self._vpools = self._prefill_fn(
                    self._values, jnp.asarray(ids), self._kpools,
                    self._vpools, jnp.asarray(slot_idx), jnp.asarray(plens))
                logits = np.asarray(logits)
        finally:
            if self._decode_timeout_s is not None:
                _watchdog.disarm()
        dt = time.perf_counter() - t0
        with self._lock:
            self._counts["prefill_batches"] += 1
        registry().histogram(SERVING_BATCH_SECONDS,
                             "prefill/decode batch wall time").observe(
            dt, labels={"phase": "prefill"})
        now = time.perf_counter()
        for i, req in enumerate(batch):
            req.ttft_s = now - req.t_submit
            req._t_last_token = now
            registry().histogram(SERVING_TTFT,
                                 "time to first token").observe(req.ttft_s)
            self._emit_token(req, logits[i], first=True)
        with self._lock:
            self._gauges_locked()
        return True

    def _decode_step(self) -> bool:
        with self._lock:
            active = self._pool.active()
            if not active:
                return False
            # snapshot the slot-state arrays under the lock: shutdown()
            # clears _active from the caller thread (tpu-lint
            # concurrency.unguarded-shared-attr)
            ids = np.array(self._ids)
            lengths = np.array(self._lengths)
            act = np.array(self._active)
        import jax.numpy as jnp
        t0 = time.perf_counter()
        faults.fault_point("serving.decode", active=len(active))
        if self._decode_timeout_s is not None:
            _watchdog.arm("serving.decode", self._decode_timeout_s)
        try:
            with span("serving.decode", active=len(active)):
                logits, self._kpools, self._vpools, _ = self._decode_fn(
                    self._values, jnp.asarray(ids), self._kpools,
                    self._vpools, jnp.asarray(lengths), jnp.asarray(act))
                logits = np.asarray(logits)
        finally:
            if self._decode_timeout_s is not None:
                _watchdog.disarm()
        dt = time.perf_counter() - t0
        with self._lock:
            self._counts["decode_steps"] += 1
        registry().histogram(SERVING_BATCH_SECONDS,
                             "prefill/decode batch wall time").observe(
            dt, labels={"phase": "decode"})
        now = time.perf_counter()
        for slot, req in active.items():
            self._lengths[slot] += 1
            lat = now - req._t_last_token
            req._t_last_token = now
            req.token_latencies_s.append(lat)
            registry().histogram(SERVING_TOKEN_LATENCY,
                                 "per-token decode latency").observe(lat)
            self._emit_token(req, logits[slot], first=False)
        with self._lock:
            self._gauges_locked()
        return True

    def _emit_token(self, req: RequestHandle, logits_row, first: bool):
        """Sample, stream, and either park the token as the slot's next
        decode input or complete + evict the request."""
        if req.done() or req._torn or req._engine is not self:
            # torn away by a supervisor abandon while this batch ran (or
            # already re-dispatched into a REBUILT engine): its slot here
            # is freed and its outcome is settled elsewhere
            return
        faults.fault_point("serving.stream", request=req.request_id)
        token = _sample_row(logits_row, req.temperature, req.top_k, req._rng)
        req._emit(token)
        registry().counter(SERVING_TOKENS, "tokens generated").inc(1.0)
        finished = (len(req._tokens) >= req.max_new_tokens or
                    (req.eos_token_id is not None and
                     token == req.eos_token_id))
        slot = req.slot
        with self._lock:
            self._counts["tokens"] += 1
            if first:
                self._lengths[slot] = req.prompt.size
            if finished:
                self._evict_locked(req, "completed")
            else:
                self._ids[slot, 0] = token
                self._active[slot] = True
        if finished:
            req._finish(None)

    def _evict_locked(self, req: RequestHandle, outcome: str):
        self._pool.free(req.slot)
        self._active[req.slot] = False
        self._evicted_counters_locked(req, outcome)

    def _evicted_counters_locked(self, req: RequestHandle, outcome: str):
        self._counts[outcome] = self._counts.get(outcome, 0) + 1
        flight.record("serving", "evict", request=req.request_id,
                      slot=-1 if req.slot is None else req.slot,
                      outcome=outcome, tokens=len(req._tokens))
        registry().counter(SERVING_REQUESTS,
                           "serving requests by outcome").inc(
            1.0, labels={"outcome": outcome})

    def _gauges_locked(self):
        reg = registry()
        reg.gauge(SERVING_ACTIVE_SLOTS,
                  "slots currently owned by requests").set(
            float(self._pool.n_active))
        reg.gauge(SERVING_QUEUE_DEPTH, "queued, unadmitted requests").set(
            float(len(self._queue)))
