"""Continuous-batching serving engine over the static KV-cache decode path.

The GPT flagship already has the fast half of a serving stack: a
single-program decode step with donated fixed-shape cache buffers
(models/gpt.py static cache; the AnalysisPredictor zero-copy run analog).
What it lacked is the request level — this module adds it, in the shape
production LLM servers (vLLM/Orca-style continuous batching) converged on:

* a **slot pool**: ONE set of ``[max_slots+1, max_len, heads, head_dim]``
  per-layer cache buffers; each in-flight request owns a slot row, freed on
  completion and recycled for the next request (SlotPool).  Row max_slots
  is a scratch slot that absorbs prefill padding writes.
* a **scheduler loop** (daemon thread): each iteration sweeps
  cancellations/deadlines, admits queued requests into free slots with ONE
  batched prefill (prompts padded to a power-of-two bucket, so compile
  count stays logarithmic), then runs ONE batched decode step for ALL
  active slots — fixed shapes, so after the first iteration the decode is
  a single compiled program forever, regardless of request churn
  (asserted via the retrace sentinel's signature count).
* a **request/response API**: ``submit() -> RequestHandle`` (Future-style:
  ``result`` / ``done`` / ``cancel`` / ``exception``), per-token streaming
  callbacks, a bounded admission queue that rejects with
  :class:`QueueFullError` when full (backpressure), and per-request
  deadlines.
* **observability**: spans + flight events for admit/prefill/decode/evict,
  gauges for active slots and queue depth, histograms for time-to-first-
  token and per-token latency — all through the paddle_tpu.observability
  registry, live from request one.

**Decode fast path** (docs/serving.md "Decode fast path"): decode is
HBM-bandwidth-bound — every step reads the full weights + KV pool to emit
one token per slot (docs/PERF.md round 5) — so three flag-gated,
composable attacks on that bound ride the same single-signature loop:

* ``prefix_cache=True`` — completed requests' KV rows are RETAINED in the
  pool behind a content-addressed index (prefix_cache.PrefixIndex); a
  request whose prompt starts with a cached row's tokens copies the row
  and prefills only the tail (shared system prompts skip re-prefill).
* ``speculative_k=k`` — draft ``k-1`` tokens per step (prompt-lookup
  n-gram drafter by default, ``drafter=`` seam for a draft model) and
  verify all of them in ONE ``k``-wide batched forward; the matched
  prefix is accepted, so each pool read yields up to ``k`` tokens.
  Greedy output stays token-identical to the plain path by construction.
* ``kv_dtype="int8"`` — pools stored int8 with per-row scales
  (kv_quant), dequantized inside the attention read: half the pool bytes,
  double the slots in the same HBM.

**Multi-LoRA serving** (docs/serving.md "Multi-LoRA serving"):
``Engine(adapters=AdapterRegistry(...))`` serves many LoRA-fine-tuned
variants of the same base weights — per-slot int32 adapter ids gather
each row's low-rank factors from stacked device banks inside the SAME
decode program (bank row 0 = the exact base model), with refcount+LRU
HBM residency and admission-time cold loads; pair with
``weight_dtype="int8"`` to store the base weights themselves quantized.

Sampling runs ON DEVICE by default (``sample_on_device=True``):
temperature / top-k / greedy with per-slot parameters and counter-based
PRNG keys live in the decode program, so only ``[B(, k)]`` token ids —
not ``[B, V]`` logits — cross the host boundary per step.

Per-slot cache positions ride the models' static-cache protocol with a
VECTOR length: ``caches = [(k_buf, v_buf, lengths[B])]`` makes each row
write its new keys at its own offset and attend under a per-row validity
mask (models/gpt.py per-slot branch; the int8 form appends per-row scale
buffers as a 5-tuple).

Thread-safety: the engine runs the model from its scheduler thread via the
functional state swap; do not run the same model's eager forward
concurrently with in-flight requests.
"""
from __future__ import annotations

import atexit
import itertools
import os
import threading
import time
import weakref
from collections import deque
from concurrent.futures import CancelledError
from typing import Callable, Optional

import numpy as np

from ..core.tensor import Tensor
from ..observability import flight, registry, span
from ..observability import perfscope as _perfscope
from ..observability import steps as _steps
from ..observability import watchdog as _watchdog
from ..observability.retrace import instrument_jit
from ..testing import faults
from .kv_tier import HostPrefixTier
from .paged_kv import PageAllocator
from .prefix_cache import PrefixEntry, PrefixIndex
from .slot_pool import SlotPool
from .speculative import NgramDrafter

__all__ = ["Engine", "RequestHandle", "QueueFullError",
           "DeadlineExceededError", "EngineClosedError", "EngineDeadError",
           "EngineDrainingError", "EngineStalledError",
           "RequestInterruptedError"]

# -- metric names (paddle_tpu.observability registry) -------------------------
SERVING_ACTIVE_SLOTS = "paddle_tpu_serving_active_slots"
SERVING_QUEUE_DEPTH = "paddle_tpu_serving_queue_depth"
SERVING_REQUESTS = "paddle_tpu_serving_requests_total"
SERVING_TOKENS = "paddle_tpu_serving_tokens_total"
SERVING_TTFT = "paddle_tpu_serving_ttft_seconds"
SERVING_TOKEN_LATENCY = "paddle_tpu_serving_token_seconds"
SERVING_BATCH_SECONDS = "paddle_tpu_serving_batch_seconds"
SERVING_REDISPATCHED = "paddle_tpu_serving_requests_redispatched_total"
SERVING_INTERRUPTED = "paddle_tpu_serving_requests_interrupted_total"
SERVING_PREFIX_HITS = "paddle_tpu_serving_prefix_cache_hits_total"
SERVING_PREFIX_MISSES = "paddle_tpu_serving_prefix_cache_misses_total"
SERVING_PREFIX_EVICTIONS = "paddle_tpu_serving_prefix_cache_evictions_total"
SERVING_SPEC_DRAFTED = "paddle_tpu_serving_speculative_tokens_drafted_total"
SERVING_SPEC_ACCEPTED = \
    "paddle_tpu_serving_speculative_tokens_accepted_total"
SERVING_KV_POOL_BYTES = "paddle_tpu_serving_kv_pool_bytes"
SERVING_KV_PAGES_FREE = "paddle_tpu_serving_kv_pages_free"
SERVING_KV_PAGES_ACTIVE = "paddle_tpu_serving_kv_pages_active"
SERVING_KV_PAGES_CACHED = "paddle_tpu_serving_kv_pages_cached"
SERVING_KV_COW_COPIES = "paddle_tpu_serving_kv_page_cow_copies_total"
SERVING_ADAPTERS_RESIDENT = "paddle_tpu_serving_adapters_resident"
SERVING_ADAPTER_TOKENS = "paddle_tpu_serving_adapter_tokens_total"
SERVING_ADAPTER_TTFT = "paddle_tpu_serving_adapter_ttft_seconds"
SERVING_ADAPTER_LOADS = "paddle_tpu_serving_adapter_loads_total"
SERVING_ADAPTER_EVICTIONS = "paddle_tpu_serving_adapter_evictions_total"
SERVING_ADAPTER_STALLS = "paddle_tpu_serving_adapter_load_stalls_total"
SERVING_WEIGHT_BYTES = "paddle_tpu_serving_weight_bytes"
SERVING_HOST_PREFIX_HITS = "paddle_tpu_serving_host_prefix_hits_total"
SERVING_HOST_PREFIX_PROMOTES = \
    "paddle_tpu_serving_host_prefix_promotes_total"
SERVING_HOST_PREFIX_PROMOTE_SECONDS = \
    "paddle_tpu_serving_host_prefix_promote_seconds"


class QueueFullError(RuntimeError):
    """Admission queue is at capacity — backpressure; retry later."""


class DeadlineExceededError(TimeoutError):
    """The request's deadline passed before it finished."""


class EngineClosedError(RuntimeError):
    """The engine was shut down with this request still in flight."""


class EngineDrainingError(EngineClosedError):
    """The engine is draining: no new admissions, in-flight work finishes
    (the graceful-shutdown analogue of QueueFullError — retry elsewhere)."""


class EngineDeadError(RuntimeError):
    """The scheduler thread crashed: the engine is permanently dead and
    rejects new work, naming the original exception — restarting the loop
    over an already-failed pool would serve garbage.  A request that had
    emitted ZERO tokens when the engine died also fails with this type
    (unless a supervisor re-dispatches it): the caller knows nothing
    reached any consumer, so a retry is duplication-safe."""

    def __init__(self, cause: BaseException):
        super().__init__(
            f"serving scheduler died: {type(cause).__name__}: {cause}")
        self.cause = cause


class EngineStalledError(RuntimeError):
    """The scheduler stopped making progress with work pending (decode
    hang): a supervisor declared the engine dead via :meth:`Engine.abandon`
    — the stuck thread cannot be killed, but the engine stops accepting
    work and its requests are classified exactly like a crash."""


class RequestInterruptedError(RuntimeError):
    """The engine died AFTER this request streamed token(s): replaying it
    elsewhere would duplicate tokens already delivered, so instead of a
    silent re-run the caller gets this typed error naming how far the
    stream got and the underlying engine failure."""

    def __init__(self, request_id: int, tokens_streamed: int,
                 cause: BaseException):
        super().__init__(
            f"request {request_id} interrupted after {tokens_streamed} "
            f"streamed token(s): {type(cause).__name__}: {cause}")
        self.request_id = request_id
        self.tokens_streamed = tokens_streamed
        self.cause = cause


_ids = itertools.count(1)


class RequestHandle:
    """Future-style handle for one submitted request.

    ``result(timeout)`` blocks for the generated token ids (raises the
    request's error instead — CancelledError / DeadlineExceededError /
    EngineClosedError).  ``tokens`` is the stream-so-far; ``ttft_s`` and
    ``token_latencies_s`` carry the latency telemetry the serving bench
    aggregates into p50/p99.
    """

    def __init__(self, engine, prompt, max_new_tokens, eos_token_id,
                 temperature, top_k, seed, deadline_s, stream,
                 adapter=None, journey=None, conversation=None):
        self.request_id = next(_ids)
        self.redispatches = 0        # times re-enqueued after an engine death
        self.adapter = adapter       # LoRA adapter name (None = base model)
        self.conversation = conversation  # prefix-index namespace qualifier
        self.journey = journey       # observability.journey.Journey or None
        self._adapter_slot = 0       # bank row while active (0 = zero adapter)
        self._adapter_pinned = False
        self.prompt = prompt
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.seed = int(seed)
        self._rng = np.random.RandomState(seed)
        self._stream = stream
        self._engine = engine
        self._state = "queued"            # queued|active|done
        self._torn = False                # torn off a dead/abandoned engine
        self._cancel_requested = False
        self._done = threading.Event()
        self._error: Optional[BaseException] = None
        self._tokens: list[int] = []
        self.slot: Optional[int] = None
        self._prefix_src = None           # PrefixEntry this request copied
        self._prefix_match = 0            # tokens covered by that copy
        self._pages: Optional[list] = None    # paged mode: backing pages
        self._cow = None                  # pending (src, dst) page COW copy
        self._promote = None              # pending (host entry, match) upload
        now = time.perf_counter()
        self.t_submit = now
        self.t_queue = now           # engine-queue entry (reset on resubmit)
        self._stall_t0: Optional[float] = None   # HOL stall began (journey)
        self._stall_kind: Optional[str] = None   # adapter_stall | page_stall
        self.t_admit: Optional[float] = None
        self._t_last_token = now
        self.ttft_s: Optional[float] = None
        self.prefix_hit = False           # admitted via a prefix-cache copy
        self.promote_s: Optional[float] = None  # host-tier promote wall s
        self.token_latencies_s: list[float] = []
        self.deadline = None if deadline_s is None else now + float(deadline_s)

    # -- future surface ------------------------------------------------------
    def done(self) -> bool:
        return self._done.is_set()

    def cancel(self) -> bool:
        """Request cancellation; returns False if already finished.  A
        queued request is failed immediately; an active one is evicted on
        the scheduler's next sweep."""
        return self._engine._request_cancel(self)

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id} not finished within {timeout}s")
        if self._error is not None:
            raise self._error
        return np.asarray(self._tokens, np.int64)

    def exception(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id} not finished within {timeout}s")
        return self._error

    @property
    def tokens(self) -> list[int]:
        """Generated token ids so far (streaming view)."""
        return list(self._tokens)

    @property
    def generated(self) -> list[int]:
        return list(self._tokens)

    def text(self) -> str:
        """Decode the generated tokens (requires the engine's tokenizer)."""
        tok = self._engine.tokenizer
        if tok is None:
            raise ValueError("engine has no tokenizer")
        return tok.decode(self.tokens)

    # -- engine internals ----------------------------------------------------
    def _finish(self, error: Optional[BaseException] = None):
        self._state = "done"
        # readers (result/exception) block on the _done Event before
        # touching _error, so the Event publishes the write
        self._error = error  # tpu-lint: ok(concurrency)
        self._done.set()

    def _emit(self, token: int):
        if self._done.is_set() or self._torn:
            # the request was torn off a dead/abandoned engine while a
            # stuck dispatch was still in flight: never stream past the
            # interruption point (a parked zero-token handle must STAY
            # zero-token or its re-dispatch would duplicate output)
            return
        self._tokens.append(int(token))
        if self._stream is not None:
            try:
                self._stream(int(token))
            except Exception:
                pass  # a broken stream consumer must not kill the batch

    def __repr__(self):
        return (f"RequestHandle(id={self.request_id}, state={self._state}, "
                f"slot={self.slot}, tokens={len(self._tokens)})")


def _sample_row(logits_row: np.ndarray, temperature: float, top_k: int,
                rng) -> int:
    """Sample one token from one row of last-position logits (host side —
    per-request temperature/top_k/rng; greedy at temperature 0).  The
    reference the device sampler's greedy path is parity-tested against
    (``sample_on_device=False`` escape hatch)."""
    logits = np.asarray(logits_row, np.float32)
    if temperature == 0.0:
        return int(logits.argmax())
    logits = logits / max(temperature, 1e-6)
    if top_k:
        kth = np.partition(logits, -top_k)[-top_k]
        logits = np.where(logits < kth, -1e30, logits)
    logits = logits - logits.max()
    p = np.exp(logits)
    p /= p.sum()
    return int(rng.choice(len(p), p=p))


def _bucket(n: int, lo: int, hi: int) -> int:
    """Smallest power of two >= n, clamped to [lo, hi] — prompt padding
    buckets keep the prefill compile count logarithmic in max_len."""
    b = lo
    while b < n:
        b *= 2
    return min(b, hi)


class Engine:
    """Continuous-batching inference engine over a cached decoder model.

    Args:
        model: a Layer with the GPT-style cached forward
            ``model(ids, caches=..., use_cache=True) -> (logits, caches)``
            (e.g. ``GPTForPretraining``); when it exposes ``.gpt`` +
            ``.lm_head`` the head runs only on the gathered positions.
        tokenizer: optional — lets ``submit`` accept strings (``encode``)
            and handles expose ``text()`` (``decode``).
        max_slots: concurrent requests sharing the batched decode step.
        max_len: per-slot cache length; every request needs
            ``len(prompt) + max_new_tokens <= max_len``.
        max_queue: admission-queue bound; submits beyond it raise
            :class:`QueueFullError` (default ``2 * max_slots``).
        prefill_batch: new slots admitted per batched prefill call
            (default ``min(4, max_slots)``).
        eos_token_id: default end-of-sequence id for requests.
        auto_start: start the scheduler thread on first submit (tests set
            False to stage a queue deterministically, then call start()).
        admission_hook: optional ``hook(request, load)`` called by
            ``submit`` after validation, BEFORE the request enters the
            queue, with the would-be :class:`RequestHandle` and a
            :meth:`load` snapshot.  Raising any exception rejects the
            request (counted as ``rejected``) and propagates to the
            caller — the seam an external admission layer (the serving
            gateway) uses to shed load without reaching into engine
            internals.
        redispatch_hook: optional ``hook(requests, cause) -> taken`` called
            from the dying scheduler thread when the engine fails, with the
            zero-tokens-emitted requests (queued or active) and the
            original exception; it returns the subset it takes ownership
            of (an :class:`EngineSupervisor` parks them for re-dispatch
            into the rebuilt engine — SAME handles, so callers never
            notice).  Requests not taken fail with
            :class:`EngineDeadError`; requests that already streamed
            tokens always fail with :class:`RequestInterruptedError` and
            are never offered to the hook.
        decode_timeout_s: arm the PR 2 step watchdog around every batched
            prefill/decode dispatch (default: the
            ``PADDLE_TPU_DECODE_TIMEOUT_S`` env var): a stalled XLA call
            produces a crash-dump bundle naming the stuck phase instead
            of a silent hang, and :meth:`health` exposes the progress age
            a supervisor uses for stall detection.
        prefix_cache: retain completed requests' KV rows behind a
            content-addressed prefix index; admissions sharing a cached
            prompt prefix copy the row and prefill only the tail
            (docs/serving.md "Decode fast path").
        prefix_block: prefix-match granularity in tokens (the index
            registers cached rows at block-boundary prefixes — the
            vLLM-style block hash; smaller blocks match more, hash more).
        speculative_k: verify ``k`` positions per decode dispatch
            (``k - 1`` drafted tokens; 0/1 disables).  Greedy requests
            accept the matched draft prefix — up to ``k`` tokens per pool
            read; sampled (temperature > 0) requests fall back to one
            token per step, correctly sampled, in the same program.
        drafter: ``drafter(context_ids, n) -> n proposed ids`` (default
            :class:`~paddle_tpu.serving.speculative.NgramDrafter`) — the
            seam a learned draft model plugs into.
        kv_dtype: None (model dtype) or ``"int8"`` — store the K/V pools
            quantized with per-row scales, dequantized inside the
            attention read (half the pool bytes → 2x slots in the same
            HBM; see serving/kv_quant.py).
        paged_kv: store K/V in fixed-size **pages** instead of dense
            per-slot rows (docs/serving.md "Paged KV").  A host-side
            :class:`~paddle_tpu.serving.paged_kv.PageAllocator` owns the
            refcounted page pool; each slot carries an int32 page table
            that is just another decode-program operand, so the decode
            signature count stays at ONE per config.  HBM scales with
            the tokens actually resident (admission reserves exactly the
            pages a request can write and blocks on page exhaustion),
            sequences may grow past ``max_len`` up to
            ``max_pages_per_slot * page_size``, and prefix-cache hits
            share pages by reference with copy-on-write instead of a
            device row copy.  Greedy output is token-identical to the
            dense pool; composable with every other flag here.
        page_size: positions per page (default ``prefix_block``, 16 —
            the prefix cache's hash granularity is the natural physical
            allocation unit: block-aligned hits share only whole pages).
        num_pages: physical pages in the pool (default
            ``max_slots * ceil(max_len / page_size)`` — dense-equivalent
            capacity; size it to the traffic, not the worst case, for
            the HBM win).
        max_pages_per_slot: page-table width per slot (default
            ``ceil(max_len / page_size)``); sets the virtual per-slot
            length ``max_pages_per_slot * page_size``, which may exceed
            ``max_len`` — long-context past the dense pool's compiled
            row length.
        decode_kernel: ``"xla"`` (default) or ``"pallas"`` — how the
            decode step READS the paged pool.  ``"pallas"`` (requires
            ``paged_kv=True``) routes the per-slot attention read
            through the fused Pallas kernel
            (kernels/paged_attention.py): the page-table walk, the int8
            dequant and the masked softmax run in one custom call that
            DMAs pages straight from HBM — no ``[B, L_virt, ...]``
            gather temp, int8 pools stream int8 bytes.  Greedy output
            is token-identical to the XLA read; decode stays ONE
            compiled signature and composes with every flag here.  On
            CPU the kernel runs in Pallas interpret mode (auto-detected;
            the parity gate tier-1 exercises).
        sample_on_device: fuse temperature/top-k/greedy sampling into the
            decode program (per-slot params + counter-based PRNG keys);
            only ``[B(, k)]`` token ids cross the host boundary per step.
            False restores the host sampler (``_sample_row``) — the
            per-request numpy RNG stream, at a ``[B, V]`` logits transfer
            per step.
        adapters: an :class:`~paddle_tpu.serving.adapters.AdapterRegistry`
            — serve many LoRA-fine-tuned variants of the base model from
            this one engine (docs/serving.md "Multi-LoRA serving"):
            ``submit(adapter=name)`` rows gather that adapter's factors
            from stacked device banks inside the same decode program
            (bank row 0 = the exact base model).  The registry persists
            across supervisor rebuilds; bank residency (refcount+LRU,
            admission-time cold loads, fully-pinned-bank backpressure)
            is fresh per engine build.
        weight_dtype: None (model dtype) or ``"int8"`` — store the
            serving weight operands quantized per output channel
            (adapters/weight_quant.py), dequantized at the top of each
            serving jit: HBM between steps holds the int8 bytes (the
            weight half of the decode HBM bound; parity-gated).
    """

    def __init__(self, model, tokenizer=None, max_slots: int = 8,
                 max_len: int = 256, max_queue: Optional[int] = None,
                 prefill_batch: Optional[int] = None, eos_token_id=None,
                 auto_start: bool = True,
                 admission_hook: Optional[Callable] = None,
                 redispatch_hook: Optional[Callable] = None,
                 decode_timeout_s: Optional[float] = None,
                 prefix_cache: bool = False,
                 prefix_block: int = 16,
                 speculative_k: int = 0,
                 drafter: Optional[Callable] = None,
                 kv_dtype: Optional[str] = None,
                 sample_on_device: bool = True,
                 paged_kv: bool = False,
                 page_size: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 max_pages_per_slot: Optional[int] = None,
                 decode_kernel: str = "xla",
                 adapters=None,
                 weight_dtype: Optional[str] = None,
                 host_prefix_mb: Optional[float] = None,
                 host_prefix=None):
        self.model = model
        self.tokenizer = tokenizer
        self.max_slots = int(max_slots)
        self.max_len = int(max_len)
        if self.max_slots < 1 or self.max_len < 2:
            raise ValueError("need max_slots >= 1 and max_len >= 2")
        cfg = getattr(getattr(model, "gpt", model), "config", None)
        limit = getattr(cfg, "max_position_embeddings", None)
        if limit is not None and self.max_len > int(limit):
            raise ValueError(
                f"max_len={self.max_len} exceeds the model's "
                f"max_position_embeddings={limit}")
        self.max_queue = (2 * self.max_slots if max_queue is None
                          else int(max_queue))
        self.prefill_batch = (min(4, self.max_slots) if prefill_batch is None
                              else max(1, min(int(prefill_batch),
                                              self.max_slots)))
        self.eos_token_id = eos_token_id
        self._auto_start = bool(auto_start)
        self.admission_hook = admission_hook
        self.redispatch_hook = redispatch_hook
        if decode_timeout_s is None:
            raw = os.environ.get("PADDLE_TPU_DECODE_TIMEOUT_S", "")
            try:
                decode_timeout_s = float(raw)
            except ValueError:
                decode_timeout_s = None
        self._decode_timeout_s = (decode_timeout_s
                                  if decode_timeout_s and
                                  decode_timeout_s > 0 else None)
        # -- decode fast-path flags (each composable, each keeping the
        # ONE-compiled-decode-signature invariant per engine config) --------
        if kv_dtype not in (None, "int8"):
            raise ValueError(f"kv_dtype must be None or 'int8', "
                             f"got {kv_dtype!r}")
        self.kv_dtype = kv_dtype
        self._kv_quant = kv_dtype == "int8"
        k = int(speculative_k)
        if k < 0:
            raise ValueError(f"speculative_k must be >= 0, got {k}")
        self.speculative_k = k
        self._spec_width = max(1, k)          # decode dispatch width
        self._drafter = (drafter if drafter is not None
                         else (NgramDrafter() if self._spec_width > 1
                               else None))
        self.sample_on_device = bool(sample_on_device)
        self._prefix = (PrefixIndex(block=prefix_block) if prefix_cache
                        else None)
        # -- multi-LoRA adapters (docs/serving.md "Multi-LoRA serving"):
        # the registry is PERSISTENT (shared across supervisor rebuilds);
        # the residency tracker — bank slots, pins, LRU — is fresh per
        # engine build, so a rebuilt engine starts with empty banks and
        # zero pins by construction --------------------------------------
        self.adapter_registry = adapters
        self._adapters = None
        if adapters is not None:
            if cfg is None:
                raise ValueError(
                    "adapters= needs a GPT-style model (config with "
                    "hidden_size/num_layers) to size the banks")
            self._adapters = adapters.residency()
        self._adapter_uploads: dict = {}     # name -> bank slot, pending
        self._adapter_load_times: list = []  # cold-load wall seconds
        self._adapter_stalled = False
        # -- int8 base weights (serving/adapters/weight_quant.py) --------
        if weight_dtype not in (None, "int8"):
            raise ValueError(f"weight_dtype must be None or 'int8', "
                             f"got {weight_dtype!r}")
        self.weight_dtype = weight_dtype
        self._weight_quant = weight_dtype == "int8"
        self._weight_bytes = 0
        # -- paged KV pool (docs/serving.md "Paged KV") ----------------------
        self.paged_kv = bool(paged_kv)
        if not self.paged_kv and (page_size is not None or
                                  num_pages is not None or
                                  max_pages_per_slot is not None):
            raise ValueError("page_size/num_pages/max_pages_per_slot "
                             "require paged_kv=True")
        if decode_kernel not in ("xla", "pallas"):
            raise ValueError(f"decode_kernel must be 'xla' or 'pallas', "
                             f"got {decode_kernel!r}")
        if decode_kernel == "pallas" and not paged_kv:
            raise ValueError(
                "decode_kernel='pallas' requires paged_kv=True — the "
                "fused kernel reads the pool through the page table")
        self.decode_kernel = decode_kernel
        self._page_alloc: Optional[PageAllocator] = None
        self._page_tables = None
        if self.paged_kv:
            P = int(prefix_block if page_size is None else page_size)
            if P < 1:
                raise ValueError(f"page_size must be >= 1, got {P}")
            dense_pages = -(-self.max_len // P)          # ceil
            n_pt = (dense_pages if max_pages_per_slot is None
                    else int(max_pages_per_slot))
            if n_pt < 1:
                raise ValueError(
                    f"max_pages_per_slot must be >= 1, got {n_pt}")
            n_pages = (self.max_slots * dense_pages if num_pages is None
                       else int(num_pages))
            self._page_alloc = PageAllocator(n_pages, P)
            self._max_pages_per_slot = n_pt
            # virtual per-slot length: how far a slot's page table can
            # address — may exceed max_len (long context), capped by the
            # model's position-embedding table
            virt = n_pt * P
            self._limit = virt if limit is None else min(virt, int(limit))
        else:
            self._limit = self.max_len

        # -- host-DRAM prefix tier (kv_tier.py; docs/serving.md "KV
        # tiering & conversations"): strictly opt-in.  host_prefix_mb=
        # builds an engine-OWNED tier (closed by shutdown);
        # host_prefix= shares a pre-built tier across supervisor
        # rebuilds / replicas (never closed by this engine) ---------------
        self._host_tier = None
        self._own_host_tier = False
        if host_prefix is not None and host_prefix_mb is not None:
            raise ValueError(
                "pass host_prefix_mb= (engine-owned tier) OR host_prefix= "
                "(shared tier), not both")
        if host_prefix is not None or host_prefix_mb is not None:
            if not (self.paged_kv and self._prefix is not None):
                raise ValueError("the host prefix tier requires "
                                 "paged_kv=True and prefix_cache=True")
            if host_prefix is not None:
                if host_prefix.block != self._prefix.block:
                    raise ValueError(
                        f"host tier block={host_prefix.block} does not "
                        f"match prefix_block={self._prefix.block}")
                self._host_tier = host_prefix
            else:
                self._host_tier = HostPrefixTier(
                    capacity_mb=float(host_prefix_mb),
                    block=self._prefix.block)
                self._own_host_tier = True

        self._pool = SlotPool(self.max_slots)
        self._queue: deque = deque()
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = False
        self._draining = False
        self._dead: Optional[BaseException] = None
        self._last_progress = time.perf_counter()
        self._thread: Optional[threading.Thread] = None
        self._spawning = False
        self._built = False
        self._values = None
        self._pools = None          # (kpools, vpools[, kscales, vscales])
        self._pool_bytes = 0
        n_rows = self.max_slots + 1           # + scratch row
        self._ids = np.zeros((n_rows, self._spec_width), np.int64)
        # free / cached / scratch rows park at the pool's addressable end
        # (max_len, or the paged virtual length): the decode scatter DROPS
        # their writes (mode="drop"), so K/V retained by the prefix cache
        # is never clobbered by an idle slot's garbage step
        self._park = (self._max_pages_per_slot * self._page_alloc.page_size
                      if self.paged_kv else self.max_len)
        self._lengths = np.full(n_rows, self._park, np.int32)
        if self.paged_kv:
            # per-slot page tables, sentinel-filled: entry num_pages is
            # out of range, so a gather clamps it (masked read) and a
            # scatter at it DROPS the write — unallocated virtual
            # positions are unwritable by construction
            self._page_tables = np.full(
                (n_rows, self._max_pages_per_slot),
                self._page_alloc.num_pages, np.int32)
        # per-slot sampling params + PRNG base keys, pool-resident mirrors
        # uploaded with every dispatch (device draws fold the key with the
        # row's position, so no key state ever crosses back to the host)
        self._temps = np.zeros(n_rows, np.float32)
        self._topks = np.zeros(n_rows, np.int32)
        self._keys = np.zeros((n_rows, 2), np.uint32)
        # per-slot adapter bank row (0 = the zero adapter: base model)
        self._aids = np.zeros(n_rows, np.int32)
        self._counts = {"submitted": 0, "completed": 0, "rejected": 0,
                        "cancelled": 0, "deadline_expired": 0, "failed": 0,
                        "decode_steps": 0, "prefill_batches": 0,
                        "tokens": 0, "resubmitted": 0, "redispatched": 0,
                        "interrupted": 0, "prefix_hits": 0,
                        "prefix_misses": 0, "prefix_evictions": 0,
                        "prefix_inserts": 0, "spec_drafted": 0,
                        "spec_accepted": 0, "page_cow_copies": 0,
                        "page_alloc_stalls": 0, "adapter_hits": 0,
                        "adapter_loads": 0, "adapter_evictions": 0,
                        "adapter_load_stalls": 0, "host_prefix_hits": 0,
                        "host_prefix_promotes": 0}
        self._active_pages = 0     # pages referenced by in-flight requests
        self._cached_pages = 0     # pages referenced by prefix entries
        self._page_stalled = False
        # HBM ownership ledger rows (observability/perfscope.py): one per
        # long-lived device allocation this build owns, registered by
        # _build and released by shutdown — a rebuilt engine registers
        # fresh rows, so leaked ledger bytes mean leaked HBM
        self._ledger_rows: list = []
        self._ledger_prefix = None     # nested sub-account of kv_pool
        self._row_bytes = 0            # dense pool: bytes per slot row
        self._was_training = model.training
        model.eval()
        # interpreter exit with a live scheduler thread mid-XLA-call
        # aborts the process; the weakref keeps the hook from pinning the
        # engine alive
        ref = weakref.ref(self)
        atexit.register(lambda: (lambda e: e and e.shutdown())(ref()))

    # -- request API ---------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int = 16, eos_token_id=...,
               temperature: float = 0.0, top_k: int = 0, seed: int = 0,
               deadline_s: Optional[float] = None,
               stream: Optional[Callable[[int], None]] = None,
               adapter: Optional[str] = None,
               journey=None,
               conversation: Optional[str] = None) -> RequestHandle:
        """Queue one request; returns a Future-style handle.  Raises
        :class:`QueueFullError` when the bounded admission queue is at
        capacity (backpressure: the caller sheds load or retries) and
        ValueError when the request cannot fit a slot.  ``adapter``
        names a registered LoRA adapter (``Engine(adapters=registry)``);
        unknown names and ranks that can never fit the bank raise the
        registry's typed errors HERE, not after queueing.  ``journey``
        is an optional :class:`~paddle_tpu.observability.journey.Journey`
        the engine appends its phase records to (engine queue wait,
        adapter/page stalls, prefill, each decode dispatch) — the
        request-scoped trace context the gateway threads through the
        whole serving path (docs/observability.md "Request journeys").
        ``conversation`` qualifies the prefix-cache namespace to
        ``(adapter, conversation)`` — turn N+1 of the same conversation
        re-uses turn N's cached KV and pays tail-prefill only
        (docs/serving.md "KV tiering & conversations")."""
        # lock-free monitor-flag reads: _dead/_stop/_draining make single
        # benign transitions; at worst a racing submit lands one sweep
        # late and fails through the death classification instead
        if self._dead is not None:  # tpu-lint: ok(concurrency)
            raise EngineDeadError(self._dead) from self._dead
        if self._stop:
            raise EngineClosedError("engine is shut down")
        if self._draining:
            raise EngineDrainingError(
                "engine is draining; no new admissions")
        if isinstance(prompt, str):
            if self.tokenizer is None:
                raise ValueError("string prompt needs a tokenizer")
            prompt = self.tokenizer.encode(prompt)
        ids = np.asarray(
            prompt._value if isinstance(prompt, Tensor) else prompt
        ).astype(np.int64).reshape(-1)
        if ids.size < 1:
            raise ValueError("empty prompt")
        if int(max_new_tokens) < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if ids.size + int(max_new_tokens) > self._limit:
            what = ("paged limit (max_pages_per_slot * page_size, capped "
                    "by the model's positions)" if self.paged_kv
                    else "max_len")
            raise ValueError(
                f"prompt ({ids.size}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds {what}={self._limit}")
        if self.paged_kv and self._pages_for(
                ids.size + int(max_new_tokens)) > self._page_alloc.num_pages:
            raise ValueError(
                f"request needs {self._pages_for(ids.size + int(max_new_tokens))} "
                f"pages but the pool has only {self._page_alloc.num_pages}")
        if adapter is not None:
            from .adapters.registry import AdapterRankError
            if self._adapters is None:
                raise ValueError(
                    "this engine has no adapter registry "
                    "(Engine(adapters=AdapterRegistry(...)))")
            entry = self.adapter_registry.get(adapter)   # typed: unknown
            if entry.rank > self.adapter_registry.max_rank:
                raise AdapterRankError(
                    f"adapter {adapter!r} rank {entry.rank} exceeds the "
                    f"bank width max_rank="
                    f"{self.adapter_registry.max_rank}: it can never "
                    f"become resident")
        eos = self.eos_token_id if eos_token_id is ... else eos_token_id
        req = RequestHandle(self, ids, max_new_tokens, eos, temperature,
                            top_k, seed, deadline_s, stream,
                            adapter=adapter, journey=journey,
                            conversation=conversation)
        hook = self.admission_hook
        if hook is not None:
            try:
                hook(req, self.load())
            except Exception:
                with self._lock:
                    self._counts["rejected"] += 1
                flight.record("serving", "reject", request=req.request_id,
                              reason="admission_hook")
                registry().counter(
                    SERVING_REQUESTS, "serving requests by outcome").inc(
                    1.0, labels={"outcome": "rejected"})
                raise
        with self._lock:
            if len(self._queue) >= self.max_queue:
                self._counts["rejected"] += 1
                self._gauges_locked()
                flight.record("serving", "reject", request=req.request_id,
                              queue_depth=len(self._queue),
                              max_queue=self.max_queue)
                registry().counter(
                    SERVING_REQUESTS, "serving requests by outcome").inc(
                    1.0, labels={"outcome": "rejected"})
                raise QueueFullError(
                    f"admission queue full ({self.max_queue}); retry later")
            self._queue.append(req)
            self._counts["submitted"] += 1
            self._gauges_locked()
        registry().counter(SERVING_REQUESTS,
                           "serving requests by outcome").inc(
            1.0, labels={"outcome": "submitted"})
        if self._auto_start:
            self.start()
        self._wake.set()
        return req

    def resubmit(self, req: RequestHandle) -> RequestHandle:
        """Re-enqueue a handle taken off a dead engine (the supervisor's
        re-dispatch path): the SAME handle object rides into this
        engine's queue, so a caller blocked on ``result()`` never notices
        the failover.  Only zero-token handles are accepted — re-running
        a request that already streamed tokens would silently duplicate
        delivered output.  Bypasses the admission hook and the queue
        bound (the request was admitted once already)."""
        if req._tokens:
            raise ValueError(
                f"request {req.request_id} already streamed "
                f"{len(req._tokens)} token(s); re-dispatch would "
                f"duplicate them")
        if req.adapter is not None and self._adapters is None:
            raise ValueError(
                f"request {req.request_id} needs adapter "
                f"{req.adapter!r} but this engine has no adapter "
                f"registry")
        if self._dead is not None:
            raise EngineDeadError(self._dead) from self._dead
        if self._stop:
            raise EngineClosedError("engine is shut down")
        req._engine = self
        req._state = "queued"
        req._torn = False       # live again: this engine may emit for it
        req.t_queue = time.perf_counter()   # journey engine_queue restarts
        req._stall_t0 = None
        req._stall_kind = None
        req.slot = None
        req._prefix_src = None  # the dead engine's pool (and index) is gone
        req._prefix_match = 0
        req._pages = None
        req._cow = None
        req._promote = None     # promote refs die with the dead engine's
        req.prefix_hit = False  # admission (_release_pages_locked)
        req._adapter_slot = 0    # the dead engine's banks (and pins) died
        req._adapter_pinned = False
        req.redispatches += 1
        with self._lock:
            self._queue.append(req)
            self._counts["resubmitted"] += 1
            self._gauges_locked()
        flight.record("serving", "resubmit", request=req.request_id,
                      redispatches=req.redispatches)
        registry().counter(
            SERVING_REDISPATCHED,
            "requests re-dispatched after an engine death").inc(
            1.0, labels={"layer": "supervisor"})
        if self._auto_start:
            self.start()
        self._wake.set()
        return req

    def start(self):
        """Start the scheduler thread (idempotent).  The check-and-spawn
        runs under the engine lock: two racing callers (e.g. a gateway
        handler submitting while a supervisor resubmits parked work)
        must never BOTH see a missing thread and spawn two schedulers —
        the second would dispatch against a pool the first is still
        building."""
        if self._dead is not None:
            raise EngineDeadError(self._dead) from self._dead
        if self._stop:
            raise EngineClosedError("engine is shut down")
        # double-checked: the common already-running path stays lock-free
        # (submit calls start() per request); a stale read just falls
        # through to the locked re-check.  The claim happens under the
        # lock but Thread.start() runs OUTSIDE it — the new scheduler's
        # first sweep takes this same lock, and making it queue behind
        # the spawner costs the admission loop its head start.  The
        # _spawning flag covers the claimed-but-not-yet-alive window so
        # two racing callers can never both spawn.
        if self._thread is None or not self._thread.is_alive():
            t = None
            with self._lock:
                if not self._spawning and (self._thread is None or
                                           not self._thread.is_alive()):
                    self._spawning = True
                    t = threading.Thread(
                        target=self._loop, name="paddle-tpu-serving",
                        daemon=True)
                    self._thread = t
            if t is not None:
                try:
                    t.start()
                finally:
                    with self._lock:
                        self._spawning = False

    def join(self, timeout: Optional[float] = None) -> bool:
        """Block until queue and slots are empty; False on timeout."""
        deadline = None if timeout is None else time.perf_counter() + timeout
        while True:
            with self._lock:
                idle = not self._queue and self._pool.n_active == 0
            if idle:
                return True
            if deadline is not None and time.perf_counter() > deadline:
                return False
            time.sleep(0.005)

    def drain(self, deadline_s: float = 30.0) -> bool:
        """Graceful shutdown, phase one: stop admission (new submits
        raise :class:`EngineDrainingError` and ``load()`` advertises
        not-alive so routers stop picking this replica) while the
        scheduler keeps finishing every queued and in-flight request.
        Returns True when all of them completed before the deadline —
        the engine is then idle and a ``shutdown()`` drops nothing."""
        with self._lock:
            self._draining = True
            depth, active = len(self._queue), self._pool.n_active
        flight.record("serving", "drain_begin", queue_depth=depth,
                      active_slots=active, deadline_s=float(deadline_s))
        if (depth or active) and self._dead is None and not self._stop:
            self.start()        # pending work with no scheduler: run it out
        ok = self.join(timeout=deadline_s) and self._dead is None
        flight.record("serving", "drain_done", drained=ok)
        return ok

    def undrain(self):
        """Reverse :meth:`drain` on a replica that never finished
        leaving — the warm-pool route-in (ISSUE 20): a parked spare is
        built and immediately drained (``load()`` advertises not-alive,
        so it refuses work while parked) until a flash scale-up routes
        it back into the fleet.  No-op on a live engine; raises on a
        dead or shut-down one, which must never re-enter a router."""
        if self._dead is not None:
            raise EngineDeadError(self._dead) from self._dead
        if self._stop:
            raise EngineClosedError("engine is shut down")
        with self._lock:
            was = self._draining
            self._draining = False
        if was:
            flight.record("serving", "undrain")

    def abandon(self, cause: Optional[BaseException] = None):
        """A supervisor declares this engine dead from OUTSIDE the
        scheduler thread (decode stall: the thread is stuck inside an
        XLA call and cannot be killed).  The engine stops accepting work
        and its requests are classified exactly as a scheduler crash —
        zero-token requests are offered to the redispatch hook, streamed
        ones get :class:`RequestInterruptedError`.  Idempotent; a no-op
        on an engine that is already dead or shut down."""
        if self._dead is not None or self._stop:
            return
        self._fail_as_dead(cause or EngineStalledError(
            "engine abandoned by its supervisor"))
        self._wake.set()        # a parked scheduler wakes up and exits

    def shutdown(self):
        """Stop the scheduler; in-flight and queued requests fail with
        EngineClosedError.  Restores the model's train/eval mode."""
        if self._stop:
            return
        # monitor flag: single False->True transition, polled by the
        # scheduler loop; a stale read costs one extra 20 ms iteration
        self._stop = True  # tpu-lint: ok(concurrency)
        self._wake.set()
        if self._thread is not None:
            # a DEAD engine's thread is exiting (or, after abandon(),
            # permanently stuck in an XLA call) — don't wait long for it
            self._thread.join(timeout=30 if self._dead is None else 2)
        err = EngineClosedError("engine shut down")
        with self._lock:
            pending = list(self._queue) + list(self._pool.active().values())
            self._queue.clear()
            for slot in list(self._pool.active()):
                req = self._pool.free(slot)
                self._release_pages_locked(req)
                if self._adapters is not None:
                    self._unpin_adapter_locked(req)
            if self._prefix is not None:
                # the pool the cached rows/pages point into is going away
                for e in self._prefix.drop_all():
                    if self.paged_kv and e.pages:
                        for p in e.pages:
                            self._page_alloc.deref(p)
                        self._cached_pages -= len(e.pages)
                for slot in list(self._pool.cached()):
                    self._pool.release_cached(slot)
            if self.paged_kv:
                self._page_alloc.check()     # zero leaked pages at teardown
            if self._adapters is not None:
                self._adapters.check()       # zero leaked adapter pins
            self._gauges_locked()
            ledger_rows, self._ledger_rows = self._ledger_rows, []
            self._ledger_prefix = None
        # this build's HBM is going away with its pools/banks: release
        # the ledger rows (a leaked row here means leaked device bytes —
        # the chaos lane asserts zero after the kill matrix)
        for row in ledger_rows:
            row.release()
        # an engine-OWNED host tier dies with the engine; a SHARED tier
        # (host_prefix=) outlives it on purpose — that is the rebuild /
        # replica survival story, and whoever built it closes it
        if self._own_host_tier and self._host_tier is not None:
            self._host_tier.close()
        _steps.record_memory_stats()
        for req in pending:
            req._finish(err)
        if self._was_training:
            self.model.train()

    close = shutdown

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False

    # -- introspection -------------------------------------------------------
    def queue_depth(self) -> int:
        """Unadmitted queued requests right now (O(1), one lock hop)."""
        with self._lock:
            return len(self._queue)

    def slots_in_use(self) -> int:
        """Slots currently owned by in-flight requests (O(1) — the pool
        keeps the count; no slot-array scan).  Cached (prefix-retained)
        rows don't count: they are reclaimable on demand."""
        with self._lock:
            return self._pool.n_active

    def adapter_resident(self, name: str) -> bool:
        """True when the LoRA adapter already occupies a bank row in
        THIS build (loaded or mid-upload) — the router's locality
        tiebreak: dispatching onto a resident replica skips the
        admission-time cold load entirely."""
        with self._lock:
            return (self._adapters is not None and
                    self._adapters.slot_of(name) is not None)

    def load(self) -> dict:
        """One-lock-hop load snapshot for external admission/routing
        (queue depth, slot occupancy, capacity, liveness).  Every field
        comes from O(1) counters — safe to poll per-request from a
        gateway without perturbing the scheduler."""
        with self._lock:
            out = {
                "queue_depth": len(self._queue),
                "slots_in_use": self._pool.n_active,
                "cached_slots": self._pool.n_cached,
                "max_slots": self.max_slots,
                "max_queue": self.max_queue,
                "max_len": self.max_len,
                "alive": (self._dead is None and not self._stop and
                          not self._draining),
                "draining": self._draining,
            }
            if self.paged_kv:
                out["kv_pages_free"] = self._page_alloc.n_free
                out["kv_num_pages"] = self._page_alloc.num_pages
            return out

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._counts)
            out["active_slots"] = self._pool.n_active
            out["queue_depth"] = len(self._queue)
            out["slot_allocs"] = self._pool.alloc_total
            out["slot_reuses"] = self._pool.reuse_total
            out["cached_slots"] = self._pool.n_cached
            out["prefix_entries"] = (0 if self._prefix is None
                                     else len(self._prefix))
            out["kv_pool_bytes"] = self._pool_bytes
            out["weight_bytes"] = self._weight_bytes
            if self._adapters is not None:
                out["adapters_resident"] = self._adapters.n_resident
                out["adapters_pinned"] = self._adapters.n_pinned
                out["adapter_bank_capacity"] = self._adapters.capacity
            if self.paged_kv:
                out["kv_num_pages"] = self._page_alloc.num_pages
                out["kv_page_size"] = self._page_alloc.page_size
                out["kv_pages_free"] = self._page_alloc.n_free
                out["kv_pages_used"] = self._page_alloc.n_used
                out["kv_pages_active"] = self._active_pages
                out["kv_pages_cached"] = self._cached_pages
        if self._host_tier is not None:
            out["host_prefix"] = self._host_tier.stats()
        out.update(self.compile_stats())
        return out

    def pool_bytes(self) -> int:
        """Total bytes of the device KV pools (+ int8 scale buffers);
        0 before the first admission builds them."""
        with self._lock:
            return self._pool_bytes

    def weight_bytes(self) -> int:
        """Device bytes of the serving weight operands as STORED (int8 +
        scale sidecars under ``weight_dtype='int8'``); 0 before the
        first admission builds them."""
        with self._lock:
            return self._weight_bytes

    def compile_stats(self) -> dict:
        """Distinct jit signatures per entry point (retrace sentinel
        counters; decode must stay at 1 — THE continuous-batching
        invariant, with every fast-path flag on)."""
        pf = getattr(self, "_prefill_fn", None)
        dc = getattr(self, "_decode_fn", None)
        tl = getattr(self, "_tail_fn", None)
        cp = getattr(self, "_copy_fn", None)
        return {
            "prefill_compiles": len(pf._signatures) if pf is not None else 0,
            "decode_compiles": len(dc._signatures) if dc is not None else 0,
            "tail_prefill_compiles":
                len(tl._signatures) if tl is not None else 0,
            "prefix_copy_compiles":
                len(cp._signatures) if cp is not None else 0,
        }

    # -- jitted pieces -------------------------------------------------------
    def _build(self):
        import contextlib

        import jax
        import jax.numpy as jnp

        from ..nn.functional_call import _swapped_state, state_values
        from .kv_quant import quantize_rows

        model = self.model
        n_rows, L = self.max_slots + 1, self.max_len
        quant = self._kv_quant
        on_device = self.sample_on_device
        self._values = state_values(model)

        def _kv_struct():
            def f(vals, ii):
                with _swapped_state(model, vals):
                    _, caches = model(Tensor(ii, _internal=True),
                                      use_cache=True)
                return [(k._value, v._value) for k, v in caches]
            return jax.eval_shape(f, self._values,
                                  jnp.zeros((1, 1), jnp.int64))

        kv = _kv_struct()

        def _leaf_bytes(leaves):
            return sum(int(np.prod(x.shape)) * jnp.dtype(x.dtype).itemsize
                       for x in leaves
                       if hasattr(x, "shape") and hasattr(x, "dtype"))

        if self._weight_quant:
            # int8 base weights: the STORED serving operands go int8 with
            # per-channel f32 scales; every jitted entry dequantizes at
            # the top of its trace, so HBM between steps holds int8 bytes
            # (docs/serving.md "Multi-LoRA serving").
            from .adapters.weight_quant import (dequantize_state,
                                                quantize_state, state_bytes)
            self._values, _wq_dtypes = quantize_state(self._values)
            wbytes = state_bytes(self._values)

            def _dq(vals, _d=_wq_dtypes):
                return dequantize_state(vals, _d)
        else:
            wbytes = _leaf_bytes(self._values.values())

            def _dq(vals):
                return vals
        wrow = _perfscope.ledger().register(
            "weights", wbytes,
            detail=("serving weight operands, int8 + scales"
                    if self._weight_quant else "serving weight operands"))
        with self._lock:
            self._weight_bytes = wbytes
            self._ledger_rows.append(wrow)
        registry().gauge(
            SERVING_WEIGHT_BYTES,
            "device bytes of the serving weight operands as stored").set(
            float(wbytes))

        # -- multi-LoRA adapter banks: fixed-shape device operands every
        # serving dispatch carries (row 0 = the zero adapter) -------------
        use_adp = self._adapters is not None
        if use_adp:
            from .adapters.lora import adapter_scope as _adapter_scope
            areg = self.adapter_registry
            Rcap = self._adapters.capacity
            r_max, n_layers, h = areg.max_rank, areg.num_layers, areg.hidden
            self._abank = jnp.zeros((Rcap + 1, n_layers, h, r_max),
                                    jnp.float32)
            self._bbank = jnp.zeros((Rcap + 1, n_layers, r_max, 3 * h),
                                    jnp.float32)
            self._ascale = jnp.zeros((Rcap + 1,), jnp.float32)
            brow = _perfscope.ledger().register(
                "adapter_bank", areg.bank_nbytes(),
                detail=f"stacked LoRA banks, {Rcap} rows + zero adapter")
            with self._lock:
                self._ledger_rows.append(brow)

        # Pallas decode kernel (kernels/paged_attention.py): the scope is
        # entered inside the DECODE jit only, so that one program's paged
        # attention read traces through the fused kernel while prefill /
        # tail-prefill keep the XLA gather — a trace-time routing
        # decision, not an operand, so the signature count is unchanged
        use_pallas_decode = self.decode_kernel == "pallas"
        if use_pallas_decode:
            from ..kernels.paged_attention import (
                decode_kernel_scope as _pk_scope)

        def _mstate(values, adp, pk=False):
            """Swapped model state, plus the batched-adapter scope when
            the dispatch carries adapter operands, plus the Pallas
            decode-kernel scope when this jit is the decode step."""
            st = contextlib.ExitStack()
            st.enter_context(_swapped_state(model, values))
            if adp is not None:
                st.enter_context(_adapter_scope(*adp))
            if pk:
                st.enter_context(_pk_scope())
            return st
        pool_dtype = jnp.int8 if quant else None
        paged = self.paged_kv
        if paged:
            # block-granular pool: [num_pages, page_size, heads, head_dim]
            # per layer — HBM holds pages, slots address them through
            # int32 page tables (just another decode operand).  int8
            # scales ride the page as a [page_size] f32 sidecar: one
            # absmax per written position, so writes stay strictly
            # incremental (nothing resident ever rescales).
            NP_ = self._page_alloc.num_pages
            P_ = self._page_alloc.page_size
            n_pt = self._max_pages_per_slot
            kpools = [jnp.zeros((NP_, P_) + tuple(k.shape[2:]),
                                pool_dtype or k.dtype) for k, _ in kv]
            vpools = [jnp.zeros((NP_, P_) + tuple(v.shape[2:]),
                                pool_dtype or v.dtype) for _, v in kv]
            if quant:
                kscales = [jnp.zeros((NP_, P_), jnp.float32) for _ in kv]
                vscales = [jnp.zeros((NP_, P_), jnp.float32) for _ in kv]
                self._pools = (kpools, vpools, kscales, vscales)
            else:
                self._pools = (kpools, vpools)
        else:
            kpools = [jnp.zeros((n_rows, L) + tuple(k.shape[2:]),
                                pool_dtype or k.dtype) for k, _ in kv]
            vpools = [jnp.zeros((n_rows, L) + tuple(v.shape[2:]),
                                pool_dtype or v.dtype) for _, v in kv]
            if quant:
                kscales = [jnp.zeros((n_rows, L), jnp.float32) for _ in kv]
                vscales = [jnp.zeros((n_rows, L), jnp.float32) for _ in kv]
                self._pools = (kpools, vpools, kscales, vscales)
            else:
                self._pools = (kpools, vpools)
        total = sum(int(np.prod(p.shape)) * p.dtype.itemsize
                    for grp in self._pools for p in grp)
        led = _perfscope.ledger()
        krow = led.register(
            "kv_pool", total,
            detail=(f"paged KV pool, {self._page_alloc.num_pages} pages"
                    if paged else f"dense KV pool, {n_rows} slot rows"))
        # prefix-cache sub-account: cached rows/pages live INSIDE the
        # pool bytes, so the ledger tracks them as a nested owner
        # (informational, never double-counted)
        prow = (led.register(
            "prefix_cache", 0, nested=True,
            detail="retained KV rows/pages (bytes inside kv_pool)")
            if self._prefix is not None else None)
        with self._lock:
            self._pool_bytes = total
            self._ledger_rows.append(krow)
            if paged:
                self._page_alloc.bytes_per_page = total // max(NP_, 1)
            else:
                self._row_bytes = total // n_rows
            if prow is not None:
                self._ledger_prefix = prow
                self._ledger_rows.append(prow)
        registry().gauge(
            SERVING_KV_POOL_BYTES,
            "device bytes of the serving KV pools (incl. int8 scales)"
        ).set(float(total))

        def _caches_from(pools, lengths, tables=None):
            """Pool arrays → the models' per-slot static-cache protocol:
            3-tuple dense, 5-tuple dense-int8, or the paged 4/6-tuple
            forms with the page-table operand at index 3."""
            if paged:
                if quant:
                    kps, vps, kss, vss = pools
                    return [(Tensor(kp, _internal=True),
                             Tensor(vp, _internal=True), lengths, tables,
                             Tensor(ks, _internal=True),
                             Tensor(vs, _internal=True))
                            for kp, vp, ks, vs in zip(kps, vps, kss, vss)]
                kps, vps = pools
                return [(Tensor(kp, _internal=True),
                         Tensor(vp, _internal=True), lengths, tables)
                        for kp, vp in zip(kps, vps)]
            if quant:
                kps, vps, kss, vss = pools
                return [(Tensor(kp, _internal=True),
                         Tensor(vp, _internal=True), lengths,
                         Tensor(ks, _internal=True),
                         Tensor(vs, _internal=True))
                        for kp, vp, ks, vs in zip(kps, vps, kss, vss)]
            kps, vps = pools
            return [(Tensor(kp, _internal=True),
                     Tensor(vp, _internal=True), lengths)
                    for kp, vp in zip(kps, vps)]

        def _pools_from(new_caches):
            if quant:
                si = 4 if paged else 3      # scale slots in the cache tuple
                return ([c[0]._value for c in new_caches],
                        [c[1]._value for c in new_caches],
                        [c[si]._value for c in new_caches],
                        [c[si + 1]._value for c in new_caches])
            return ([c[0]._value for c in new_caches],
                    [c[1]._value for c in new_caches])

        def _fwd_last(ids_t, caches_t, gather_idx=None):
            """(per-row logits at the last real position, new caches); when
            the model exposes trunk + head, the vocab matmul runs on ONLY
            the gathered positions."""
            inner = getattr(model, "gpt", None)
            head = getattr(model, "lm_head", None)
            if inner is not None and callable(head):
                x, new_caches = inner(ids_t, caches=caches_t, use_cache=True)
                h = x._value
                h_last = (h[:, -1] if gather_idx is None
                          else h[jnp.arange(h.shape[0]), gather_idx])
                logits = head(Tensor(h_last[:, None],
                                     _internal=True))._value[:, 0]
            else:
                lg, new_caches = model(ids_t, caches=caches_t,
                                       use_cache=True)
                lg = lg._value
                logits = (lg[:, -1] if gather_idx is None
                          else lg[jnp.arange(lg.shape[0]), gather_idx])
            return logits, new_caches

        def _fwd_all(ids_t, caches_t):
            """Logits at EVERY input position — the speculative verify
            needs the model's choice after each drafted prefix."""
            inner = getattr(model, "gpt", None)
            head = getattr(model, "lm_head", None)
            if inner is not None and callable(head):
                x, new_caches = inner(ids_t, caches=caches_t, use_cache=True)
                logits = head(Tensor(x._value, _internal=True))._value
            else:
                lg, new_caches = model(ids_t, caches=caches_t,
                                       use_cache=True)
                logits = lg._value
            return logits, new_caches

        def _sample_rows(lg, temps, topks, keys):
            """Device sampler, one row each: greedy at temp 0, else
            temperature + optional top-k via Gumbel-max (categorical
            sampling without materializing probabilities)."""
            greedy = jnp.argmax(lg, axis=-1)

            def row(l_row, temp, k, key):
                l32 = l_row.astype(jnp.float32) / jnp.maximum(temp, 1e-6)
                v = l_row.shape[-1]
                srt = jnp.sort(l32)                 # ascending
                kth = srt[jnp.clip(v - k, 0, v - 1)]
                keep = (k <= 0) | (l32 >= kth)
                masked = jnp.where(keep, l32, -1e30)
                g = jax.random.gumbel(key, masked.shape, jnp.float32)
                return jnp.argmax(masked + g)

            sampled = jax.vmap(row)(lg, temps, topks, keys)
            return jnp.where(temps > 0, sampled, greedy)

        def _step_keys(keys, positions):
            """Counter-based per-draw keys: fold the row's base key with
            the position its logits sit at — stateless, so no key state
            ever returns to the host, and the draw for 'token after
            position p' is identical whichever path (cold prefill, tail
            prefill, decode) produced it."""
            return jax.vmap(jax.random.fold_in)(keys, positions)

        def prefill(values, ids, pools, slot_idx, prompt_lens, temps,
                    topks, keys, adp=None):
            # the per-request caches are BUILT inside this jit with a
            # python-int length 0 (static prefill: the prompt keeps the
            # causal flash path), then the filled rows scatter into the
            # pool at each request's slot; padding rows target the scratch
            # slot.  int8 pools quantize at the scatter (the prompt math
            # itself stays full precision).
            n = ids.shape[0]
            caches_t = [
                (Tensor(jnp.zeros((n, L) + tuple(k.shape[2:]), k.dtype),
                        _internal=True),
                 Tensor(jnp.zeros((n, L) + tuple(v.shape[2:]), v.dtype),
                        _internal=True), 0)
                for k, v in kv]
            with _mstate(_dq(values), adp):
                logits, new_caches = _fwd_last(
                    Tensor(ids, _internal=True), caches_t,
                    gather_idx=prompt_lens - 1)
            if quant:
                kpools_, vpools_, kscales_, vscales_ = pools
                kq = [quantize_rows(c[0]._value) for c in new_caches]
                vq = [quantize_rows(c[1]._value) for c in new_caches]
                kpools_ = [kp.at[slot_idx].set(q)
                           for kp, (q, _) in zip(kpools_, kq)]
                vpools_ = [vp.at[slot_idx].set(q)
                           for vp, (q, _) in zip(vpools_, vq)]
                kscales_ = [ks.at[slot_idx].set(s)
                            for ks, (_, s) in zip(kscales_, kq)]
                vscales_ = [vs.at[slot_idx].set(s)
                            for vs, (_, s) in zip(vscales_, vq)]
                pools = (kpools_, vpools_, kscales_, vscales_)
            else:
                kpools_, vpools_ = pools
                kpools_ = [kp.at[slot_idx].set(c[0]._value)
                           for kp, c in zip(kpools_, new_caches)]
                vpools_ = [vp.at[slot_idx].set(c[1]._value)
                           for vp, c in zip(vpools_, new_caches)]
                pools = (kpools_, vpools_)
            if on_device:
                toks = _sample_rows(logits, temps, topks,
                                    _step_keys(keys, prompt_lens - 1))
                return toks, pools
            return logits, pools

        def prefill_paged(values, ids, pools, tables, prompt_lens, temps,
                          topks, keys, adp=None):
            # paged cold prefill: the per-request caches are built inside
            # this jit exactly as in the dense path (python-int length 0
            # keeps the causal flash path — the prompt math is IDENTICAL,
            # so greedy outputs match the dense pool bitwise), then every
            # written position scatters into its slot's pages through the
            # batch page tables.  Padding positions (and padding lanes,
            # whose tables are all-sentinel) resolve to page id
            # num_pages, which mode="drop" discards.
            n, bucket = ids.shape
            caches_t = [
                (Tensor(jnp.zeros((n, bucket) + tuple(k.shape[2:]),
                                  k.dtype), _internal=True),
                 Tensor(jnp.zeros((n, bucket) + tuple(v.shape[2:]),
                                  v.dtype), _internal=True), 0)
                for k, v in kv]
            with _mstate(_dq(values), adp):
                logits, new_caches = _fwd_last(
                    Tensor(ids, _internal=True), caches_t,
                    gather_idx=prompt_lens - 1)
            pos = jnp.arange(bucket)
            valid = pos[None, :] < prompt_lens[:, None]          # [n, bucket]
            pslot = jnp.clip(pos // P_, 0, n_pt - 1)
            pid = jnp.where(valid, tables[:, pslot], NP_)
            off = jnp.broadcast_to((pos % P_)[None, :], pid.shape)
            if quant:
                kpools_, vpools_, kscales_, vscales_ = pools
                kq = [quantize_rows(c[0]._value) for c in new_caches]
                vq = [quantize_rows(c[1]._value) for c in new_caches]
                kpools_ = [kp.at[pid, off].set(q, mode="drop")
                           for kp, (q, _) in zip(kpools_, kq)]
                vpools_ = [vp.at[pid, off].set(q, mode="drop")
                           for vp, (q, _) in zip(vpools_, vq)]
                kscales_ = [ks.at[pid, off].set(s, mode="drop")
                            for ks, (_, s) in zip(kscales_, kq)]
                vscales_ = [vs.at[pid, off].set(s, mode="drop")
                            for vs, (_, s) in zip(vscales_, vq)]
                pools = (kpools_, vpools_, kscales_, vscales_)
            else:
                kpools_, vpools_ = pools
                kpools_ = [kp.at[pid, off].set(c[0]._value, mode="drop")
                           for kp, c in zip(kpools_, new_caches)]
                vpools_ = [vp.at[pid, off].set(c[1]._value, mode="drop")
                           for vp, c in zip(vpools_, new_caches)]
                pools = (kpools_, vpools_)
            if on_device:
                toks = _sample_rows(logits, temps, topks,
                                    _step_keys(keys, prompt_lens - 1))
                return toks, pools
            return logits, pools

        def decode_paged(values, ids, pools, lengths, tables, temps,
                         topks, keys, adp=None):
            # the paged decode is the dense decode with the page tables
            # riding along as one more int32 operand — the per-slot
            # gather/scatter lives in the model's paged cache branch, so
            # this stays ONE compiled program per engine config
            caches_t = _caches_from(pools, lengths, tables)
            with _mstate(_dq(values), adp, pk=use_pallas_decode):
                logits, new_caches = _fwd_all(
                    Tensor(ids, _internal=True), caches_t)
            pools = _pools_from(new_caches)
            if on_device:
                greedy = jnp.argmax(logits, axis=-1)
                first = _sample_rows(logits[:, 0], temps, topks,
                                     _step_keys(keys, lengths))
                toks = greedy.at[:, 0].set(first)
                return toks, pools
            return logits, pools

        def tail_prefill_paged(values, ids, pools, lengths, tables,
                               gather_idx, temps, topks, keys, adp=None):
            caches_t = _caches_from(pools, lengths, tables)
            with _mstate(_dq(values), adp):
                logits, new_caches = _fwd_last(
                    Tensor(ids, _internal=True), caches_t,
                    gather_idx=gather_idx)
            pools = _pools_from(new_caches)
            if on_device:
                toks = _sample_rows(logits, temps, topks,
                                    _step_keys(keys, lengths + gather_idx))
                return toks, pools
            return logits, pools

        def copy_pages(pools, src, dst):
            # copy-on-write: clone whole pages (K/V + scale sidecars)
            # src->dst — the writer gets a private copy of a shared page,
            # the readers' bytes are untouched.  Sentinel-padded lanes
            # gather a clamped page and then DROP the scatter: no-ops.
            return tuple([p.at[dst].set(p[jnp.clip(src, 0, NP_ - 1)],
                                        mode="drop") for p in grp]
                         for grp in pools)

        def decode(values, ids, pools, lengths, temps, topks, keys,
                   adp=None):
            # ONE batched step over every slot row (+ scratch): vector
            # lengths route the per-slot static-cache branch; idle rows
            # are parked at max_len so their writes DROP (a prefix-cached
            # row is never clobbered) and their logits are garbage that
            # is never read.  ids is [n_rows, W]: W=1 is the plain decode,
            # W=k the speculative verify — same program shape either way,
            # ONE signature per engine config.
            caches_t = _caches_from(pools, lengths)
            with _mstate(_dq(values), adp):
                logits, new_caches = _fwd_all(
                    Tensor(ids, _internal=True), caches_t)
            pools = _pools_from(new_caches)
            if on_device:
                greedy = jnp.argmax(logits, axis=-1)        # [B, W]
                first = _sample_rows(logits[:, 0], temps, topks,
                                     _step_keys(keys, lengths))
                toks = greedy.at[:, 0].set(first)
                return toks, pools
            return logits, pools

        def tail_prefill(values, ids, pools, lengths, gather_idx, temps,
                         topks, keys, adp=None):
            # prefix-cache hit path: the prompt HEAD was copied from a
            # cached row, only the tail runs through the per-slot branch
            # (rows not in this admit batch park at max_len: writes drop)
            caches_t = _caches_from(pools, lengths)
            with _mstate(_dq(values), adp):
                logits, new_caches = _fwd_last(
                    Tensor(ids, _internal=True), caches_t,
                    gather_idx=gather_idx)
            pools = _pools_from(new_caches)
            if on_device:
                toks = _sample_rows(logits, temps, topks,
                                    _step_keys(keys, lengths + gather_idx))
                return toks, pools
            return logits, pools

        def copy_rows(pools, src, dst):
            # prefix-cache hit: clone the cached rows (K/V + scales) into
            # the hitting requests' slots — a pure device-side gather/
            # scatter, bitwise-preserving; padding lanes copy scratch onto
            # itself
            return tuple([p.at[dst].set(p[src]) for p in grp]
                         for grp in pools)

        # cache pools are donated: prefill/decode update HBM in place (no
        # donation on CPU — it only warns there)
        on_cpu = jax.default_backend() == "cpu"
        self._prefill_fn = instrument_jit(
            jax.jit(prefill_paged if paged else prefill,
                    donate_argnums=() if on_cpu else (2,)),
            "serving.prefill")
        self._decode_fn = instrument_jit(
            jax.jit(decode_paged if paged else decode,
                    donate_argnums=() if on_cpu else (2,)),
            "serving.decode")
        self._tail_fn = instrument_jit(
            jax.jit(tail_prefill_paged if paged else tail_prefill,
                    donate_argnums=() if on_cpu else (2,)),
            "serving.tail_prefill")
        self._copy_fn = instrument_jit(
            jax.jit(copy_pages if paged else copy_rows,
                    donate_argnums=() if on_cpu else (0,)),
            "serving.prefix_copy")
        with self._lock:
            self._built = True
        # the build just placed the big long-lived allocations: refresh
        # the backend device-memory gauges so a pure-serving process
        # exports them without a train loop in sight
        _steps.record_memory_stats()

    # -- scheduler loop ------------------------------------------------------
    def _loop(self):
        while not self._stop and self._dead is None:
            try:
                did = self._step_once()
            except Exception as e:  # noqa: BLE001 — fail loudly, not hang
                self._fail_as_dead(e)
                raise
            with self._lock:
                # progress heartbeat: freezes while a dispatch is stuck
                # inside XLA (the supervisor's stall detector reads the
                # age via health())
                self._last_progress = time.perf_counter()
            if not did:
                self._wake.wait(0.02)
                self._wake.clear()

    def _fail_as_dead(self, cause: BaseException):
        """Death path, from the dying scheduler thread (crash) or a
        supervisor (:meth:`abandon` on a stall): mark the engine DEAD —
        a later submit() must not restart the loop over an already-failed
        pool — then classify the in-flight work by what already reached a
        consumer: requests with ZERO streamed tokens are duplication-safe
        and are offered to the redispatch hook (untaken ones fail with
        EngineDeadError); requests that streamed tokens fail with the
        typed RequestInterruptedError, never a silent replay."""
        with self._lock:
            if self._dead is not None:      # lost the race: already dead
                return
            # single None->exc transition; racing lock-free readers at
            # worst see the engine alive one sweep late
            self._dead = cause  # tpu-lint: ok(concurrency)
            queued = list(self._queue)
            active = list(self._pool.active().values())
            self._queue.clear()
            for slot in list(self._pool.active()):
                req = self._pool.free(slot)
                self._release_pages_locked(req)
                if self._adapters is not None:
                    self._unpin_adapter_locked(req)
            if self._prefix is not None:
                # dead pool: every cached row/page dies with it — a
                # rebuilt engine starts with an EMPTY index and a fresh
                # allocator (no stale-row or stale-page reuse)
                for e in self._prefix.drop_all():
                    if self.paged_kv and e.pages:
                        for p in e.pages:
                            self._page_alloc.deref(p)
                        self._cached_pages -= len(e.pages)
                for slot in list(self._pool.cached()):
                    self._pool.release_cached(slot)
            for r in queued + active:
                # freeze the token streams FIRST: after abandon() a
                # stuck dispatch may still come back and try to emit
                r._torn = True
                r._prefix_src = None
        flight.record("serving", "scheduler_error",
                      error=f"{type(cause).__name__}: {cause}",
                      queued=len(queued), active=len(active))
        fresh = [r for r in queued + active if not r._tokens]
        streamed = [r for r in active if r._tokens]
        taken_ids: set = set()
        hook = self.redispatch_hook
        if hook is not None and fresh:
            try:
                taken_ids = {id(r) for r in hook(list(fresh), cause)}
            except Exception:  # noqa: BLE001
                taken_ids = set()   # a broken hook must not mask the death
        lost = [r for r in fresh if id(r) not in taken_ids]
        with self._lock:
            self._counts["failed"] += len(lost) + len(streamed)
            self._counts["redispatched"] += len(taken_ids)
            self._counts["interrupted"] += len(streamed)
            self._gauges_locked()
        for r in lost:
            r._finish(EngineDeadError(cause))
        reg = registry()
        for r in streamed:
            flight.record("serving", "interrupted", request=r.request_id,
                          tokens=len(r._tokens))
            reg.counter(SERVING_INTERRUPTED,
                        "requests failed mid-stream by an engine death"
                        ).inc(1.0)
            r._finish(RequestInterruptedError(
                r.request_id, len(r._tokens), cause))
        if taken_ids:
            flight.record("serving", "handoff", n=len(taken_ids),
                          requests=",".join(
                              str(r.request_id) for r in fresh
                              if id(r) in taken_ids))

    def _step_once(self) -> bool:
        """One scheduler iteration: sweep, admit (batched prefill), one
        batched decode step.  Returns whether any work happened."""
        faults.fault_point("serving.scheduler")
        self._sweep()
        did = self._admit()
        did = self._decode_step() or did
        return did

    def health(self) -> dict:
        """Liveness snapshot: ``alive`` is True only while the engine can
        still take and make progress on requests.  ``progress_age_s`` is
        the time since the scheduler last completed an iteration — with
        work pending, a growing age means the thread is stuck inside a
        dispatch (the supervisor's stall signal)."""
        with self._lock:
            active, depth = self._pool.n_active, len(self._queue)
            progress_age = time.perf_counter() - self._last_progress
            built = self._built
        return {
            "alive": (self._dead is None and not self._stop and
                      not self._draining),
            "dead": self._dead is not None,
            "draining": self._draining,
            "error": (None if self._dead is None
                      else f"{type(self._dead).__name__}: {self._dead}"),
            "stopped": self._stop,
            "scheduler_running": (self._thread is not None and
                                  self._thread.is_alive()),
            "active_slots": active,
            "queue_depth": depth,
            "progress_age_s": progress_age,
            # warm = the decode program exists: dispatches are now
            # bounded, so a frozen progress age means a genuine stall
            # (cold engines legitimately sit in multi-second compiles)
            "warm": built and
            self.compile_stats()["decode_compiles"] >= 1,
        }

    def _sweep(self):
        """Evict cancelled / past-deadline requests (queued and active)."""
        now = time.perf_counter()
        to_finish = []
        with self._lock:
            for req in list(self._queue):
                if req._cancel_requested or (req.deadline is not None and
                                             now > req.deadline):
                    self._queue.remove(req)
                    outcome = ("cancelled" if req._cancel_requested
                               else "deadline_expired")
                    self._evicted_counters_locked(req, outcome)
                    to_finish.append((req, outcome))
            for slot, req in self._pool.active().items():
                if req._cancel_requested or (req.deadline is not None and
                                             now > req.deadline):
                    outcome = ("cancelled" if req._cancel_requested
                               else "deadline_expired")
                    self._evict_locked(req, outcome)
                    to_finish.append((req, outcome))
            self._gauges_locked()
        for req, outcome in to_finish:
            err = (CancelledError() if outcome == "cancelled" else
                   DeadlineExceededError(
                       f"request {req.request_id} missed its deadline"))
            req._finish(err)

    def _request_cancel(self, req: RequestHandle) -> bool:
        if req.done():
            return False
        req._cancel_requested = True
        with self._lock:
            if req in self._queue:       # not yet admitted: fail right away
                self._queue.remove(req)
                self._evicted_counters_locked(req, "cancelled")
                self._gauges_locked()
                req._finish(CancelledError())
                return True
        self._wake.set()                 # active: next sweep evicts
        return True

    # -- admission -----------------------------------------------------------
    def _pages_for(self, n_tokens: int) -> int:
        """Pages covering positions [0, n_tokens) at the pool page size."""
        return -(-int(n_tokens) // self._page_alloc.page_size)

    @staticmethod
    def _journey_admit_locked(req: RequestHandle, **attrs):
        """Close the request's engine-queue window on its journey: one
        ``engine_queue`` phase (queue entry -> admit), split at the
        stall boundary into an explicit ``adapter_stall`` /
        ``page_stall`` phase when the head-of-line request spent part of
        that window blocked on bank pins or page exhaustion — the
        attribution that turns "TTFT was 480 ms" into "300 ms of it was
        a page stall"."""
        j = req.journey
        if j is None:
            return
        stall_t0, kind = req._stall_t0, req._stall_kind
        req._stall_t0 = None
        req._stall_kind = None
        if stall_t0 is not None and kind is not None and \
                stall_t0 > req.t_queue:
            j.phase("engine_queue", req.t_queue, stall_t0 - req.t_queue,
                    **attrs)
            j.phase(kind, stall_t0, req.t_admit - stall_t0)
        else:
            j.phase("engine_queue", req.t_queue,
                    req.t_admit - req.t_queue, **attrs)

    def _mark_stall_locked(self, req: RequestHandle, kind: str):
        """First time the head-of-line request blocks this episode:
        remember when, so the admit-time journey phase can attribute the
        stalled tail of the queue wait to its cause."""
        if req._stall_t0 is None:
            req._stall_t0 = time.perf_counter()
            req._stall_kind = kind

    def _pin_adapter_locked(self, req: RequestHandle) -> bool:
        """Make the request's adapter RESIDENT and pinned before its slot
        is taken, scheduling a cold bank upload when needed.  False means
        every bank row is pinned by other in-flight work — the request
        stays QUEUED (head-of-line backpressure, the same semantics as
        page exhaustion; admitted work never waits, so the bank always
        frees up)."""
        if req.adapter is None or req._adapter_pinned:
            return True
        res = self._adapters
        ev0 = res.evictions
        got = res.acquire(req.adapter)
        if got is None:
            self._mark_stall_locked(req, "adapter_stall")
            if not self._adapter_stalled:
                self._adapter_stalled = True
                self._counts["adapter_load_stalls"] += 1
                flight.record("serving", "adapter_load_stall",
                              request=req.request_id, adapter=req.adapter,
                              resident=res.n_resident)
                registry().counter(
                    SERVING_ADAPTER_STALLS,
                    "admissions stalled on a fully-pinned adapter bank"
                ).inc(1.0)
            return False
        slot, cold = got
        self._adapter_stalled = False
        req._adapter_slot = slot
        req._adapter_pinned = True
        dev = res.evictions - ev0
        if dev:
            self._counts["adapter_evictions"] += dev
            flight.record("serving", "adapter_evict", n=dev,
                          request=req.request_id,
                          for_adapter=req.adapter)
            registry().counter(
                SERVING_ADAPTER_EVICTIONS,
                "refs-0 adapters evicted from the bank (LRU)").inc(
                float(dev))
        if cold:
            if req.adapter not in self._adapter_uploads:
                self._counts["adapter_loads"] += 1
                self._adapter_uploads[req.adapter] = (slot, req.request_id)
        else:
            self._counts["adapter_hits"] += 1
        return True

    def _unpin_adapter_locked(self, req: RequestHandle):
        """Drop the request's pin (the bank row stays resident at refs 0
        for the next hit; only LRU pressure reclaims it)."""
        if req._adapter_pinned:
            self._adapters.release(req.adapter)
            req._adapter_pinned = False
        req._adapter_slot = 0

    def _req_ns(self, req: RequestHandle):
        """Prefix-index namespace for one request: the adapter alone, or
        ``(adapter, conversation)`` when the request carries a
        conversation id — each conversation owns its cached turns, so a
        returning user's turn N+1 hits turn N's KV and nobody else's."""
        return (req.adapter if req.conversation is None
                else (req.adapter, req.conversation))

    def _demote_locked(self, e):
        """Hand an evicted prefix entry's page bytes to the host tier.

        The gather (``pool[pages]`` per layer per pool group) is EAGER
        and runs here, under the lock, BEFORE the pages are deref'd:
        the engine's jits donate the pools operand on device, so a raw
        ``self._pools`` snapshot is invalidated by the very next
        dispatch — fresh gathered arrays are the only thing the spill
        worker can safely ``device_get`` later, off this hot path."""
        if self._pools is None or not e.pages:
            return
        try:
            import jax.numpy as jnp
            idx = jnp.asarray(np.asarray(e.pages, np.int32))
            gathered = [[pool[idx] for pool in grp]
                        for grp in self._pools]
        except Exception:  # noqa: BLE001 — a dying device must not
            return         # turn an eviction into an engine failure
        self._host_tier.demote_async(e.ns, e.tokens, gathered)

    def _admit_dense_locked(self):
        """Dense-pool admission: head-of-queue requests admit while a
        free slot AND (when they name one) a pinnable adapter bank row
        are available, evicting unreferenced prefix rows under slot
        pressure.  An unpinnable adapter is head-of-line backpressure
        (FIFO fairness, like page exhaustion in the paged pool)."""
        evicted = 0
        want = min(self.prefill_batch, len(self._queue))
        if want == 0:
            self._adapter_stalled = False
            return [], 0
        if self._prefix is not None and want > self._pool.n_free:
            # reclaim cache capacity: LRU unreferenced entries go back
            # to the free list.  Referenced rows (copy sources for
            # in-flight requests) survive the sweep, and so do the
            # entries the incoming wave itself is about to hit — a
            # peek pass finds them first, otherwise a fully-cached
            # pool would evict exactly the rows the queue wants
            protect = set()
            for req in itertools.islice(self._queue, want):
                hit = self._prefix.lookup(req.prompt, peek=True,
                                          ns=self._req_ns(req))
                if hit is not None:
                    protect.add(id(hit[0]))
            for e in self._prefix.evict_lru(want - self._pool.n_free,
                                            protect=protect):
                self._pool.release_cached(e.slot)
                self._counts["prefix_evictions"] += 1
                evicted += 1
                flight.record("serving", "prefix_evict", slot=e.slot,
                              cached_tokens=e.n)
        batch = []
        while self._queue and len(batch) < want and self._pool.n_free > 0:
            req = self._queue[0]
            if not self._pin_adapter_locked(req):
                break
            self._queue.popleft()
            req.slot = self._pool.alloc(req)
            req._state = "active"
            req.t_admit = time.perf_counter()
            self._journey_admit_locked(req, slot=req.slot)
            if self._prefix is not None:
                hit = self._prefix.lookup(req.prompt,
                                          ns=self._req_ns(req))
                if hit is not None:
                    entry, matched = hit
                    self._prefix.acquire(entry)
                    req._prefix_src = entry
                    req._prefix_match = matched
                    req.prefix_hit = True
                    self._counts["prefix_hits"] += 1
                else:
                    self._counts["prefix_misses"] += 1
            batch.append(req)
        return batch, evicted

    def _admit_paged_locked(self):
        """Paged-pool admission: head-of-queue requests admit while a
        slot lane AND their page reservation both fit.  A request
        reserves every page it can ever write (``ceil((prompt +
        max_new_tokens) / page_size)``, minus fully-shared prefix
        pages), so decode can never hit mid-flight page exhaustion —
        exhaustion is an ADMISSION condition: the request stays queued
        (backpressure, like slot exhaustion in the dense pool) until
        retiring work or cache eviction frees pages.  No deadlock:
        admitted requests never wait on pages, so they always retire."""
        alloc = self._page_alloc
        P = alloc.page_size
        evicted = 0
        want = min(self.prefill_batch, len(self._queue))
        if want == 0:
            # stall episode over (the stalled request retired or was
            # cancelled): the next exhaustion is a fresh flight event
            self._page_stalled = False
            self._adapter_stalled = False
            return [], 0
        protect = set()
        if self._prefix is not None:
            for req in itertools.islice(self._queue, want):
                hit = self._prefix.lookup(req.prompt, peek=True,
                                          ns=self._req_ns(req))
                if hit is not None:
                    protect.add(id(hit[0]))
        batch = []
        while self._queue and len(batch) < want and self._pool.n_free > 0:
            req = self._queue[0]
            if not self._pin_adapter_locked(req):
                break                # HOL backpressure: bank fully pinned
            total = self._pages_for(req.prompt.size + req.max_new_tokens)
            hit = (self._prefix.lookup(req.prompt, peek=True,
                                       ns=self._req_ns(req))
                   if self._prefix is not None else None)
            # an HBM miss probes the host tier (kv_tier.py): a host hit
            # still allocates the FULL reservation — the promoted prefix
            # uploads into this request's own fresh pages
            # (_flush_promotes), then shares them back into the device
            # index, so `need` stays `total` here
            promote = (self._host_tier.lookup(req.prompt, peek=True,
                                              ns=self._req_ns(req))
                       if hit is None and self._host_tier is not None
                       else None)
            # fully-matched pages are shared by reference; a partial
            # boundary page (match not page-aligned) is replaced by a
            # one-page COW copy, so its replacement stays in `need`
            shared_full = (hit[1] // P) if hit is not None else 0
            need = total - shared_full
            while (need > alloc.n_free and self._prefix is not None):
                # reclaim pages from unreferenced LRU entries, sparing
                # the ones this wave is about to hit; with a host tier
                # attached the victim's bytes demote instead of dying
                victims = self._prefix.evict_lru(1, protect=protect)
                if not victims:
                    break
                e = victims[0]
                if self._host_tier is not None and e.pages:
                    self._demote_locked(e)
                for p in e.pages:
                    alloc.deref(p)
                self._cached_pages -= len(e.pages)
                self._counts["prefix_evictions"] += 1
                evicted += 1
                flight.record("serving", "prefix_evict",
                              pages=len(e.pages), cached_tokens=e.n)
            pages = alloc.alloc(need)
            if pages is None:
                # page exhaustion: head-of-line request stays queued
                # (FIFO fairness — no small-request overtake that would
                # starve the head); the pin taken above is dropped so a
                # parked request never holds bank capacity; flight-record
                # the stall once per stall episode, not per 20 ms sweep
                self._unpin_adapter_locked(req)
                self._mark_stall_locked(req, "page_stall")
                if not self._page_stalled:
                    self._page_stalled = True
                    self._counts["page_alloc_stalls"] += 1
                    flight.record("serving", "page_alloc_stall",
                                  request=req.request_id, need=need,
                                  free=alloc.n_free,
                                  cached_pages=self._cached_pages)
                break
            self._page_stalled = False
            self._queue.popleft()
            req.slot = self._pool.alloc(req)
            req._state = "active"
            req.t_admit = time.perf_counter()
            self._journey_admit_locked(req, slot=req.slot,
                                       pages_reserved=len(pages),
                                       pages_shared=shared_full)
            if hit is not None:
                entry, matched = hit
                self._prefix.touch(entry)      # count the peeked hit
                self._prefix.acquire(entry)
                req._prefix_src = entry
                req._prefix_match = matched
                req.prefix_hit = True
                self._counts["prefix_hits"] += 1
            elif promote is not None:
                # HBM miss, host hit: still a device-index miss (both
                # counters tell the truth), but the upload in
                # _flush_promotes turns it into a normal zero-copy hit
                # before prefill — tail-only from there on
                hentry, matched = promote
                self._host_tier.touch(hentry)  # count the peeked hit
                self._host_tier.acquire(hentry)   # un-droppable mid-flight
                req._promote = (hentry, matched)
                self._counts["host_prefix_hits"] += 1
                self._prefix.miss()
                self._counts["prefix_misses"] += 1
            elif self._prefix is not None:
                self._prefix.miss()
                self._counts["prefix_misses"] += 1
                if self._host_tier is not None:
                    self._host_tier.miss()     # missed BOTH tiers
            self._map_pages_locked(req, pages)
            batch.append(req)
        return batch, evicted

    def _map_pages_locked(self, req: RequestHandle, fresh):
        """Fill the slot's page table: the hit entry's fully-matched
        pages by reference (refcount++ each), then the fresh pages.
        When the hit boundary lands inside a shared page, schedule the
        copy-on-write clone of exactly that page into the first fresh
        page — the writer diverges on a private copy, the cached
        entry's bytes are untouched."""
        alloc = self._page_alloc
        P = alloc.page_size
        table = self._page_tables[req.slot]
        table[:] = alloc.num_pages
        pages = []
        m = req._prefix_match
        shared_full = m // P
        req._cow = None
        if req._prefix_src is not None:
            src_pages = req._prefix_src.pages
            for i in range(shared_full):
                alloc.share(src_pages[i])
                table[i] = src_pages[i]
                pages.append(src_pages[i])
            if m % P:
                req._cow = (src_pages[shared_full], fresh[0])
        for j, p in enumerate(fresh):
            table[shared_full + j] = p
            pages.append(p)
        req._pages = pages
        self._active_pages += len(pages)

    def _admit(self) -> bool:
        import jax

        with self._lock:
            if self.paged_kv:
                batch, evicted = self._admit_paged_locked()
            else:
                batch, evicted = self._admit_dense_locked()
            prefix_metrics = None
            if self._prefix is not None and batch:
                prefix_metrics = (sum(1 for r in batch if r.prefix_hit),
                                  sum(1 for r in batch if not r.prefix_hit))
            self._gauges_locked()
        if not batch:
            return False
        if not self._built:
            t_b0 = time.perf_counter()
            with span("serving.build"):
                self._build()
            dt_b = time.perf_counter() - t_b0
            for req in batch:
                if req.journey is not None:
                    # cold start: the first admission wave pays the pool
                    # build — attribute it, don't leave a mystery gap
                    req.journey.phase("build", t_b0, dt_b)
        self._flush_adapter_uploads(batch)
        self._flush_promotes(batch)
        if evicted:
            registry().counter(
                SERVING_PREFIX_EVICTIONS,
                "prefix-cache rows evicted back to the free list").inc(
                float(evicted))
        if prefix_metrics is not None:
            reg = registry()
            hits, misses = prefix_metrics
            if hits:
                reg.counter(SERVING_PREFIX_HITS,
                            "admissions served from the prefix cache").inc(
                    float(hits))
            if misses:
                reg.counter(SERVING_PREFIX_MISSES,
                            "admissions with no usable cached prefix").inc(
                    float(misses))
        for req in batch:
            # per-request PRNG base key for the device sampler (one tiny
            # eager op per ADMISSION, not per token)
            req._base_key = np.asarray(jax.random.PRNGKey(req.seed),
                                       np.uint32)
        cold = [r for r in batch if r._prefix_src is None]
        hits = [r for r in batch if r._prefix_src is not None]
        if cold:
            self._prefill_cold(cold)
        if hits:
            self._prefill_hits(hits)
        with self._lock:
            self._gauges_locked()
        return True

    def _set_slot_params_locked(self, req: RequestHandle):
        slot = req.slot
        self._temps[slot] = req.temperature
        self._topks[slot] = req.top_k
        self._keys[slot] = req._base_key
        self._aids[slot] = req._adapter_slot

    def _flush_adapter_uploads(self, batch=()):
        """Admission-time load of cold adapters: upload every scheduled
        adapter's zero-padded factors into its bank row (eager device
        writes, once per cold admission — never per token).  Runs on the
        scheduler thread after ``_build`` so the banks exist; the
        residency mapping is re-checked under the lock in case a stalled
        request's row was LRU-reused before its upload ran.  ``batch``
        is this admission wave — every admitted request waiting on a
        loaded adapter gets an ``adapter_load`` phase on its journey."""
        if self._adapters is None:
            return
        with self._lock:
            if not self._adapter_uploads:
                return
            ups = [(name, slot, rid) for name, (slot, rid) in
                   self._adapter_uploads.items()
                   if self._adapters.slot_of(name) == slot]
            self._adapter_uploads.clear()
        for name, slot, rid in ups:
            t0 = time.perf_counter()
            with span("serving.adapter_load", adapter=name, bank_slot=slot):
                self._load_adapter_bank(slot,
                                        self.adapter_registry.get(name))
            dt = time.perf_counter() - t0
            with self._lock:
                if self._adapters.slot_of(name) == slot:
                    self._adapters.mark_loaded(name)
                self._adapter_load_times.append(dt)
            registry().counter(
                SERVING_ADAPTER_LOADS,
                "cold adapter loads into the device bank").inc(1.0)
            flight.record("serving", "adapter_load", adapter=name,
                          bank_slot=slot, request=rid,
                          load_ms=round(dt * 1e3, 3))
            for req in batch:
                if req.adapter == name and req.journey is not None:
                    req.journey.phase("adapter_load", t0, dt, adapter=name,
                                      bank_slot=slot)

    def _flush_promotes(self, batch=()):
        """Host-tier promotion: upload each promoted request's cached
        prefix bytes into the fresh device pages admission reserved for
        it, then re-insert the prefix into the device index so the NEXT
        turn hits in HBM directly.

        Runs on the scheduler thread after ``_build`` (the pools exist)
        and before prefill partitioning — a promoted request leaves here
        as a normal zero-copy hit (``_prefix_src`` set, tail-prefill
        only).  The writes are EAGER ``.at[pages].set`` updates per pool
        per layer, never a jitted entry point, so the decode signature
        count stays at ONE; the page bytes land verbatim (int8 payload +
        f32 scales), so greedy output is bitwise-identical to a
        never-evicted hit.  The upload runs OFF-lock (device work);
        the mapping is re-checked under the lock first in case the
        engine shut down while this wave was in flight."""
        if self._host_tier is None:
            return
        import jax.numpy as jnp
        tier = self._host_tier
        todo = []
        with self._lock:
            for req in batch:
                if req._promote is None:
                    continue
                hentry, m = req._promote
                if req._pages is None or req.slot is None:
                    req._promote = None
                    tier.release(hentry)
                    continue
                todo.append((req, hentry, m))
        P = self._page_alloc.page_size
        for req, hentry, m in todo:
            q = -(-m // P)                       # ceil: pages holding m
            pids = req._pages[:q]
            t0 = time.perf_counter()
            try:
                payload = tier.payload(hentry, q)
            except KeyError:
                # the entry vanished under us (tier closed externally):
                # the request still holds its full reservation — fall
                # back to a plain cold prefill, never an engine death
                with self._lock:
                    req._promote = None
                    tier.release(hentry)
                continue
            idx = jnp.asarray(np.asarray(pids, np.int32))
            self._pools = tuple(
                [pool.at[idx].set(jnp.asarray(arr, pool.dtype))
                 for pool, arr in zip(grp, host_grp)]
                for grp, host_grp in zip(self._pools, payload))
            dt = time.perf_counter() - t0
            nbytes = sum(a.nbytes for g in payload for a in g)
            with self._lock:
                req._promote = None
                entry = self._prefix.insert(None, hentry.tokens[:m],
                                            pages=list(pids),
                                            ns=hentry.ns)
                if entry is not None:
                    # the index and this request each hold a page ref
                    for p in pids:
                        self._page_alloc.share(p)
                    self._cached_pages += q
                else:
                    # pathological duplicate (an unaddressable entry
                    # already owns (ns, tokens[:m])): ride the hit path
                    # on a DETACHED entry — not in the index, no page
                    # sharing; release just decrements its refs
                    entry = PrefixEntry(None, hentry.tokens[:m], 0,
                                        pages=None, ns=hentry.ns)
                self._prefix.acquire(entry)
                req._prefix_src = entry
                req._prefix_match = m
                req._cow = None                  # page-aligned by block
                req.prefix_hit = True
                req.promote_s = dt
                self._counts["host_prefix_promotes"] += 1
                tier.release(hentry)
            reg = registry()
            reg.counter(
                SERVING_HOST_PREFIX_HITS,
                "admissions whose prefix was found in the host tier").inc(
                1.0)
            reg.counter(
                SERVING_HOST_PREFIX_PROMOTES,
                "host-tier prefixes re-uploaded into device pages").inc(1.0)
            reg.histogram(
                SERVING_HOST_PREFIX_PROMOTE_SECONDS,
                "host->device promote wall seconds (upload + re-index)"
            ).observe(dt)
            flight.record("serving", "host_prefix_promote",
                          request=req.request_id, cached_tokens=m,
                          pages=q, bytes=nbytes,
                          promote_ms=round(dt * 1e3, 3))
            if req.journey is not None:
                req.journey.phase("prefix_promote", t0, dt,
                                  cached_tokens=m, pages=q, bytes=nbytes)

    def _load_adapter_bank(self, slot: int, adapter):
        """Write one adapter's factors (zero-padded to the bank's
        ``r_max``) into bank row ``slot``; padding columns contribute
        exact zeros to the delta."""
        import jax.numpy as jnp
        r = adapter.rank
        a = np.zeros(tuple(self._abank.shape[1:]), np.float32)
        b = np.zeros(tuple(self._bbank.shape[1:]), np.float32)
        for i in range(adapter.num_layers):
            a[i, :, :r] = adapter.a[i]
            b[i, :r, :] = adapter.b[i]
        self._abank = self._abank.at[slot].set(jnp.asarray(a))
        self._bbank = self._bbank.at[slot].set(jnp.asarray(b))
        self._ascale = self._ascale.at[slot].set(float(adapter.scale))

    def _adp_args(self, aids):
        """The adapter operand tuple one dispatch carries: per-row bank
        ids + the stacked banks (fixed shapes — ONE decode signature)."""
        import jax.numpy as jnp
        return (jnp.asarray(aids, jnp.int32), self._abank, self._bbank,
                self._ascale)

    def _prefill_cold(self, batch) -> None:
        """Batched prefill of requests with no cached prefix (the only
        admission path when the prefix cache is off)."""
        import jax.numpy as jnp
        bucket = _bucket(max(r.prompt.size for r in batch),
                         min(8, self._limit), self._limit)
        P = self.prefill_batch
        ids = np.zeros((P, bucket), np.int64)
        slot_idx = np.full(P, self.max_slots, np.int32)
        plens = np.ones(P, np.int32)
        temps = np.zeros(P, np.float32)
        topks = np.zeros(P, np.int32)
        keys = np.zeros((P, 2), np.uint32)
        aid_rows = np.zeros(P, np.int32)
        tables = (np.full((P, self._max_pages_per_slot),
                          self._page_alloc.num_pages, np.int32)
                  if self.paged_kv else None)
        with self._lock:
            for i, req in enumerate(batch):
                ids[i, :req.prompt.size] = req.prompt
                slot_idx[i] = req.slot
                plens[i] = req.prompt.size
                temps[i] = req.temperature
                topks[i] = req.top_k
                keys[i] = req._base_key
                aid_rows[i] = req._adapter_slot
                if tables is not None:
                    tables[i] = self._page_tables[req.slot]
                self._set_slot_params_locked(req)
                flight.record("serving", "admit", request=req.request_id,
                              slot=req.slot,
                              prompt_len=int(req.prompt.size),
                              queue_wait_ms=round(
                                  1e3 * (req.t_admit - req.t_submit), 3))
        t0 = time.perf_counter()
        faults.fault_point("serving.prefill", n=len(batch))
        if self._decode_timeout_s is not None:
            _watchdog.arm("serving.prefill", self._decode_timeout_s)
        try:
            extra = ((self._adp_args(aid_rows),)
                     if self._adapters is not None else ())
            with span("serving.prefill", n=len(batch), bucket=bucket):
                if self.paged_kv:
                    out, self._pools = self._prefill_fn(
                        self._values, jnp.asarray(ids), self._pools,
                        jnp.asarray(tables), jnp.asarray(plens),
                        jnp.asarray(temps), jnp.asarray(topks),
                        jnp.asarray(keys), *extra)
                else:
                    out, self._pools = self._prefill_fn(
                        self._values, jnp.asarray(ids), self._pools,
                        jnp.asarray(slot_idx), jnp.asarray(plens),
                        jnp.asarray(temps), jnp.asarray(topks),
                        jnp.asarray(keys), *extra)
                out = np.asarray(out)
        finally:
            if self._decode_timeout_s is not None:
                _watchdog.disarm()
        dt = time.perf_counter() - t0
        with self._lock:
            self._counts["prefill_batches"] += 1
        registry().histogram(SERVING_BATCH_SECONDS,
                             "prefill/decode batch wall time").observe(
            dt, labels={"phase": "prefill"})
        for req in batch:
            if req.journey is not None:
                req.journey.phase("prefill", t0, dt, n=len(batch),
                                  bucket=bucket,
                                  prompt=int(req.prompt.size))
        self._emit_first_tokens(batch, out, by_slot=False)

    def _prefill_hits(self, hits) -> None:
        """Prefix-cache hit path.  Dense pool: device-copy the cached
        rows into the new slots, then prefill ONLY the prompt tails
        through the per-slot branch — admission cost scales with the
        tail, not the prompt.  Paged pool: ZERO-copy — the hit already
        shares the cached pages by reference through the page table
        (host-side int writes); only a partial boundary page needs its
        one-page COW clone before the tail writes into it."""
        import jax.numpy as jnp
        P = self.prefill_batch
        scratch = self.max_slots
        paged = self.paged_kv
        sentinel = self._page_alloc.num_pages if paged else scratch
        src = np.full(P, sentinel, np.int32)
        dst = np.full(P, sentinel, np.int32)
        n_copy = 0
        cow_ids: list[int] = []      # requests whose boundary page COWs
        n_rows = self.max_slots + 1
        tails = [r.prompt.size - r._prefix_match for r in hits]
        tb = _bucket(max(tails), 1, self._limit)
        ids = np.zeros((n_rows, tb), np.int64)
        lens = np.full(n_rows, self._park, np.int32)
        gidx = np.zeros(n_rows, np.int32)
        tables = None
        with self._lock:
            for i, req in enumerate(hits):
                e, m = req._prefix_src, req._prefix_match
                if paged:
                    if req._cow is not None:
                        src[n_copy], dst[n_copy] = req._cow
                        n_copy += 1
                        cow_ids.append(req.request_id)
                        req._cow = None
                else:
                    src[i], dst[i] = e.slot, req.slot
                    n_copy += 1
                tail = req.prompt[m:]
                ids[req.slot, :tail.size] = tail
                lens[req.slot] = m
                gidx[req.slot] = tail.size - 1
                self._set_slot_params_locked(req)
                flight.record("serving", "prefix_admit",
                              request=req.request_id, slot=req.slot,
                              src_slot=-1 if e.slot is None else e.slot,
                              cached_tokens=m, tail=int(tail.size),
                              queue_wait_ms=round(
                                  1e3 * (req.t_admit - req.t_submit), 3))
            if paged:
                tables = np.array(self._page_tables)
            aids_snap = np.array(self._aids)
        t0 = time.perf_counter()
        faults.fault_point("serving.prefill", n=len(hits))
        if self._decode_timeout_s is not None:
            _watchdog.arm("serving.tail_prefill", self._decode_timeout_s)
        try:
            if n_copy or not paged:
                # dense: whole-row clone per hit; paged: only the COW'd
                # boundary pages (usually zero — block == page size makes
                # every shared page a full page)
                with span("serving.prefix_copy", n=n_copy):
                    self._pools = self._copy_fn(
                        self._pools, jnp.asarray(src), jnp.asarray(dst))
                if paged and n_copy:
                    with self._lock:
                        self._counts["page_cow_copies"] += n_copy
                    registry().counter(
                        SERVING_KV_COW_COPIES,
                        "shared KV pages cloned for a diverging writer"
                    ).inc(float(n_copy))
                    flight.record("serving", "page_cow", copies=n_copy,
                                  requests=",".join(map(str, cow_ids)))
            t_copy_end = time.perf_counter()
            extra = ((self._adp_args(aids_snap),)
                     if self._adapters is not None else ())
            with span("serving.tail_prefill", n=len(hits), bucket=tb):
                if paged:
                    out, self._pools = self._tail_fn(
                        self._values, jnp.asarray(ids), self._pools,
                        jnp.asarray(lens), jnp.asarray(tables),
                        jnp.asarray(gidx), jnp.asarray(self._temps),
                        jnp.asarray(self._topks), jnp.asarray(self._keys),
                        *extra)
                else:
                    out, self._pools = self._tail_fn(
                        self._values, jnp.asarray(ids), self._pools,
                        jnp.asarray(lens), jnp.asarray(gidx),
                        jnp.asarray(self._temps), jnp.asarray(self._topks),
                        jnp.asarray(self._keys), *extra)
                out = np.asarray(out)
        finally:
            if self._decode_timeout_s is not None:
                _watchdog.disarm()
        t_end = time.perf_counter()
        dt = t_end - t0
        with self._lock:
            self._counts["prefill_batches"] += 1
        registry().histogram(SERVING_BATCH_SECONDS,
                             "prefill/decode batch wall time").observe(
            dt, labels={"phase": "tail_prefill"})
        cow_set = set(cow_ids)
        for req in hits:
            if req.journey is None:
                continue
            m = req._prefix_match
            # dense hits device-copy their cached row; paged hits share
            # pages by reference (zero-copy) unless a boundary page COWed
            if not paged or req.request_id in cow_set:
                req.journey.phase("prefix_copy", t0, t_copy_end - t0,
                                  cached_tokens=m)
            req.journey.phase("tail_prefill", t_copy_end,
                              t_end - t_copy_end, cached_tokens=m,
                              tail=int(req.prompt.size - m),
                              zero_copy=bool(paged and
                                             req.request_id not in cow_set))
        self._emit_first_tokens(hits, out, by_slot=True)

    def _emit_first_tokens(self, batch, out, by_slot: bool):
        """Shared tail of both admission paths: record TTFT and emit each
        request's first token (``out`` is device-sampled token ids, or
        logits rows when ``sample_on_device=False``)."""
        now = time.perf_counter()
        finishers = []
        for i, req in enumerate(batch):
            row = out[req.slot] if by_slot else out[i]
            req.ttft_s = now - req.t_submit
            req._t_last_token = now
            registry().histogram(SERVING_TTFT,
                                 "time to first token").observe(req.ttft_s)
            if req.adapter is not None:
                registry().histogram(
                    SERVING_ADAPTER_TTFT,
                    "time to first token, per adapter").observe(
                    req.ttft_s, labels={"adapter": req.adapter})
            if req.done() or req._torn or req._engine is not self:
                continue
            token = (int(row) if self.sample_on_device else
                     _sample_row(row, req.temperature, req.top_k, req._rng))
            if req.journey is not None:
                req.journey.mark_first_token(now)
            finished = self._emit_one(req, token)
            if req.adapter is not None:
                registry().counter(
                    SERVING_ADAPTER_TOKENS,
                    "tokens served, per adapter").inc(
                    1.0, labels={"adapter": req.adapter})
            slot = req.slot
            with self._lock:
                self._counts["tokens"] += 1
                self._lengths[slot] = req.prompt.size
                if finished:
                    self._evict_locked(req, "completed")
                else:
                    self._ids[slot, 0] = token
            if finished:
                finishers.append(req)
        for req in finishers:
            req._finish(None)

    # -- decode --------------------------------------------------------------
    def _decode_step(self) -> bool:
        with self._lock:
            active = self._pool.active()
            if not active:
                return False
        W = self._spec_width
        drafts: dict = {}
        if W > 1:
            for slot, req in active.items():
                if req.temperature == 0.0:
                    # prompt-lookup drafting is greedy-only: an accepted
                    # draft must equal the token the model WOULD emit,
                    # which is only well-defined for argmax decoding
                    ctx = np.concatenate(
                        [req.prompt, np.asarray(req._tokens, np.int64)])
                    drafts[slot] = np.asarray(
                        self._drafter(ctx, W - 1), np.int64)
        with self._lock:
            # snapshot the slot-state arrays under the lock: shutdown()
            # mutates slot state from the caller thread (tpu-lint
            # concurrency.unguarded-shared-attr)
            for slot in active:
                d = drafts.get(slot)
                if W > 1:
                    self._ids[slot, 1:] = (d if d is not None
                                           else self._ids[slot, 0])
            ids = np.array(self._ids)
            lengths = np.array(self._lengths)
            temps = np.array(self._temps)
            topks = np.array(self._topks)
            keys = np.array(self._keys)
            aids = np.array(self._aids)
            tables = (np.array(self._page_tables) if self.paged_kv
                      else None)
        import jax.numpy as jnp
        t0 = time.perf_counter()
        faults.fault_point("serving.decode", active=len(active))
        if self._decode_timeout_s is not None:
            _watchdog.arm("serving.decode", self._decode_timeout_s)
        try:
            extra = ((self._adp_args(aids),)
                     if self._adapters is not None else ())
            with span("serving.decode", active=len(active)):
                if self.paged_kv:
                    out, self._pools = self._decode_fn(
                        self._values, jnp.asarray(ids), self._pools,
                        jnp.asarray(lengths), jnp.asarray(tables),
                        jnp.asarray(temps), jnp.asarray(topks),
                        jnp.asarray(keys), *extra)
                else:
                    out, self._pools = self._decode_fn(
                        self._values, jnp.asarray(ids), self._pools,
                        jnp.asarray(lengths), jnp.asarray(temps),
                        jnp.asarray(topks), jnp.asarray(keys), *extra)
                out = np.asarray(out)
        finally:
            if self._decode_timeout_s is not None:
                _watchdog.disarm()
        dt = time.perf_counter() - t0
        with self._lock:
            self._counts["decode_steps"] += 1
        registry().histogram(SERVING_BATCH_SECONDS,
                             "prefill/decode batch wall time").observe(
            dt, labels={"phase": "decode"})
        now = time.perf_counter()
        tok_hist = registry().histogram(SERVING_TOKEN_LATENCY,
                                        "per-token decode latency")
        drafted_total = accepted_total = 0
        finishers = []
        for slot, req in active.items():
            if req.done() or req._torn or req._engine is not self:
                # torn away by a supervisor abandon while this batch ran
                # (or already re-dispatched into a REBUILT engine): its
                # outcome is settled elsewhere
                continue
            if self.sample_on_device:
                toks_row = out[slot]                      # [W] token ids
            else:
                row_logits = out[slot]                    # [W, V] logits
                first = _sample_row(row_logits[0], req.temperature,
                                    req.top_k, req._rng)
                toks_row = np.concatenate(
                    [[first], row_logits[1:].argmax(-1)]) \
                    if W > 1 else np.array([first])
            # acceptance: the draft at position j (ids[slot, j]) is kept
            # iff it equals the model's choice at position j-1; the run
            # t_0..t_m then emits m+1 tokens for this one pool read
            run = [int(toks_row[0])]
            d = drafts.get(slot)
            if d is not None:
                for j in range(1, W):
                    if int(d[j - 1]) != int(toks_row[j - 1]):
                        break
                    run.append(int(toks_row[j]))
                drafted_total += W - 1
                accepted_total += len(run) - 1
            old_len = int(lengths[slot])
            lat = now - req._t_last_token
            req._t_last_token = now
            emitted = 0
            finished = False
            for token in run:
                finished = self._emit_one(req, token)
                emitted += 1
                if finished:
                    break
            # one pool read emitted `emitted` tokens: split the wall time
            # so the per-token histogram stays sum-preserving
            for _ in range(emitted):
                req.token_latencies_s.append(lat / max(emitted, 1))
                tok_hist.observe(lat / max(emitted, 1))
            if req.adapter is not None and emitted:
                registry().counter(
                    SERVING_ADAPTER_TOKENS,
                    "tokens served, per adapter").inc(
                    float(emitted), labels={"adapter": req.adapter})
            if req.journey is not None:
                # one phase per batched DISPATCH the request rode (the
                # existing per-token boundary), never per token
                attrs = {"emitted": emitted, "active": len(active)}
                if d is not None:
                    attrs["drafted"] = W - 1
                    attrs["accepted"] = len(run) - 1
                req.journey.phase("decode", t0, dt, **attrs)
            with self._lock:
                self._counts["tokens"] += emitted
                self._lengths[slot] = old_len + emitted
                if finished:
                    self._evict_locked(req, "completed")
                else:
                    self._ids[slot, 0] = run[emitted - 1]
            if finished:
                finishers.append(req)
        if drafted_total:
            with self._lock:
                self._counts["spec_drafted"] += drafted_total
                self._counts["spec_accepted"] += accepted_total
            reg = registry()
            reg.counter(SERVING_SPEC_DRAFTED,
                        "speculative tokens drafted").inc(
                float(drafted_total))
            if accepted_total:
                reg.counter(SERVING_SPEC_ACCEPTED,
                            "speculative tokens accepted").inc(
                    float(accepted_total))
            flight.record("serving", "spec_verify", drafted=drafted_total,
                          accepted=accepted_total,
                          rejected=drafted_total - accepted_total)
        for req in finishers:
            req._finish(None)
        with self._lock:
            self._gauges_locked()
        return True

    def _emit_one(self, req: RequestHandle, token: int) -> bool:
        """Stream one token to the request; returns whether the request
        is now finished (budget or EOS)."""
        faults.fault_point("serving.stream", request=req.request_id)
        req._emit(token)
        registry().counter(SERVING_TOKENS, "tokens generated").inc(1.0)
        return (len(req._tokens) >= req.max_new_tokens or
                (req.eos_token_id is not None and
                 token == req.eos_token_id))

    # -- eviction / retention ------------------------------------------------
    def _release_pages_locked(self, req: RequestHandle):
        """Drop the request's page references (freed at refcount 0) and
        sentinel its table row.  No-op outside paged mode."""
        if req._promote is not None and self._host_tier is not None:
            # a pending promote dies with the admission (shutdown /
            # engine death before _flush_promotes ran): drop the tier
            # pin so the entry becomes LRU-droppable again
            self._host_tier.release(req._promote[0])
            req._promote = None
        if not self.paged_kv or req._pages is None:
            return
        for p in req._pages:
            self._page_alloc.deref(p)
        self._active_pages -= len(req._pages)
        req._pages = None
        req._cow = None
        if req.slot is not None:
            self._page_tables[req.slot, :] = self._page_alloc.num_pages

    def _evict_locked(self, req: RequestHandle, outcome: str):
        slot = req.slot
        if req._prefix_src is not None:
            self._prefix.release(req._prefix_src)
            req._prefix_src = None
        retained = False
        if self._prefix is not None and outcome == "completed":
            # the slot holds the K/V of prompt + generated[:-1] (exactly
            # `lengths[slot]` positions) — retain it as a reusable
            # prefix instead of recycling it; duplicates free normally
            n = int(self._lengths[slot])
            cached = np.concatenate(
                [req.prompt, np.asarray(req._tokens, np.int64)])[:n]
            if self.paged_kv:
                # the ENTRY takes ownership of the pages covering the
                # cached tokens (refcounts transfer, no device work);
                # the unused tail of the reservation is released.  The
                # slot LANE is always recycled — cached prefixes hold
                # pages, never decode capacity.
                keep = self._pages_for(n) if n > 0 else 0
                entry = (self._prefix.insert(
                    None, cached, pages=req._pages[:keep],
                    ns=self._req_ns(req))
                    if keep > 0 else None)
                if entry is not None:
                    for p in req._pages[keep:]:
                        self._page_alloc.deref(p)
                    self._active_pages -= len(req._pages)
                    self._cached_pages += keep
                    req._pages = None
                    self._counts["prefix_inserts"] += 1
                    flight.record("serving", "prefix_insert", pages=keep,
                                  request=req.request_id, cached_tokens=n)
                    retained = True
            else:
                entry = (self._prefix.insert(slot, cached,
                                             ns=self._req_ns(req))
                         if n > 0 else None)
                if entry is not None:
                    self._pool.retain(slot, entry)
                    self._counts["prefix_inserts"] += 1
                    flight.record("serving", "prefix_insert", slot=slot,
                                  request=req.request_id, cached_tokens=n)
                    retained = True
        if self.paged_kv:
            self._release_pages_locked(req)
            self._page_tables[slot, :] = self._page_alloc.num_pages
            self._pool.free(slot)
        elif not retained:
            self._pool.free(slot)
        if self._adapters is not None:
            self._unpin_adapter_locked(req)
            self._aids[slot] = 0
        # park the row: idle (and cached) rows' pool writes must DROP
        self._lengths[slot] = self._park
        self._evicted_counters_locked(req, outcome)

    def _evicted_counters_locked(self, req: RequestHandle, outcome: str):
        self._counts[outcome] = self._counts.get(outcome, 0) + 1
        flight.record("serving", "evict", request=req.request_id,
                      slot=-1 if req.slot is None else req.slot,
                      outcome=outcome, tokens=len(req._tokens))
        registry().counter(SERVING_REQUESTS,
                           "serving requests by outcome").inc(
            1.0, labels={"outcome": outcome})

    def _gauges_locked(self):
        reg = registry()
        if self._ledger_prefix is not None and self._built:
            # retained-row bytes: cached slot rows (dense) or cached
            # pages (paged) — a sub-account of the kv_pool owner
            nb = (self._cached_pages * self._page_alloc.bytes_per_page
                  if self.paged_kv else
                  self._pool.n_cached * self._row_bytes)
            self._ledger_prefix.update(nb)
        reg.gauge(SERVING_ACTIVE_SLOTS,
                  "slots currently owned by requests").set(
            float(self._pool.n_active))
        reg.gauge(SERVING_QUEUE_DEPTH, "queued, unadmitted requests").set(
            float(len(self._queue)))
        if self._adapters is not None:
            reg.gauge(SERVING_ADAPTERS_RESIDENT,
                      "adapters resident in the device bank").set(
                float(self._adapters.n_resident))
        if self.paged_kv:
            reg.gauge(SERVING_KV_PAGES_FREE,
                      "KV pages on the free list").set(
                float(self._page_alloc.n_free))
            reg.gauge(SERVING_KV_PAGES_ACTIVE,
                      "KV pages referenced by in-flight requests "
                      "(shared pages count once per reference)").set(
                float(self._active_pages))
            reg.gauge(SERVING_KV_PAGES_CACHED,
                      "KV pages referenced by prefix-cache entries "
                      "(shared pages count once per reference)").set(
                float(self._cached_pages))
