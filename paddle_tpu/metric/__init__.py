"""paddle.metric parity (reference: python/paddle/metric/metrics.py).

Accuracy — the metric the hapi fit/eval loop updates EVERY batch —
computes on device when fed Tensors: top-k and the correctness compare
run in jnp, and only ``len(topk)`` scalars cross the host boundary per
update.  (The original downloaded the full ``[N, C]`` predictions and
argsorted on host once per batch — a per-step blocking transfer, the
tpu-lint ``trace-hygiene.device-sync`` class of bug.)  Precision and
Recall reduce their counts on device the same way.  Auc keeps a host
histogram by design — like quantization's HistObserver it needs the
full score distribution, and its inputs are ``[N]`` score vectors, not
``[N, C]`` logits.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


def _host_small(x):
    """Host view of a SMALL operand (labels, score vectors).  The big
    per-batch operands — predictions — never come through here: their
    reductions run on device."""
    if isinstance(x, Tensor):
        return np.asarray(x._value)
    return np.asarray(x)


def _device(x):
    import jax.numpy as jnp

    return x._value if isinstance(x, Tensor) else jnp.asarray(np.asarray(x))


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self._name = name or "acc"
        self.maxk = max(self.topk)
        self.reset()

    def compute(self, pred, label, *args):
        if isinstance(pred, Tensor):
            # top-k + compare stay on device; the [N, C] predictions are
            # never downloaded — update() syncs len(topk) scalars
            import jax
            import jax.numpy as jnp

            p = pred._value
            lab = _device(label)
            _, order = jax.lax.top_k(p, self.maxk)
            if lab.ndim == p.ndim and lab.shape[-1] == 1:
                lab = lab[..., 0]
            correct = (order == lab[..., None]).astype(jnp.float32)
            return Tensor(correct, _internal=True)
        pred_np = np.asarray(pred)
        label_np = _host_small(label)
        order = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        if label_np.ndim == pred_np.ndim and label_np.shape[-1] == 1:
            label_np = label_np.squeeze(-1)
        correct = (order == label_np[..., None]).astype(np.float32)
        return Tensor(correct)

    def update(self, correct, *args):
        c = correct._value if isinstance(correct, Tensor) else \
            np.asarray(correct)
        n = int(c.shape[0]) if c.ndim else 1
        accs = []
        for i, k in enumerate(self.topk):
            num = float(c[..., :k].sum())   # one scalar per k on device
            accs.append(num / max(n, 1))
            self.total[i] += num
            self.count[i] += n
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        self._name = name
        self.reset()

    def update(self, preds, labels):
        pb = (_device(preds) > 0.5).reshape(-1)
        lb = (_device(labels).astype("int32") == 1).reshape(-1)
        tp = pb & lb
        fp = pb & ~lb
        # two scalars cross the host boundary (was two full downloads)
        self.tp += int(tp.sum())
        self.fp += int(fp.sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        self._name = name
        self.reset()

    def update(self, preds, labels):
        pb = (_device(preds) > 0.5).reshape(-1)
        lb = (_device(labels).astype("int32") == 1).reshape(-1)
        tp = pb & lb
        fn = ~pb & lb
        self.tp += int(tp.sum())
        self.fn += int(fn.sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """Streaming AUC with histogram buckets (reference: metrics.py Auc +
    framework/fleet/metrics.cc BasicAucCalculator).  Host-side by
    design: the bucketed count update needs the score distribution."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def update(self, preds, labels):
        p = _host_small(preds)
        if p.ndim == 2:
            p = p[:, -1]
        lab = _host_small(labels).reshape(-1)
        bins = np.clip((p * self.num_thresholds).astype(np.int64), 0,
                       self.num_thresholds)
        for b, y in zip(bins, lab):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, np.int64)

    def accumulate(self):
        tot_pos = tot_neg = 0.0
        auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_pos + tot_pos) * (new_neg - tot_neg) / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        denom = tot_pos * tot_neg
        return float(auc / denom) if denom else 0.0

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):  # noqa: A002
    if isinstance(input, Tensor):
        import jax
        import jax.numpy as jnp

        p = input._value
        lab = _device(label).reshape(-1)
        _, order = jax.lax.top_k(p, int(k))
        hit = (order == lab[:, None]).any(axis=1)
        return Tensor(hit.astype(jnp.float32).mean(), _internal=True)
    pred = np.asarray(input)
    lab = _host_small(label).reshape(-1)
    order = np.argsort(-pred, axis=-1)[:, :k]
    correct_np = (order == lab[:, None]).any(axis=1).mean()
    return Tensor(np.float32(correct_np))
