"""paddle.metric parity (reference: python/paddle/metric/metrics.py)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


def _np(x):
    return x.numpy() if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self._name = name or "acc"
        self.maxk = max(self.topk)
        self.reset()

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        order = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        if label_np.ndim == pred_np.ndim and label_np.shape[-1] == 1:
            label_np = label_np.squeeze(-1)
        correct = (order == label_np[..., None]).astype(np.float32)
        return Tensor(correct)

    def update(self, correct, *args):
        c = _np(correct)
        accs = []
        for k in self.topk:
            num = c[..., :k].sum()
            accs.append(num / max(c.shape[0], 1))
            self.total[self.topk.index(k)] += num
            self.count[self.topk.index(k)] += c.shape[0]
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """Streaming AUC with histogram buckets (reference: metrics.py Auc +
    framework/fleet/metrics.cc BasicAucCalculator)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2:
            p = p[:, -1]
        l = _np(labels).reshape(-1)
        bins = np.clip((p * self.num_thresholds).astype(np.int64), 0,
                       self.num_thresholds)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, np.int64)

    def accumulate(self):
        tot_pos = tot_neg = 0.0
        auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_pos + tot_pos) * (new_neg - tot_neg) / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        denom = tot_pos * tot_neg
        return float(auc / denom) if denom else 0.0

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):  # noqa: A002
    pred = _np(input)
    lab = _np(label).reshape(-1)
    order = np.argsort(-pred, axis=-1)[:, :k]
    correct_np = (order == lab[:, None]).any(axis=1).mean()
    return Tensor(np.float32(correct_np))
