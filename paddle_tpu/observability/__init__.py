"""paddle_tpu.observability — process-wide runtime telemetry.

Two layers with different duty cycles:

**Metrics (off by default).**  One registry (counters / gauges /
histograms with labels) fed by three instrumentation layers: op-dispatch
telemetry in the ``@defop`` hub (``core/op.py``), the retrace sentinel
around the jit entry points (``distributed/spmd.py`` train steps,
``jit.to_static``), and step-level training metrics (step latency,
examples/s, device memory gauges; hapi ``TelemetryCallback``).  Costs one
boolean check per op when off.  Enable with ``PADDLE_TPU_TELEMETRY=1``,
``paddle_tpu.set_flags({"FLAGS_telemetry": True})`` or :func:`enable`.
Export with :func:`dump` (JSON), :func:`to_prometheus_text`, or let
``profiler.export_chrome_tracing`` merge counter samples into its
host-span timeline.  ``python bench.py --telemetry`` appends a per-leg
telemetry block to the bench JSON.

**Timeline (always on).**  :mod:`trace` spans (``span("compile", ...)``
context manager/decorator with thread-local nesting), the :mod:`flight`
recorder (a bounded ring of structured events fed by span open/close plus
one-shot events from compiles, collectives, dataloader waits, checkpoint
phases, flag changes and NaN/Inf hits), and :mod:`watchdog` crash/hang
diagnostics (excepthook + SIGTERM/SIGINT dump of the flight tail and
all-thread stacks; opt-in step deadline via ``PADDLE_TPU_STEP_TIMEOUT_S``).
These run from import because their cost is per-span, never per-op — the
crash that matters never reproduces under a profiler.
"""
from __future__ import annotations

import json
import os

from .metrics import (  # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry)

_REGISTRY = MetricsRegistry()
_ENABLED = False


def registry() -> MetricsRegistry:
    """The process-wide registry (always usable, even when disabled)."""
    return _REGISTRY


def enabled() -> bool:
    return _ENABLED


def enable(on: bool = True):
    """Flip telemetry globally; syncs the op-layer fast-path flag."""
    global _ENABLED
    _ENABLED = bool(on)
    _REGISTRY.sampling = _ENABLED
    from ..core import op as op_mod
    op_mod.TELEMETRY = _ENABLED


def disable():
    enable(False)


def dump() -> dict:
    return _REGISTRY.dump()


def dump_json() -> str:
    return json.dumps(_REGISTRY.dump(), sort_keys=True)


def to_prometheus_text() -> str:
    return _REGISTRY.to_prometheus_text()


def _bootstrap_from_env():
    v = os.environ.get("PADDLE_TPU_TELEMETRY", "")
    if v.lower() in ("1", "true", "yes", "on"):
        enable(True)


# imported AFTER registry()/enable() exist: both modules pull `registry`
# from this package at import time
from . import dispatch  # noqa: E402,F401
from . import retrace  # noqa: E402,F401
from . import steps  # noqa: E402,F401
from .retrace import (  # noqa: E402,F401
    get_retrace_threshold, instrument_jit, set_retrace_threshold)
# the always-on timeline layer (no registry dependency)
from . import flight  # noqa: E402,F401
from . import trace  # noqa: E402,F401
from . import watchdog  # noqa: E402,F401
from .trace import span  # noqa: E402,F401
# request journeys (per-request phase timelines + the windowed feed);
# imported after registry() exists — journey feeds phase histograms
from . import journey  # noqa: E402,F401
# device perfscope: per-program device-time/MFU attribution + the HBM
# ownership ledger (already pulled in by retrace; re-exported here)
from . import perfscope  # noqa: E402,F401
# SLO engine: objectives + burn-rate alerts + incident bundles,
# layered over the keyed journey window and the watchdog seam
from . import slo  # noqa: E402,F401
# traffic capture: the always-on admission recorder + replay/fit feeds;
# its process default registers the capture_tail incident section lazily
from . import capture  # noqa: E402,F401

_bootstrap_from_env()
watchdog._bootstrap_from_env()
