"""Request journeys — end-to-end per-request tracing with phase-level
latency attribution, plus the windowed telemetry feed built on top.

The serving stack already *emits* plenty of telemetry (flight events,
Prometheus series, spans), but none of it answers "where did THIS
request's 480 ms go?" — events are uncorrelated across layers and
nothing splits one request's wall time into queue wait vs adapter
cold-load vs prefill vs decode.  This module is that correlation layer:

* a **Journey** is one request's bounded timeline.  The gateway handler
  mints one (or adopts the client's ``X-Request-Id``) and every layer
  the request crosses — protocol parse, fair-share queueing, router
  pick, engine queue, adapter load/stall, page stall, prefill,
  tail-prefill, prefix copy, each decode dispatch, stream emission,
  supervisor rebuild, cross-replica redispatch — appends a typed phase
  record (name, t_start, duration, attrs).
* the **attribution invariant**: when a journey finishes, its phases are
  laid out on one monotone timeline that PARTITIONS the observed wall
  time — overlapping records are clipped against a forward cursor, and
  every gap becomes an explicit ``unattributed`` phase.  By construction
  ``sum(phase durations) == wall time`` exactly, so a missing
  instrumentation site shows up as attributed-to-nothing instead of
  silently vanishing.
* **aggregates**: each finished journey feeds per-phase duration
  histograms (``paddle_tpu_journey_phase_seconds{phase,outcome}``), and
  a journey slower than the ``journey_slow_ms`` threshold dumps its full
  timeline to the flight recorder and a structured log line.
* **query surfaces**: finished journeys land in a bounded ring —
  ``GET /debug/requests/<id>`` returns one JSON timeline,
  ``GET /debug/requests?last=N`` the recent window, and
  ``tools/journey_report.py`` renders a window as a chrome trace that
  merges with the PR 2 span/counter timeline (:func:`chrome_events`
  emits the same clock base as ``trace.chrome_events``).
* :class:`TelemetryWindow` — a rolling time-windowed aggregator over
  finished journeys (queue-wait / TTFT / per-token p50/p99, shed rate,
  per-phase time shares, redispatch + rebuild counts).  The gateway
  exposes it as ``Gateway.window_stats()`` and under ``/metrics`` — the
  closed-loop input a trace-driven autoscaler consumes (ROADMAP item 5).

Duty cycle: the layer follows the PR 2 rule — ring-buffered, always on,
one host-side append per PHASE (admission, one batched dispatch, a
rebuild), never per-op and never per-token beyond the existing dispatch
boundary.  Nothing here touches the device or adds jit operands, so the
decode program count is untouched (asserted in tests/test_journey.py).
"""
from __future__ import annotations

import itertools
import json
import logging
import os
import threading
import time
import uuid
from collections import deque

from . import flight, registry

__all__ = ["Journey", "TelemetryWindow", "begin", "adopt_or_begin", "get",
           "recent", "active", "set_slow_ms", "slow_ms", "chrome_events",
           "JOURNEY_PHASE_SECONDS", "UNATTRIBUTED"]

JOURNEY_PHASE_SECONDS = "paddle_tpu_journey_phase_seconds"

# the synthetic phase name gaps surface as (never recorded explicitly)
UNATTRIBUTED = "unattributed"

logger = logging.getLogger("paddle_tpu.journey")

_lock = threading.Lock()
_seq = itertools.count(1)
# id -> live Journey (gateway handler owns begin/finish; layers append)
_active: dict[str, "Journey"] = {}
# finished journeys, oldest first — the /debug/requests?last=N window
_RING: deque = deque(
    maxlen=max(8, int(os.environ.get("PADDLE_TPU_JOURNEYS", "256"))))
# per-journey phase-record bound: decode dispatches are the only
# unbounded phase, so past the cap consecutive same-name records merge
# (the partition invariant survives; only per-dispatch granularity is
# lost on pathologically long generations)
_PHASE_CAP = max(16, int(os.environ.get("PADDLE_TPU_JOURNEY_PHASES", "512")))


def _slow_from_env() -> float | None:
    raw = os.environ.get("PADDLE_TPU_JOURNEY_SLOW_MS", "")
    try:
        v = float(raw)
    except ValueError:
        return None
    return v if v > 0 else None


_slow_ms: float | None = _slow_from_env()


def set_slow_ms(ms: float | None):
    """Set (or disable, with None) the slow-request threshold: a journey
    whose wall time reaches it dumps its full timeline to the flight
    recorder + a structured log line at finish."""
    global _slow_ms
    _slow_ms = None if ms is None or ms <= 0 else float(ms)


def slow_ms() -> float | None:
    return _slow_ms


class Journey:
    """One request's end-to-end timeline (see module docstring).

    Layers append with :meth:`phase`; the creator (the gateway handler,
    or whoever called :func:`begin`) calls :meth:`finish` exactly once.
    Thread-safe: phases arrive from handler, dispatcher and engine
    scheduler threads.
    """

    __slots__ = ("id", "t0", "t0_wall", "attrs", "_phases", "_t_first",
                 "_done", "_outcome", "_t_end", "_final", "_lock",
                 "_merged")

    def __init__(self, journey_id: str, **attrs):
        self.id = journey_id
        self.t0 = time.perf_counter()
        self.t0_wall = time.time()
        self.attrs = dict(attrs)
        self._phases: list[dict] = []   # raw records, append order
        self._t_first: float | None = None   # first generated token
        self._done = False
        self._outcome: str | None = None
        self._t_end: float | None = None
        self._final: list[dict] | None = None
        self._merged = 0
        self._lock = threading.Lock()

    # -- recording (any layer, any thread) -----------------------------------
    def phase(self, name: str, t_start: float, dur_s: float, **attrs):
        """Append one typed phase record.  ``t_start`` is a
        ``time.perf_counter()`` timestamp (the module clock), ``dur_s``
        its extent; attrs must be JSON-safe scalars.  Records may arrive
        out of order across threads — finalization sorts and clips."""
        rec = {"phase": str(name), "t": float(t_start),
               "dur": max(0.0, float(dur_s)), "attrs": attrs}
        with self._lock:
            if self._done:
                return          # late engine echo after finish: drop
            ph = self._phases
            if len(ph) >= _PHASE_CAP and ph and \
                    ph[-1]["phase"] == rec["phase"]:
                # bounded timeline: merge into the previous same-name
                # record (decode dispatches past the cap lose their
                # per-dispatch split, nothing else)
                last = ph[-1]
                last["dur"] = (rec["t"] + rec["dur"]) - last["t"]
                for k, v in attrs.items():
                    if isinstance(v, (int, float)) and \
                            isinstance(last["attrs"].get(k), (int, float)):
                        last["attrs"][k] += v
                    else:
                        last["attrs"][k] = v
                n = last["attrs"].get("merged", 1)
                last["attrs"]["merged"] = int(n) + 1
                self._merged += 1
                return
            ph.append(rec)

    def mark_first_token(self, t: float | None = None):
        """Record the first generated token's timestamp (once): the
        journey-level TTFT the window aggregator reports."""
        with self._lock:
            if self._t_first is None and not self._done:
                self._t_first = time.perf_counter() if t is None else t

    def annotate(self, **attrs):
        """Attach journey-level attrs (tenant, engine, token counts)."""
        with self._lock:
            self.attrs.update(attrs)

    # -- finalization (the creator, once) ------------------------------------
    def finish(self, outcome: str = "ok", t_end: float | None = None):
        """Close the journey: lay the raw records out as a monotone,
        gap-free partition of [t0, t_end] (gaps become ``unattributed``
        segments), feed the per-phase histograms, run the slow-request
        hook, and move the journey from the active table to the ring.
        Idempotent — the first call wins."""
        with self._lock:
            if self._done:
                return
            self._done = True
            self._outcome = str(outcome)
            self._t_end = (time.perf_counter() if t_end is None
                           else float(t_end))
            if self._t_end < self.t0:
                self._t_end = self.t0
            self._final = self._attribute_locked()
        with _lock:
            _active.pop(self.id, None)
            _RING.append(self)
        self._export()

    def _attribute_locked(self) -> list[dict]:
        """The attribution pass: sort raw records by start, clip each
        against a forward cursor from t0, insert ``unattributed``
        segments for gaps, close the tail at t_end.  The result is the
        invariant the tests assert: segment k+1 starts exactly where
        segment k ends, and the durations sum to the wall time."""
        t0, t_end = self.t0, self._t_end
        eps = 1e-6                  # sub-µs gaps are clock jitter, not time
        out: list[dict] = []
        cursor = t0
        for rec in sorted(self._phases, key=lambda r: r["t"]):
            start = max(rec["t"], cursor)
            end = min(max(rec["t"] + rec["dur"], start), t_end)
            if end <= cursor + eps:
                # fully shadowed by earlier attribution (overlapping
                # layers): keep the record's attrs on a zero segment so
                # nothing silently disappears from the JSON
                if rec["attrs"]:
                    out.append({"phase": rec["phase"], "t": cursor,
                                "dur": 0.0, "attrs": dict(rec["attrs"])})
                continue
            if start > cursor + eps:
                out.append({"phase": UNATTRIBUTED, "t": cursor,
                            "dur": start - cursor, "attrs": {}})
            else:
                start = cursor      # absorb jitter: stay gap-free
            out.append({"phase": rec["phase"], "t": start,
                        "dur": end - start, "attrs": dict(rec["attrs"])})
            cursor = end
        if t_end > cursor + eps:
            out.append({"phase": UNATTRIBUTED, "t": cursor,
                        "dur": t_end - cursor, "attrs": {}})
        elif out:
            # close the tail exactly at t_end (jitter absorbed into the
            # last segment) so the partition sums to the wall time
            out[-1]["dur"] += t_end - cursor
        return out

    def _export(self):
        hist = registry().histogram(
            JOURNEY_PHASE_SECONDS,
            "per-request journey phase durations")
        for seg in self._final:
            if seg["dur"] > 0:
                hist.observe(seg["dur"], labels={
                    "phase": seg["phase"], "outcome": self._outcome})
        thresh = _slow_ms
        wall_ms = (self._t_end - self.t0) * 1e3
        if thresh is not None and wall_ms >= thresh:
            tl = self.timeline()
            payload = json.dumps(tl["phases"])
            if len(payload) > 4096:
                payload = payload[:4096] + "...]"
            flight.record("journey", "slow", request=self.id,
                          outcome=self._outcome,
                          wall_ms=round(wall_ms, 3),
                          threshold_ms=float(thresh), phases=payload)
            logger.warning(
                "slow journey %s: %.1f ms (threshold %.1f ms) "
                "outcome=%s timeline=%s",
                self.id, wall_ms, thresh, self._outcome, payload)

    # -- introspection -------------------------------------------------------
    @property
    def done(self) -> bool:
        return self._done

    @property
    def outcome(self) -> str | None:
        return self._outcome

    @property
    def wall_s(self) -> float | None:
        return None if self._t_end is None else self._t_end - self.t0

    @property
    def ttft_s(self) -> float | None:
        """First generated token relative to journey start (None before
        a token exists)."""
        return None if self._t_first is None else self._t_first - self.t0

    def phases(self) -> list[dict]:
        """The finished, attributed partition (finished journeys) or a
        snapshot of the raw records (live ones)."""
        with self._lock:
            if self._final is not None:
                return [dict(p, attrs=dict(p["attrs"])) for p in self._final]
            return [dict(p, attrs=dict(p["attrs"])) for p in self._phases]

    def phase_totals(self) -> dict[str, float]:
        """{phase name: total attributed seconds} of a finished journey."""
        out: dict[str, float] = {}
        for seg in self.phases():
            out[seg["phase"]] = out.get(seg["phase"], 0.0) + seg["dur"]
        return out

    def timeline(self) -> dict:
        """The JSON shape /debug/requests serves: phase offsets are
        milliseconds relative to the journey start; ``mono0`` is the
        process-monotonic base (perf_counter seconds) so external tools
        can merge with the span ring's chrome events."""
        with self._lock:
            done, outcome, t_end = self._done, self._outcome, self._t_end
            t_first = self._t_first
            merged = self._merged
        return {
            "id": self.id,
            "done": done,
            "outcome": outcome,
            "t_start_unix": self.t0_wall,
            "mono0": self.t0,
            "wall_ms": (None if t_end is None
                        else round((t_end - self.t0) * 1e3, 3)),
            "ttft_ms": (None if t_first is None
                        else round((t_first - self.t0) * 1e3, 3)),
            "attrs": dict(self.attrs),
            "merged_phase_records": merged,
            "phases": [{"phase": p["phase"],
                        "t_ms": round((p["t"] - self.t0) * 1e3, 3),
                        "dur_ms": round(p["dur"] * 1e3, 3),
                        "attrs": p["attrs"]} for p in self.phases()],
        }

    def __repr__(self):
        return (f"Journey(id={self.id!r}, phases={len(self._phases)}, "
                f"done={self._done}, outcome={self._outcome})")


# -- registry ------------------------------------------------------------------

def _mint_id() -> str:
    return f"req-{uuid.uuid4().hex[:16]}"


def begin(journey_id: str | None = None, **attrs) -> Journey:
    """Start a journey; ``journey_id=None`` mints one.  An id already
    active gets a uniquifying suffix (a client reusing X-Request-Id must
    not cross-wire two live timelines)."""
    jid = _sanitize(journey_id) or _mint_id()
    with _lock:
        if jid in _active:
            jid = f"{jid}-{next(_seq)}"
        j = Journey(jid, **attrs)
        _active[jid] = j
    return j


def adopt_or_begin(header_value: str | None, **attrs) -> Journey:
    """The gateway entry point: adopt the client's ``X-Request-Id`` when
    present (so client-side and server-side traces correlate), mint
    otherwise."""
    return begin(header_value, **attrs)


def _sanitize(raw: str | None) -> str | None:
    if raw is None:
        return None
    s = "".join(c for c in str(raw).strip() if c.isprintable())[:128]
    return s or None


def get(journey_id: str) -> Journey | None:
    """Look one journey up by id — live ones first, then the ring."""
    with _lock:
        j = _active.get(journey_id)
        if j is not None:
            return j
        for j in reversed(_RING):
            if j.id == journey_id:
                return j
    return None


def recent(n: int = 32) -> list[Journey]:
    """The newest finished journeys, oldest first."""
    with _lock:
        out = list(_RING)
    return out[-max(0, int(n)):]


def active() -> list[Journey]:
    """Live (unfinished) journeys."""
    with _lock:
        return list(_active.values())


def clear():
    """Drop every finished journey and forget live ones (tests)."""
    with _lock:
        _RING.clear()
        _active.clear()


def chrome_events(journeys=None) -> list[dict]:
    """Finished journeys as chrome-trace 'X' events on the SAME clock
    base as trace.chrome_events (perf_counter * 1e6), ``"cat":
    "journey"`` — drop them into the profiler's chrome JSON next to the
    span and counter tracks and each request renders as one row of
    phase blocks."""
    pid = os.getpid()
    out = []
    for j in (recent(len(_RING) or 1) if journeys is None else journeys):
        for seg in j.phases():
            args = dict(seg["attrs"])
            args["journey"] = j.id
            out.append({"name": seg["phase"], "ph": "X",
                        "ts": seg["t"] * 1e6, "dur": seg["dur"] * 1e6,
                        "pid": pid, "tid": j.id, "cat": "journey",
                        "args": args})
    return out


# -- the windowed feed ---------------------------------------------------------

def _percentile(sorted_vals: list[float], q: float) -> float:
    """Linear-interpolated percentile over a pre-sorted list."""
    if not sorted_vals:
        return 0.0
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    pos = q * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


class TelemetryWindow:
    """Rolling time-windowed aggregate over finished journeys — the
    closed-loop feed a trace-driven autoscaler consumes (ROADMAP item
    5): queue-wait / TTFT / per-token p50+p99, shed rate, per-phase time
    shares, redispatch + rebuild counts, all over the trailing
    ``window_s`` seconds.

    Samples are **keyed** by ``(tenant, priority class)`` (ISSUE 16):
    each key owns its own bounded deque, so a noisy tenant flooding the
    window can only evict its OWN oldest samples, never another
    tenant's — per-class SLO attainment stays computable under skewed
    load.  :meth:`snapshot` aggregates globally (the PR 13 shape) or
    per key with ``by="tenant"`` / ``by="class"``; :meth:`events` hands
    the raw in-horizon samples+sheds to the SLO burn-rate evaluator.

    Feed it with :meth:`observe_journey` (one call per finished
    journey), :meth:`observe_shed` (one call per shed/rejected
    admission), or the low-level :meth:`observe_sample` (the FleetSim
    virtual-time bridge).  Bounded: at most ``max_samples_per_key``
    samples per key, at most ``max_keys`` keys (least-recently-fed key
    evicted first), oldest-in-key dropped first.
    """

    # phases whose attributed time counts as "waiting in a queue" for
    # the queue_wait percentile (gateway fair-share + engine admission)
    QUEUE_PHASES = ("queue", "engine_queue", "adapter_stall", "page_stall")

    def __init__(self, window_s: float = 60.0, max_samples: int = 4096,
                 *, max_samples_per_key: int | None = None,
                 max_keys: int = 64):
        if window_s <= 0:
            raise ValueError("window_s must be > 0")
        self.window_s = float(window_s)
        self.max_samples = max(16, int(max_samples))
        # per-key bound: a key never holds more than this, so the
        # worst-case retention is max_keys * max_samples_per_key
        self.max_samples_per_key = (
            max(16, self.max_samples // 8) if max_samples_per_key is None
            else max(16, int(max_samples_per_key)))
        self.max_keys = max(1, int(max_keys))
        self._lock = threading.Lock()
        # (tenant, priority) -> deque; separate stores for samples and
        # sheds, one LRU clock across both for key eviction
        self._samples: dict[tuple, deque] = {}
        self._sheds: dict[tuple, deque] = {}
        self._touched: dict[tuple, float] = {}

    @staticmethod
    def _key(tenant, priority) -> tuple:
        return (str(tenant or ""), str(priority or ""))

    def _deque_for_locked(self, store: dict, key: tuple,
                          now: float) -> deque:
        dq = store.get(key)
        if dq is None:
            dq = store[key] = deque(maxlen=self.max_samples_per_key)
        self._touched[key] = now
        known = set(self._samples) | set(self._sheds)
        while len(known) > self.max_keys:
            victim = min(known - {key},
                         key=lambda k: self._touched.get(k, 0.0))
            self._samples.pop(victim, None)
            self._sheds.pop(victim, None)
            self._touched.pop(victim, None)
            known.discard(victim)
        return dq

    # -- feeding -------------------------------------------------------------
    def observe_journey(self, j: Journey, now: float | None = None, *,
                        tenant: str | None = None,
                        priority: str | None = None):
        """Fold one FINISHED journey in (unfinished ones are skipped:
        their partition does not exist yet).  Tenant and priority class
        default to the journey's own attrs (the gateway annotates both
        at admission)."""
        if j is None or not j.done:
            return
        totals = j.phase_totals()
        queue_wait = sum(totals.get(p, 0.0) for p in self.QUEUE_PHASES)
        decode_s = totals.get("decode", 0.0)
        tokens = 0
        redispatches = 0
        rebuilds = 0
        for seg in j.phases():
            name = seg["phase"]
            if name == "decode":
                tokens += int(seg["attrs"].get("emitted", 0) or 0)
            elif name == "redispatch":
                redispatches += 1
            elif name == "rebuild":
                rebuilds += 1
        self.observe_sample(
            now=now,
            wall_s=j.wall_s or 0.0,
            ttft_s=j.ttft_s,
            queue_wait_s=queue_wait,
            # decode emits the first-of-run token too, but the FIRST
            # token of the request came from prefill — per-token decode
            # latency divides decode time by the decode-emitted count
            token_s=(decode_s / tokens) if tokens > 0 else None,
            phase_totals=totals,
            outcome=j.outcome or "ok",
            redispatches=redispatches,
            rebuilds=rebuilds,
            tenant=tenant if tenant is not None else j.attrs.get("tenant"),
            priority=(priority if priority is not None
                      else j.attrs.get("priority")))

    def observe_sample(self, *, now: float | None = None,
                       wall_s: float = 0.0, ttft_s: float | None = None,
                       queue_wait_s: float | None = None,
                       token_s: float | None = None,
                       phase_totals: dict | None = None,
                       outcome: str = "ok", redispatches: int = 0,
                       rebuilds: int = 0, tenant: str | None = None,
                       priority: str | None = None):
        """Low-level feed: one finished-request sample without a Journey
        object — the bridge FleetSim uses to drive the window (and the
        SLO evaluator on top of it) in virtual time."""
        t = time.perf_counter() if now is None else float(now)
        tenant, priority = self._key(tenant, priority)
        sample = {
            "t": t, "wall_s": float(wall_s),
            "ttft_s": None if ttft_s is None else float(ttft_s),
            "queue_wait_s": (None if queue_wait_s is None
                             else float(queue_wait_s)),
            "token_s": None if token_s is None else float(token_s),
            "phase_totals": dict(phase_totals or {}),
            "outcome": str(outcome),
            "redispatches": int(redispatches),
            "rebuilds": int(rebuilds),
            "tenant": tenant, "priority": priority,
        }
        with self._lock:
            self._deque_for_locked(
                self._samples, (tenant, priority), t).append(sample)

    def observe_shed(self, reason: str = "", now: float | None = None, *,
                     tenant: str | None = None,
                     priority: str | None = None):
        """One shed/rejected admission, attributed to its tenant and
        priority class (ISSUE 16: the shed deque used to hold only
        ``(t, reason)``, making per-tenant shed rate uncomputable)."""
        t = time.perf_counter() if now is None else float(now)
        tenant, priority = self._key(tenant, priority)
        shed = {"t": t, "reason": str(reason),
                "tenant": tenant, "priority": priority}
        with self._lock:
            self._deque_for_locked(
                self._sheds, (tenant, priority), t).append(shed)

    # -- reading -------------------------------------------------------------
    def _prune_locked(self, now: float):
        horizon = now - self.window_s
        for store in (self._samples, self._sheds):
            for key in list(store):
                dq = store[key]
                while dq and dq[0]["t"] < horizon:
                    dq.popleft()
                if not dq:
                    del store[key]
        for key in list(self._touched):
            if key not in self._samples and key not in self._sheds:
                del self._touched[key]

    def _collect_locked(self, now: float, horizon_s: float | None,
                        tenant: str | None, priority: str | None):
        lo = now - min(self.window_s, horizon_s if horizon_s is not None
                       else self.window_s)
        out = []
        for store in (self._samples, self._sheds):
            rows = []
            for key, dq in store.items():
                if tenant is not None and key[0] != str(tenant):
                    continue
                if priority is not None and key[1] != str(priority):
                    continue
                rows.extend(r for r in dq if lo <= r["t"] <= now)
            out.append(rows)
        return out[0], out[1]

    def events(self, now: float | None = None, *,
               horizon_s: float | None = None, tenant: str | None = None,
               priority: str | None = None) -> tuple:
        """``(samples, sheds)`` inside the trailing ``horizon_s``
        (clamped to ``window_s``), optionally filtered to one tenant
        and/or priority class — the raw feed the SLO burn-rate
        evaluator counts good/bad events over."""
        now = time.perf_counter() if now is None else float(now)
        with self._lock:
            self._prune_locked(now)
            samples, sheds = self._collect_locked(
                now, horizon_s, tenant, priority)
        return ([dict(s) for s in samples], [dict(s) for s in sheds])

    def keys(self, now: float | None = None) -> list:
        """The ``(tenant, priority)`` keys with in-window data."""
        now = time.perf_counter() if now is None else float(now)
        with self._lock:
            self._prune_locked(now)
            return sorted(set(self._samples) | set(self._sheds))

    @staticmethod
    def _aggregate(samples: list, sheds: list) -> dict:
        def _pcts(key):
            vals = sorted(s[key] for s in samples if s[key] is not None)
            return {"p50": round(_percentile(vals, 0.50), 6),
                    "p99": round(_percentile(vals, 0.99), 6),
                    "n": len(vals)}

        phase_totals: dict[str, float] = {}
        for s in samples:
            for name, dur in s["phase_totals"].items():
                phase_totals[name] = phase_totals.get(name, 0.0) + dur
        attributed = sum(phase_totals.values())
        shares = {name: round(dur / attributed, 4)
                  for name, dur in sorted(phase_totals.items())} \
            if attributed > 0 else {}
        n_requests = len(samples)
        n_shed = len(sheds)
        denominator = n_requests + n_shed
        return {
            "requests": n_requests,
            "shed": n_shed,
            "shed_rate": round(n_shed / denominator, 4) if denominator
            else 0.0,
            "shed_reasons": _count_by(sheds, "reason"),
            "ttft_s": _pcts("ttft_s"),
            "queue_wait_s": _pcts("queue_wait_s"),
            "token_s": _pcts("token_s"),
            "phase_share": shares,
            "redispatches": sum(s["redispatches"] for s in samples),
            "rebuilds": sum(s["rebuilds"] for s in samples),
            "outcomes": _count_by(samples, "outcome"),
        }

    def snapshot(self, now: float | None = None,
                 by: str | None = None) -> dict:
        """The window aggregate, computed fresh (sorting a few thousand
        floats at poll rate, not request rate).  ``by=None`` is the
        global aggregate; ``by="tenant"`` / ``by="class"`` group by the
        sample key's tenant / priority-class component — the per-key
        feed SLO objectives with a ``per=`` selector evaluate over."""
        if by not in (None, "tenant", "class"):
            raise ValueError('by must be None, "tenant" or "class"')
        now = time.perf_counter() if now is None else float(now)
        with self._lock:
            self._prune_locked(now)
            samples, sheds = self._collect_locked(now, None, None, None)
        if by is None:
            out = {"window_s": self.window_s}
            out.update(self._aggregate(samples, sheds))
            return out
        field = "tenant" if by == "tenant" else "priority"
        groups: dict[str, tuple] = {}
        for s in samples:
            groups.setdefault(s[field], ([], []))[0].append(s)
        for s in sheds:
            groups.setdefault(s[field], ([], []))[1].append(s)
        return {
            "window_s": self.window_s,
            "by": by,
            "keys": {name: self._aggregate(ss, sh)
                     for name, (ss, sh) in sorted(groups.items())},
        }


def _count_by(samples, key) -> dict:
    out: dict[str, int] = {}
    for s in samples:
        out[s[key]] = out.get(s[key], 0) + 1
    return out
