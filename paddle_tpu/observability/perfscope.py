"""Device perfscope — per-program device-time/MFU attribution + the HBM
ownership ledger (the device-side twin of the journey layer).

PR 13's request journeys partition *host* wall time exactly; nothing
attributed *device* time or HBM bytes.  This module closes that gap with
two always-available registries:

**Per-program device accounting.**  Every :class:`retrace.InstrumentedJit`
entry point (the SPMD train steps, ``jit.to_static`` caches, and the
serving engine's prefill / tail_prefill / prefix_copy / decode programs)
registers its compiled ``cost_analysis`` (flops + bytes accessed) once
per abstract signature, and a sampling timer measures device seconds:
with ``PADDLE_TPU_PERFSCOPE_SAMPLE=N`` (or :func:`set_sample_every`),
every Nth dispatch of a program is bracketed with a
``block_until_ready`` — the other ``N-1`` dispatches stay fully async,
and the decode hot path keeps its ONE compiled signature (sampling never
touches the arguments, test-asserted).  Dividing the sampled wall by the
:mod:`~paddle_tpu.distributed.auto_parallel.cluster` peak table (CPU
carries a synthetic peak so the math is tier-1-testable) yields live

* ``paddle_tpu_device_program_seconds{program}`` — sampled device
  seconds (counter),
* ``paddle_tpu_device_program_mfu{program}`` — model-flops utilization
  of the last sampled dispatch (gauge),
* ``paddle_tpu_device_program_hbm_bw_frac{program}`` — fraction of peak
  HBM bandwidth (gauge),

plus :func:`perf_report` (the ``GET /debug/perf`` JSON roofline table)
and :func:`chrome_events` (sampled program intervals as a
``"cat": "device"`` lane that merges with the PR 2 span ring and the
journey tracks on one timeline).

**HBM ownership ledger.**  Long-lived device allocations declare a named
owner (``weights`` incl. int8 + scales, ``kv_pool`` / page pool,
``adapter_bank``, ``prefix_cache`` retained rows — a *nested*
sub-account of the pool bytes — and ``prefetch`` buffers):
``ledger().register(owner, nbytes)`` returns a row with
``update``/``add``/``release``; per-owner sums export as
``paddle_tpu_hbm_bytes{owner}`` and :func:`memory_report` (the
``GET /debug/memory`` JSON) reconciles them against the backend's
``bytes_in_use`` with an explicit ``unattributed`` remainder.  The
ledger is always on (flight-recorder duty cycle: a few rows per engine
build, never per-op) so an allocation failure can name its owner:
:func:`note_exception` detects RESOURCE_EXHAUSTED, records an ``oom``
flight event with the owner table, and writes a watchdog crash bundle
whose ``hbm_ledger`` section carries the full ledger — an OOM becomes an
artifact that says *who* held the HBM, not just that it ran out.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque

from . import flight, registry

logger = logging.getLogger("paddle_tpu.observability")

# -- metric names --------------------------------------------------------------
DEVICE_PROGRAM_SECONDS = "paddle_tpu_device_program_seconds"
DEVICE_PROGRAM_MFU = "paddle_tpu_device_program_mfu"
DEVICE_PROGRAM_BW_FRAC = "paddle_tpu_device_program_hbm_bw_frac"
HBM_BYTES = "paddle_tpu_hbm_bytes"

# ledger owners whose bytes live in host DRAM, not on the device: part of
# the consolidated KV budget, excluded from the bytes_in_use reconciliation
HOST_OWNERS = frozenset({"host_prefix"})

_lock = threading.Lock()

# sample every Nth dispatch per program; 0 = sampling off (the default:
# the hot path then costs one integer compare per dispatch)
_SAMPLE = [max(0, int(os.environ.get("PADDLE_TPU_PERFSCOPE_SAMPLE",
                                     "0") or 0))]
# sampled program intervals (the cat:"device" chrome lane)
_RING: deque = deque(
    maxlen=max(16, int(os.environ.get("PADDLE_TPU_PERFSCOPE_RING", "2048"))))
# (peak_flops, peak_hbm_bw) — resolved lazily from the cluster table
_peaks: list = [None]


def sample_every() -> int:
    return _SAMPLE[0]


def set_sample_every(n: int):
    """Sample one in every ``n`` dispatches per program (0 disables)."""
    _SAMPLE[0] = max(0, int(n))


def sampling_active() -> bool:
    return _SAMPLE[0] > 0


def _telemetry_on() -> bool:
    from ..core import op as op_mod
    return bool(op_mod.TELEMETRY)


# -- peaks ---------------------------------------------------------------------

def peaks() -> tuple:
    """(peak FLOP/s, peak HBM bytes/s) of the live backend, from the
    cluster spec table.  CPU resolves to the synthetic spec-sheet entry
    so MFU math is exercised (and testable) in tier-1."""
    p = _peaks[0]
    if p is None:
        try:
            from ..distributed.auto_parallel.cluster import Cluster
            c = Cluster.auto()
            p = (float(c.peak_flops()), float(c.peak_hbm_bw()))
        except Exception:  # noqa: BLE001 — no backend: MFU just stays None
            p = (0.0, 0.0)
        _peaks[0] = p
    return p


def set_peaks(flops: float, hbm_bw: float):
    """Pin the peak table (tests / explicit hardware description)."""
    _peaks[0] = (float(flops), float(hbm_bw))


def reset_peaks():
    _peaks[0] = None


# -- per-program accounting ----------------------------------------------------

class _ProgramStats:
    __slots__ = ("name", "costs", "dispatches", "sampled",
                 "device_seconds", "last")

    def __init__(self, name: str):
        self.name = name
        self.costs: dict = {}        # signature key -> {"flops", "bytes"}
        self.dispatches = 0
        self.sampled = 0
        self.device_seconds = 0.0
        self.last: dict | None = None


_programs: dict[str, _ProgramStats] = {}


def _program(name: str) -> _ProgramStats:
    st = _programs.get(name)
    if st is None:
        st = _programs[name] = _ProgramStats(name)
    return st


def poll_sample(program: str) -> bool:
    """Count one dispatch of ``program``; True when THIS dispatch should
    be timed (every ``sample_every()``-th).  Callers only invoke this
    while :func:`sampling_active`."""
    n = _SAMPLE[0]
    with _lock:
        st = _program(program)
        st.dispatches += 1
        return n > 0 and st.dispatches % n == 0


def register_cost(program: str, key, cost: dict):
    """Book one compiled signature's ``cost_analysis`` numbers (called
    once per signature, at compile time)."""
    with _lock:
        _program(program).costs[str(key)[:256]] = {
            "flops": float(cost.get("flops", 0.0) or 0.0),
            "bytes": float(cost.get("bytes accessed", 0.0) or 0.0),
        }


def register_program(program: str, key, fn, args, kwargs):
    """Cost registration hook for :class:`retrace.InstrumentedJit`: AOT
    lower+compile the entry point at the signature just compiled and book
    its cost.  Only runs when the perfscope is live (sampling on or
    telemetry on) — the lower/compile is once per signature, the same
    order of work as the compile that just happened."""
    if not (sampling_active() or _telemetry_on()):
        return
    try:
        from .._compat import cost_analysis
        cost = cost_analysis(fn.lower(*args, **kwargs).compile())
    except Exception:  # noqa: BLE001 — AOT path missing on this fn: no cost
        return
    register_cost(program, key, cost)


def block_ready(out):
    """The sampling barrier (module-level so tests can count calls)."""
    import jax
    jax.block_until_ready(out)


def record_sample(program: str, key, seconds: float):
    """Book one sampled dispatch: ``seconds`` is the host-observed wall
    of a blocked call (dispatch + device; on a warm async backend the
    device term dominates).  Updates the roofline stats, the device-lane
    ring, and (telemetry on) the exported series."""
    seconds = max(float(seconds), 1e-12)
    pf, pb = peaks()
    with _lock:
        st = _program(program)
        st.sampled += 1
        st.device_seconds += seconds
        cost = st.costs.get(str(key)[:256]) or {}
        flops = cost.get("flops", 0.0)
        bts = cost.get("bytes", 0.0)
        mfu = (flops / (seconds * pf)) if flops and pf else None
        bw = (bts / (seconds * pb)) if bts and pb else None
        st.last = {"seconds": seconds, "mfu": mfu, "bw_frac": bw,
                   "flops": flops, "bytes": bts}
        _RING.append({"program": program, "ts": time.perf_counter() * 1e6,
                      "dur": seconds * 1e6, "mfu": mfu, "bw_frac": bw,
                      "flops": flops, "bytes": bts})
    if _telemetry_on():
        reg = registry()
        reg.counter(DEVICE_PROGRAM_SECONDS,
                    "sampled device seconds per compiled program").inc(
            seconds, labels={"program": program})
        if mfu is not None:
            reg.gauge(DEVICE_PROGRAM_MFU,
                      "model-flops utilization of the last sampled "
                      "dispatch").set(mfu, labels={"program": program})
        if bw is not None:
            reg.gauge(DEVICE_PROGRAM_BW_FRAC,
                      "fraction of peak HBM bandwidth of the last "
                      "sampled dispatch").set(bw, labels={"program": program})


def program_stats(program: str) -> dict | None:
    """One program's accounting as plain data (None when never seen)."""
    with _lock:
        st = _programs.get(program)
        if st is None:
            return None
        return {"program": st.name, "signatures": len(st.costs),
                "dispatches": st.dispatches, "sampled": st.sampled,
                "device_seconds": st.device_seconds,
                "costs": dict(st.costs), "last": dict(st.last or {})}


def perf_report() -> dict:
    """The ``GET /debug/perf`` roofline table: one row per program with
    dispatch/sample counts, sampled device time, the estimated total
    (mean sampled dt x dispatches), its share of the estimated step, and
    the cost-derived MFU / HBM-bandwidth fractions."""
    pf, pb = peaks()
    rows = []
    with _lock:
        stats = list(_programs.values())
        for st in stats:
            mean_dt = (st.device_seconds / st.sampled) if st.sampled else None
            # estimated total device time: mean sampled dt x dispatches
            # (every dispatch counted while sampling; direct
            # record_sample feeds fall back to the sampled count)
            est = (mean_dt * max(st.dispatches, st.sampled)
                   if mean_dt is not None else None)
            # the roofline row uses the largest-cost signature (the
            # steady-state program; tiny warmup signatures would
            # understate flops)
            cost = max(st.costs.values(), key=lambda c: c["flops"],
                       default={"flops": 0.0, "bytes": 0.0})
            mfu = (cost["flops"] / (mean_dt * pf)
                   if mean_dt and cost["flops"] and pf else None)
            bw = (cost["bytes"] / (mean_dt * pb)
                  if mean_dt and cost["bytes"] and pb else None)
            rows.append({
                "program": st.name, "signatures": len(st.costs),
                "dispatches": st.dispatches, "sampled": st.sampled,
                "device_s": round(st.device_seconds, 6),
                "est_total_s": None if est is None else round(est, 6),
                "flops": cost["flops"], "bytes": cost["bytes"],
                "mfu": None if mfu is None else round(mfu, 6),
                "hbm_bw_frac": None if bw is None else round(bw, 6),
                "last": dict(st.last) if st.last else None,
            })
    total_est = sum(r["est_total_s"] or 0.0 for r in rows)
    for r in rows:
        r["share"] = (round((r["est_total_s"] or 0.0) / total_est, 4)
                      if total_est > 0 else 0.0)
    rows.sort(key=lambda r: -(r["est_total_s"] or 0.0))
    return {"sample_every": _SAMPLE[0], "peak_flops": pf,
            "peak_hbm_bw": pb, "programs": rows}


def chrome_events() -> list[dict]:
    """Sampled program intervals as chrome-trace 'X' events on the SAME
    perf_counter*1e6 clock base as ``trace.chrome_events`` and the
    journey tracks, ``"cat": "device"`` — one lane per program."""
    pid = os.getpid()
    with _lock:
        samples = list(_RING)
    out = []
    for s in samples:
        args = {k: s[k] for k in ("mfu", "bw_frac", "flops", "bytes")
                if s[k] is not None}
        out.append({"name": s["program"], "ph": "X",
                    "ts": s["ts"] - s["dur"], "dur": s["dur"], "pid": pid,
                    "tid": f"device:{s['program']}", "cat": "device",
                    "args": args})
    return out


def reset_programs():
    """Drop program stats + the device-lane ring (bench per-leg deltas,
    tests).  The HBM ledger is NOT touched — its rows mirror live
    allocations."""
    with _lock:
        _programs.clear()
        _RING.clear()


# -- the HBM ownership ledger --------------------------------------------------

class LedgerRow:
    """One owned long-lived device allocation.  ``nested`` rows are
    informational sub-accounts of bytes already counted by a top-level
    owner (e.g. prefix-cache retained rows inside the KV pool) — they
    never contribute to the ledger total."""

    __slots__ = ("owner", "detail", "nbytes", "nested", "_ledger",
                 "_released")

    def __init__(self, ledger, owner: str, nbytes: int, detail, nested):
        self.owner = str(owner)
        self.detail = detail
        self.nbytes = max(0, int(nbytes))
        self.nested = bool(nested)
        self._ledger = ledger
        self._released = False

    @property
    def released(self) -> bool:
        return self._released

    def update(self, nbytes: int):
        """Set this row's byte count (in-place resize)."""
        self._ledger._set(self, max(0, int(nbytes)))

    def add(self, delta: int):
        """Adjust this row's byte count by ``delta`` (clamped at 0)."""
        self._ledger._add(self, int(delta))

    def release(self):
        """Drop the row (the allocation was freed).  Idempotent."""
        self._ledger._release(self)


class HbmLedger:
    """Registry of named long-lived device allocations (see module doc).
    Always on; one lock-guarded dict update per register/update/release
    — never per-op, never per-dispatch."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rows: list[LedgerRow] = []
        self.registered_total = 0       # rows ever registered (chaos lane)
        self.released_total = 0

    def register(self, owner: str, nbytes: int = 0, detail=None,
                 nested: bool = False) -> LedgerRow:
        row = LedgerRow(self, owner, nbytes, detail, nested)
        with self._lock:
            self._rows.append(row)
            self.registered_total += 1
        self._export(row.owner, row.nested)
        return row

    # -- row plumbing --------------------------------------------------------
    def _set(self, row: LedgerRow, nbytes: int):
        with self._lock:
            if row._released:
                return
            row.nbytes = nbytes
        self._export(row.owner, row.nested)

    def _add(self, row: LedgerRow, delta: int):
        with self._lock:
            if row._released:
                return
            row.nbytes = max(0, row.nbytes + delta)
        self._export(row.owner, row.nested)

    def _release(self, row: LedgerRow):
        with self._lock:
            if row._released:
                return
            row._released = True
            self._rows.remove(row)
            self.released_total += 1
        self._export(row.owner, row.nested)

    def _export(self, owner: str, nested: bool):
        """Refresh the owner's gauge after any row change (telemetry
        on); nested owners export too — their gauge is the sub-account,
        not part of the total."""
        if not _telemetry_on():
            return
        with self._lock:
            total = sum(r.nbytes for r in self._rows if r.owner == owner)
        registry().gauge(
            HBM_BYTES,
            "device bytes held per declared owner (HBM ledger)").set(
            float(total), labels={"owner": owner})

    # -- reading -------------------------------------------------------------
    def owner_bytes(self) -> dict:
        """{owner: bytes} over top-level rows (the partition that sums
        to :meth:`total`)."""
        out: dict[str, int] = {}
        with self._lock:
            for r in self._rows:
                if not r.nested:
                    out[r.owner] = out.get(r.owner, 0) + r.nbytes
        return out

    def nested_bytes(self) -> dict:
        out: dict[str, int] = {}
        with self._lock:
            for r in self._rows:
                if r.nested:
                    out[r.owner] = out.get(r.owner, 0) + r.nbytes
        return out

    def total(self) -> int:
        with self._lock:
            return sum(r.nbytes for r in self._rows if not r.nested)

    def rows(self) -> list[dict]:
        with self._lock:
            return [{"owner": r.owner, "bytes": r.nbytes,
                     "nested": r.nested, "detail": r.detail}
                    for r in self._rows]

    def snapshot(self) -> dict:
        """JSON-safe ledger state (the watchdog bundle section and the
        OOM flight payload)."""
        return {"owners": self.owner_bytes(), "nested": self.nested_bytes(),
                "total": self.total(), "rows": self.rows(),
                "registered_total": self.registered_total,
                "released_total": self.released_total}


_LEDGER = HbmLedger()


def ledger() -> HbmLedger:
    """The process-wide HBM ownership ledger (always usable)."""
    return _LEDGER


def memory_report() -> dict:
    """The ``GET /debug/memory`` JSON: per-owner bytes, the tracked
    total, the backend allocator's view, and the unattributed remainder
    (``bytes_in_use`` the ledger cannot name — jit temporaries, XLA
    scratch, untracked arrays)."""
    led = ledger()
    owners = led.owner_bytes()
    total = sum(owners.values())
    # host-plane rows (the host prefix tier) live in the same ledger for
    # one consolidated budget, but must not count against the device
    # allocator when reconciling bytes_in_use
    device_total = total - sum(owners.get(o, 0) for o in HOST_OWNERS)
    backend = {}
    try:
        from ..device.tpu import memory_stats
        backend = {k: int(v) for k, v in memory_stats(0).items()
                   if isinstance(v, (int, float))}
    except Exception:  # noqa: BLE001 — no backend stats on this platform
        backend = {}
    out = {"owners": owners, "nested": led.nested_bytes(),
           "total_tracked": total, "backend": backend,
           "rows": led.rows()}
    if "bytes_in_use" in backend:
        out["unattributed"] = int(backend["bytes_in_use"]) - device_total
    return out


# -- OOM forensics -------------------------------------------------------------

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory")
_oom_dumped: set = set()


def looks_like_oom(exc: BaseException) -> bool:
    text = f"{type(exc).__name__}: {exc}"
    return any(m in text for m in _OOM_MARKERS)


def note_exception(exc: BaseException, program: str = "") -> bool:
    """Allocation-failure forensics: when ``exc`` is a RESOURCE_EXHAUSTED
    (device OOM), record an ``oom`` flight event carrying the owner
    table and write ONE watchdog bundle per program (the bundle's
    ``hbm_ledger`` section holds the full ledger + the flight tail shows
    what led up to it).  Returns whether the exception matched."""
    if not looks_like_oom(exc):
        return False
    snap = ledger().snapshot()
    flight.record("oom", program or "device",
                  error=f"{type(exc).__name__}: {str(exc)[:512]}",
                  total_tracked=snap["total"],
                  owners=json.dumps(snap["owners"]))
    logger.warning(
        "paddle_tpu perfscope: %s",
        json.dumps({"event": "resource_exhausted",
                    "program": program or None,
                    "owners": snap["owners"],
                    "total_tracked": snap["total"],
                    "hint": "device OOM — the hbm_ledger section of the "
                            "crash bundle names who holds the bytes; "
                            "see GET /debug/memory on a live server"}))
    if program not in _oom_dumped:
        _oom_dumped.add(program)
        from . import watchdog
        watchdog.dump(f"resource_exhausted:{program or 'device'}")
    return True


def reset_oom_dumps():
    """Re-arm the one-bundle-per-program guard (tests)."""
    _oom_dumped.clear()


# the crash bundle carries the ledger: an OOM artifact names its owners
from . import watchdog as _watchdog  # noqa: E402

_watchdog.add_section("hbm_ledger", lambda: ledger().snapshot())
