"""Process-wide metrics registry — counters, gauges, histograms with labels.

The reference stack scatters its runtime accounting across host_tracer.cc
RecordEvents, CUPTI device streams and ad-hoc VLOG counters; here ONE
registry owns every runtime series so the op layer, the retrace sentinel and
the train-step instrumentation all land in the same snapshot.  The shape of
the API follows the Prometheus client convention (metric → labeled child →
inc/set/observe) because that is the export format operators already parse:
``to_prometheus_text()`` is scrape-ready, ``dump()`` is the JSON twin.

Time-series samples: when sampling is enabled (telemetry on), counter and
gauge updates append (ts, name, labels, value) into a bounded ring so the
profiler can merge them into its chrome-trace output as 'C' (counter)
events — host spans and metric series on one timeline.
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque

# default latency buckets (seconds) — spans eager-op dispatch (~50us) to
# cold XLA compiles (~100s)
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)


def _label_key(labels: dict | None) -> tuple:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Metric:
    """Base: one named family holding children keyed by label values."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "", registry=None):
        self.name = name
        self.help = help
        self._children: dict[tuple, object] = {}
        self._lock = threading.Lock()
        self._registry = registry

    def _child(self, labels, default):
        key = _label_key(labels)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = default()
            return child

    def _sample(self, labels, value):
        reg = self._registry
        if reg is not None and reg.sampling:
            reg.record_sample(self.name, value, labels)

    def series(self):
        """[(labels-dict, child), ...] snapshot."""
        with self._lock:
            return [(dict(k), v) for k, v in self._children.items()]

    def remove(self, labels: dict | None = None) -> bool:
        """Delete one labeled series (True if it existed).  For series
        keyed by a dynamic entity — an engine replica, an adapter — the
        entity's removal must delete its series, not freeze it at the
        last value: a dashboard showing a dead replica's stale occupancy
        is a mis-diagnosis trap."""
        with self._lock:
            return self._children.pop(_label_key(labels), None) is not None


class Counter(_Metric):
    kind = "counter"

    def inc(self, value: float = 1.0, labels: dict | None = None):
        if value < 0:
            raise ValueError("counters only go up")
        key = _label_key(labels)
        with self._lock:
            new = self._children.get(key, 0.0) + value
            self._children[key] = new
        self._sample(labels, new)
        return new

    def value(self, labels: dict | None = None) -> float:
        with self._lock:
            return self._children.get(_label_key(labels), 0.0)

    def total(self) -> float:
        """Sum over every label combination."""
        with self._lock:
            return float(sum(self._children.values()))


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, labels: dict | None = None):
        with self._lock:
            self._children[_label_key(labels)] = float(value)
        self._sample(labels, float(value))

    def inc(self, value: float = 1.0, labels: dict | None = None):
        key = _label_key(labels)
        with self._lock:
            new = self._children.get(key, 0.0) + value
            self._children[key] = new
        self._sample(labels, new)

    def dec(self, value: float = 1.0, labels: dict | None = None):
        self.inc(-value, labels)

    def value(self, labels: dict | None = None) -> float:
        with self._lock:
            return self._children.get(_label_key(labels), 0.0)


class _HistValue:
    __slots__ = ("buckets", "counts", "count", "sum")

    def __init__(self, buckets):
        self.buckets = buckets
        self.counts = [0] * len(buckets)
        self.count = 0
        self.sum = 0.0


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help="", buckets=DEFAULT_BUCKETS, registry=None):
        super().__init__(name, help, registry)
        self.buckets = tuple(sorted(buckets))

    def observe(self, value: float, labels: dict | None = None):
        h = self._child(labels, lambda: _HistValue(self.buckets))
        with self._lock:
            h.count += 1
            h.sum += float(value)
            # cumulative bucket counts, the prometheus convention:
            # counts[i] = observations <= buckets[i]
            for i, le in enumerate(self.buckets):
                if value <= le:
                    h.counts[i] += 1

    def snapshot(self, labels: dict | None = None) -> dict:
        """{count, sum, buckets: {le: cumulative count}}."""
        with self._lock:
            h = self._children.get(_label_key(labels))
            if h is None:
                return {"count": 0, "sum": 0.0, "buckets": {}}
            return {"count": h.count, "sum": h.sum,
                    "buckets": {str(le): c
                                for le, c in zip(self.buckets, h.counts)}}

    def count(self, labels: dict | None = None) -> int:
        with self._lock:
            h = self._children.get(_label_key(labels))
            return h.count if h else 0

    def total_count(self) -> int:
        with self._lock:
            return sum(h.count for h in self._children.values())


class MetricsRegistry:
    """Named metric families + the chrome-trace sample ring."""

    def __init__(self, max_samples: int = 8192):
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()
        self.sampling = False
        self._samples: deque = deque(maxlen=max_samples)

    # -- registration --------------------------------------------------------
    def _register(self, cls, name, help, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as {m.kind}")
                return m
            m = cls(name, help, registry=self, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._register(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._register(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._register(Histogram, name, help, buckets=buckets)

    def get(self, name: str) -> _Metric | None:
        with self._lock:
            return self._metrics.get(name)

    def reset(self):
        """Drop every series and sample (bench uses this between legs)."""
        with self._lock:
            self._metrics.clear()
            self._samples.clear()

    # -- chrome-trace samples ------------------------------------------------
    def record_sample(self, name, value, labels=None, ts=None):
        self._samples.append({
            "name": name, "value": float(value),
            "labels": dict(labels) if labels else {},
            "ts": time.perf_counter() * 1e6 if ts is None else ts})

    def samples(self) -> list[dict]:
        return list(self._samples)

    # -- export --------------------------------------------------------------
    def dump(self) -> dict:
        """JSON-ready snapshot: {kind: {name: [{labels, ...}, ...]}}."""
        out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            if isinstance(m, Histogram):
                out["histograms"][m.name] = [
                    {"labels": labels, **m.snapshot(labels)}
                    for labels, _ in m.series()]
            elif isinstance(m, Counter):
                out["counters"][m.name] = [
                    {"labels": labels, "value": v} for labels, v in m.series()]
            elif isinstance(m, Gauge):
                out["gauges"][m.name] = [
                    {"labels": labels, "value": v} for labels, v in m.series()]
        return out

    def to_json(self) -> str:
        return json.dumps(self.dump(), sort_keys=True)

    def to_prometheus_text(self) -> str:
        """Prometheus exposition text format (scrape-ready)."""
        def fmt_labels(labels: dict, extra: dict | None = None) -> str:
            items = dict(labels)
            if extra:
                items.update(extra)
            if not items:
                return ""
            body = ",".join(f'{k}="{v}"' for k, v in sorted(items.items()))
            return "{" + body + "}"

        lines = []
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            if isinstance(m, Histogram):
                for labels, _ in m.series():
                    snap = m.snapshot(labels)
                    for le in m.buckets:
                        lines.append(
                            f"{m.name}_bucket"
                            f"{fmt_labels(labels, {'le': le})} "
                            f"{snap['buckets'].get(str(le), 0)}")
                    lines.append(
                        f"{m.name}_bucket{fmt_labels(labels, {'le': '+Inf'})}"
                        f" {snap['count']}")
                    lines.append(
                        f"{m.name}_sum{fmt_labels(labels)} {snap['sum']}")
                    lines.append(
                        f"{m.name}_count{fmt_labels(labels)} {snap['count']}")
            else:
                for labels, v in m.series():
                    lines.append(f"{m.name}{fmt_labels(labels)} {v}")
        return "\n".join(lines) + "\n"
