"""Step-level training metrics — latency, throughput, device memory.

Fed by the SPMD train step (`distributed/spmd.py`), the hapi
``TelemetryCallback`` and anything else that owns a step boundary.  Host
latency on an async backend measures dispatch, not device time — but a
dispatch-bound loop is exactly the pathology worth seeing, and on a
steady-state synced loop the two converge.
"""
from __future__ import annotations

from . import registry

STEP_LATENCY = "paddle_tpu_step_latency_seconds"
STEPS_TOTAL = "paddle_tpu_steps_total"
EXAMPLES_TOTAL = "paddle_tpu_examples_total"
EXAMPLES_PER_SEC = "paddle_tpu_examples_per_sec"
MEMORY_GAUGE = "paddle_tpu_device_memory_bytes"
# input-pipeline metrics (io/prefetch.py DevicePrefetcher)
HOST_INPUT_WAIT = "paddle_tpu_host_input_wait_seconds_total"
PREFETCH_DEPTH = "paddle_tpu_prefetch_buffer_depth"
PREFETCH_BATCHES = "paddle_tpu_prefetch_batches_total"
PIPELINE_STALLS = "paddle_tpu_pipeline_stalls_total"


def record_step(seconds: float, examples: int | None = None,
                fn: str = "train_step"):
    reg = registry()
    labels = {"fn": fn}
    reg.histogram(STEP_LATENCY, "host wall-time per train step").observe(
        seconds, labels=labels)
    reg.counter(STEPS_TOTAL, "train steps dispatched").inc(1.0, labels=labels)
    if examples is not None:
        reg.counter(EXAMPLES_TOTAL, "examples consumed").inc(
            float(examples), labels=labels)
        if seconds > 0:
            reg.gauge(EXAMPLES_PER_SEC,
                      "instantaneous examples/s of the last step").set(
                examples / seconds, labels=labels)


def record_memory_stats():
    """Snapshot ``device.memory_stats()`` gauges where the backend reports
    them (PJRT on CPU returns nothing — the gauges simply stay absent)."""
    try:
        from ..device.tpu import memory_stats
        stats = memory_stats(0)
    except Exception:
        return
    if not stats:
        return
    g = registry().gauge(MEMORY_GAUGE, "PJRT allocator stats, device 0")
    for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                "largest_alloc_size"):
        if key in stats:
            g.set(float(stats[key]), labels={"stat": key})


def record_input_wait(seconds: float, fn: str = "prefetch"):
    """Time the train loop spent blocked waiting for the next device-ready
    batch (DevicePrefetcher found its buffer empty)."""
    registry().counter(
        HOST_INPUT_WAIT,
        "train-loop wall-time blocked on host input").inc(
        max(0.0, float(seconds)), labels={"fn": fn})


def set_prefetch_depth(depth: int, fn: str = "prefetch"):
    registry().gauge(
        PREFETCH_DEPTH,
        "DevicePrefetcher buffer occupancy (device-resident batches)").set(
        float(depth), labels={"fn": fn})


def record_prefetch_batch(fn: str = "prefetch"):
    registry().counter(
        PREFETCH_BATCHES,
        "batches delivered by DevicePrefetcher").inc(1.0, labels={"fn": fn})


def record_pipeline_stall(fn: str = "prefetch"):
    registry().counter(
        PIPELINE_STALLS,
        "warm-buffer underruns (device waited on host input)").inc(
        1.0, labels={"fn": fn})


def step_latency_count(fn: str = "train_step") -> int:
    h = registry().get(STEP_LATENCY)
    return h.count(labels={"fn": fn}) if h is not None else 0
