"""Tracing spans — nested host-side timeline events.

``span("compile", fn=...)`` is a context manager *and* decorator marking
one timed region.  Spans nest through a thread-local stack (each span
records its parent's id), carry monotonic timestamps on the same clock as
the profiler's host tracer, and land in three places:

* the **span ring** — a bounded deque of completed spans that
  ``profiler.export_chrome_tracing`` merges into its chrome-trace output
  (``"cat": "span"``) alongside RecordEvent host spans and the metrics
  registry's counter samples, so compile, collective, dataloader and
  train-step regions share one timeline;
* the **flight recorder** (flight.py) — span open/close are flight events,
  so the crash/hang dump shows which regions were in flight;
* the **open-span table** — per-thread stacks of live spans the watchdog
  snapshots when a step stalls ("the step is 40 s into collective X").

Spans are always on (the cost is two perf_counter reads, two flight
appends and one ring append per span) and are used only at non-per-op
sites — the ``@defop`` hub stays a single-boolean fast path.
"""
from __future__ import annotations

import functools
import itertools
import os
import threading
import time
from collections import deque

from . import flight

_lock = threading.Lock()
_ids = itertools.count(1)
_SPANS: deque = deque(
    maxlen=max(16, int(os.environ.get("PADDLE_TPU_SPAN_RING", "4096"))))
_local = threading.local()
# tid -> list of live span handles (the watchdog reads this from another
# thread, so it cannot live in _local)
_open_by_tid: dict[int, list] = {}


def _stack() -> list:
    st = getattr(_local, "stack", None)
    if st is None:
        st = _local.stack = []
    return st


class span:
    """One timed region: ``with span("checkpoint.save", dir=d) as sp: ...``
    or ``@span("collective.all_reduce")``.  Attrs may be added to
    ``sp.attrs`` while the span is open; they ship with the completed
    record.  As a decorator each call opens a fresh span."""

    __slots__ = ("name", "attrs", "id", "parent_id", "tid", "_t0", "_wall")

    def __init__(self, name: str, attrs: dict | None = None, **kw):
        self.name = name
        self.attrs = dict(attrs) if attrs else {}
        self.attrs.update(kw)
        self.id = None
        self.parent_id = None
        self.tid = None
        self._t0 = None
        self._wall = None

    def __enter__(self):
        st = _stack()
        self.id = next(_ids)
        self.parent_id = st[-1].id if st else None
        self.tid = threading.get_ident()
        st.append(self)
        with _lock:
            _open_by_tid[self.tid] = st
        self._wall = time.time()
        self._t0 = time.perf_counter()
        flight.record("span_begin", self.name, span_id=self.id,
                      parent_id=self.parent_id, **self.attrs)
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self._t0
        st = _stack()
        if st and st[-1] is self:
            st.pop()
        elif self in st:  # mis-nested close (generator teardown): best effort
            st.remove(self)
        rec = {"name": self.name, "id": self.id, "parent_id": self.parent_id,
               "tid": self.tid, "ts": self._t0 * 1e6, "dur": dur * 1e6,
               "wall_ts": self._wall, "attrs": dict(self.attrs)}
        if exc_type is not None:
            rec["attrs"]["status"] = "error"
            rec["attrs"]["exception"] = exc_type.__name__
        with _lock:
            _SPANS.append(rec)
        flight.record("span_end", self.name, span_id=self.id,
                      dur_ms=round(dur * 1e3, 3), **rec["attrs"])
        return False

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with span(self.name, self.attrs):
                return fn(*args, **kwargs)
        return wrapper

    @property
    def elapsed(self) -> float:
        return 0.0 if self._t0 is None else time.perf_counter() - self._t0


def current_span() -> span | None:
    st = _stack()
    return st[-1] if st else None


def spans(name: str | None = None) -> list[dict]:
    """Completed spans, oldest first (optionally filtered by name)."""
    with _lock:
        out = list(_SPANS)
    if name is None:
        return out
    return [s for s in out if s["name"] == name]


def open_spans() -> dict[int, list[dict]]:
    """{tid: [live span snapshots, outermost first]} across ALL threads —
    the watchdog's view of what a stalled process is doing right now."""
    with _lock:
        table = {tid: list(st) for tid, st in _open_by_tid.items()}
    out = {}
    for tid, st in table.items():
        if st:
            out[tid] = [{"name": s.name, "id": s.id,
                         "parent_id": s.parent_id,
                         "elapsed_s": round(s.elapsed, 6),
                         "attrs": dict(s.attrs)} for s in st]
    return out


def clear():
    """Drop completed spans (live stacks are untouched)."""
    with _lock:
        _SPANS.clear()


def chrome_events() -> list[dict]:
    """Completed spans as chrome-trace 'X' events (profiler merge).  The
    ts base is perf_counter*1e6 — the same clock RecordEvent spans and
    counter samples use, so everything aligns on one timeline."""
    pid = os.getpid()
    out = []
    for s in spans():
        args = dict(s["attrs"])
        args["span_id"] = s["id"]
        if s["parent_id"] is not None:
            args["parent_id"] = s["parent_id"]
        out.append({"name": s["name"], "ph": "X", "ts": s["ts"],
                    "dur": s["dur"], "pid": pid, "tid": s["tid"],
                    "cat": "span", "args": args})
    return out
