"""Retrace sentinel — catches shape-driven recompile storms.

``jax.jit`` silently retraces (and XLA recompiles) whenever a call arrives
with a new abstract signature.  On TPU that is the classic silent perf
killer: a stray python int in the batch path or a ragged final batch turns
every step into a multi-second compile while the throughput chart quietly
collapses.  The sentinel wraps the framework's jit entry points
(`distributed/spmd.py` train steps, `jit.to_static` caches), records every
distinct abstract signature and its compile wall-time, and logs ONE
structured warning per threshold crossing when the same entry point
recompiles more than N times.

The signature key is the tree of (shape, dtype) of the flattened call args
— exactly the part of jax's cache key a user can influence from the data
path.  Compile wall-time is measured around the first call with a new
signature, so it includes trace + lower + backend compile (the end-to-end
latency a training loop actually observes).
"""
from __future__ import annotations

import json
import logging
import os
import time

from . import flight, perfscope, registry
from . import trace as trace_mod

logger = logging.getLogger("paddle_tpu.observability")

JIT_COMPILE_TOTAL = "paddle_tpu_jit_compile_total"
JIT_COMPILE_SECONDS = "paddle_tpu_jit_compile_seconds"
JIT_RETRACE_WARNINGS = "paddle_tpu_jit_retrace_warnings_total"
DYNAMIC_CACHE_WARNINGS = "paddle_tpu_dynamic_cache_warnings_total"

# warn when one entry point compiles MORE than this many times
_DEFAULT_THRESHOLD = int(os.environ.get("PADDLE_TPU_RETRACE_WARN", "5"))
_threshold = [_DEFAULT_THRESHOLD]


def set_retrace_threshold(n: int):
    _threshold[0] = int(n)


def get_retrace_threshold() -> int:
    return _threshold[0]


def _abstract_signature(args, kwargs=None) -> tuple:
    import jax.tree_util as jtu
    leaves, treedef = jtu.tree_flatten((args, kwargs or {}))
    sig = []
    for lv in leaves:
        shape = getattr(lv, "shape", None)
        dtype = getattr(lv, "dtype", None)
        if shape is None and dtype is None:
            sig.append(repr(lv))  # static python leaf
        else:
            sig.append((tuple(shape) if shape is not None else None,
                        str(dtype)))
    return (str(treedef), tuple(sig))


def record_compile(name: str, key, seconds: float, n_compiles: int):
    """Book one (re)compile of jit entry point `name`; warn on storms."""
    reg = registry()
    reg.counter(JIT_COMPILE_TOTAL,
                "jit trace+compile events per entry point").inc(
        1.0, labels={"fn": name})
    reg.histogram(JIT_COMPILE_SECONDS,
                  "end-to-end compile wall-time (trace+lower+compile)"
                  ).observe(seconds, labels={"fn": name})
    if n_compiles > _threshold[0]:
        reg.counter(JIT_RETRACE_WARNINGS,
                    "retrace-storm warnings emitted").inc(
            1.0, labels={"fn": name})
        flight.record("retrace_storm", name, compiles=n_compiles,
                      threshold=_threshold[0])
        logger.warning(
            "paddle_tpu retrace sentinel: %s",
            json.dumps({"event": "retrace_storm", "fn": name,
                        "compiles": n_compiles,
                        "threshold": _threshold[0],
                        "last_signature": str(key)[:512],
                        "hint": "same step function keeps recompiling — "
                                "check for shape-polymorphic inputs "
                                "(ragged final batch, python scalars in "
                                "the data path)"}))


_STATIC_CACHE_HINT = (
    "a growing-concat KV cache changes the key length every decode step, "
    "so a jitted decode retraces per token; use the STATIC cache path — "
    "caches of (k_buf, v_buf, length) fixed-shape buffers, as built by "
    "paddle_tpu.serving.Engine or "
    "fleet.utils.HybridParallelInferenceHelper")
_dynamic_cache_warned: set = set()


def note_dynamic_cache_growth(site: str):
    """One-shot structured warning for the growing-concat KV-cache shape
    pattern: emitted the first time `site` is seen appending to a cache,
    into the flight recorder always and the metrics registry when telemetry
    is on.  The hint names the static-cache path to switch to."""
    if site in _dynamic_cache_warned:
        return
    _dynamic_cache_warned.add(site)
    flight.record("dynamic_kv_cache", site, hint=_STATIC_CACHE_HINT)
    logger.warning(
        "paddle_tpu retrace sentinel: %s",
        json.dumps({"event": "dynamic_kv_cache_growth", "site": site,
                    "hint": _STATIC_CACHE_HINT}))
    from ..core import op as op_mod
    if op_mod.TELEMETRY:
        registry().counter(
            DYNAMIC_CACHE_WARNINGS,
            "growing-concat KV-cache warnings emitted").inc(
            1.0, labels={"site": site})


def reset_dynamic_cache_warnings():
    """Re-arm the one-shot (tests)."""
    _dynamic_cache_warned.clear()


class InstrumentedJit:
    """Pass-through wrapper over a ``jax.jit``-ed callable that books
    compiles per distinct abstract signature.  Signature tracking is
    always on (one tree-flatten per *step* call — per-step, never per-op)
    so compile begin/end lands in the flight recorder even with telemetry
    off; the metrics registry is only touched when telemetry is on.
    Attribute access (``.lower``, ``.trace``...) delegates to the wrapped
    function so AOT paths keep working.

    Device perfscope (observability/perfscope.py) rides the same wrapper:
    each new signature registers its ``cost_analysis`` flops/bytes once
    at compile, and with ``PADDLE_TPU_PERFSCOPE_SAMPLE=N`` every Nth
    dispatch is bracketed with a ``block_until_ready`` to measure device
    seconds — the other ``N-1`` dispatches stay fully async, and the
    arguments are never touched, so the signature count (ONE compiled
    decode program per serving config) is unaffected."""

    def __init__(self, fn, name: str):
        self._fn = fn
        self._name = name
        self._signatures: set = set()

    def _invoke(self, args, kwargs):
        try:
            return self._fn(*args, **kwargs)
        except Exception as e:  # noqa: BLE001 — OOM forensics, then re-raise
            perfscope.note_exception(e, program=self._name)
            raise

    def _timed(self, key, args, kwargs):
        """One sampled dispatch: block until the result is device-ready
        and book the wall as device seconds for this program."""
        t0 = time.perf_counter()
        out = self._invoke(args, kwargs)
        # audited sync: runs on 1/N dispatches only (perfscope sampling);
        # the timer must observe device completion to measure anything
        perfscope.block_ready(out)  # tpu-lint: ok(trace-hygiene)
        perfscope.record_sample(self._name, key,
                                time.perf_counter() - t0)
        return out

    def __call__(self, *args, **kwargs):
        key = _abstract_signature(args, kwargs)
        sample = (perfscope.poll_sample(self._name)
                  if perfscope.sampling_active() else False)
        if key in self._signatures:
            if sample:
                return self._timed(key, args, kwargs)
            return self._invoke(args, kwargs)
        # new abstract signature → jax will trace + compile inside this
        # call; the span books compile begin/end (with the signature key)
        # into the flight record — a hang inside XLA leaves an open
        # "compile" span for the crash dump to show.  Compile dispatches
        # are never timed (the wall is trace+compile, not device time).
        n = len(self._signatures) + 1
        t0 = time.perf_counter()
        with trace_mod.span("compile", fn=self._name, n_compiles=n,
                            signature=str(key)[:256]):
            out = self._invoke(args, kwargs)
        dt = time.perf_counter() - t0
        self._signatures.add(key)
        from ..core import op as op_mod
        if op_mod.TELEMETRY:
            record_compile(self._name, key, dt, len(self._signatures))
        perfscope.register_program(self._name, key, self._fn, args, kwargs)
        return out

    def __getattr__(self, item):
        return getattr(self._fn, item)


def instrument_jit(fn, name: str) -> InstrumentedJit:
    return InstrumentedJit(fn, name)


def compile_count(name: str | None = None) -> float:
    """Total recorded compiles (optionally for one entry point)."""
    c = registry().get(JIT_COMPILE_TOTAL)
    if c is None:
        return 0.0
    if name is None:
        return c.total()
    return c.value(labels={"fn": name})


def retrace_warning_count() -> float:
    c = registry().get(JIT_RETRACE_WARNINGS)
    return c.total() if c is not None else 0.0
