"""Always-on flight recorder — a bounded ring of structured runtime events.

Round 5's canonical evidence was zeroed by one silent failure: bench burned
1,501 s inside ``jax.devices()`` with no artifact explaining why.  Metrics
(metrics.py) answer "how much / how often"; the flight recorder answers
"what happened, in what order" when the process dies or hangs — the
timeline layer large training fleets keep permanently armed because the
interesting crash never reproduces under a profiler.

Design constraints:

* **Always on.**  Unlike the metrics registry (gated by FLAGS_telemetry),
  the recorder runs from import: a fixed-size deque of plain dicts, one
  lock-guarded append per event.  That is affordable because events come
  only from *non-per-op* sites — span open/close (trace.py), jit compile
  begin/end, collective calls, dataloader waits, checkpoint phases, flag
  changes, NaN/Inf hits.  The ``@defop`` hub never touches it.
* **Bounded.**  ``PADDLE_TPU_FLIGHT_EVENTS`` (default 1024) caps the ring;
  old events fall off the front.  A crash dump therefore always costs the
  same and always shows the *most recent* history.
* **JSON-safe.**  Event attrs are scalars (str/int/float/bool) so the
  watchdog can serialize a dump bundle without touching the objects that
  may be mid-crash.

Disable entirely (paranoid benchmarking) with ``PADDLE_TPU_FLIGHT=0``.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque

DEFAULT_CAPACITY = 1024

_lock = threading.Lock()
_seq = itertools.count(1)
_EVENTS: deque = deque(
    maxlen=max(8, int(os.environ.get("PADDLE_TPU_FLIGHT_EVENTS",
                                     DEFAULT_CAPACITY))))
_ENABLED = os.environ.get("PADDLE_TPU_FLIGHT", "1").lower() not in (
    "0", "false", "no", "off")
# process-local monotonic epoch: event "mono" values are comparable with
# each other and with span timestamps (trace.py uses the same clock)
_T0 = time.perf_counter()


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool):
    global _ENABLED
    _ENABLED = bool(on)


def capacity() -> int:
    return _EVENTS.maxlen or 0


def set_capacity(n: int):
    """Resize the ring, keeping the newest events (tests; runtime sizing
    should use the PADDLE_TPU_FLIGHT_EVENTS env var)."""
    global _EVENTS
    with _lock:
        _EVENTS = deque(_EVENTS, maxlen=max(8, int(n)))


def record(kind: str, name: str, /, **attrs):
    """Append one structured event.  `attrs` values must be JSON-safe
    scalars — the recorder stores them as-is and the crash dump serializes
    them verbatim.  kind/name are positional-only so attrs may use those
    words too."""
    if not _ENABLED:
        return
    ev = {"seq": next(_seq), "ts": time.time(),
          "mono": time.perf_counter() - _T0,
          "tid": threading.get_ident(), "kind": kind, "name": name,
          "attrs": attrs}
    with _lock:
        _EVENTS.append(ev)


def events(kind: str | None = None) -> list[dict]:
    """Snapshot of the ring, oldest first (optionally one kind)."""
    with _lock:
        evs = list(_EVENTS)
    if kind is None:
        return evs
    return [e for e in evs if e["kind"] == kind]


def tail(n: int = 64) -> list[dict]:
    """The newest `n` events, oldest first — the crash-dump payload."""
    with _lock:
        evs = list(_EVENTS)
    return evs[-n:]


def clear():
    with _lock:
        _EVENTS.clear()
