"""Crash/hang diagnostics — dump the flight record when the process dies
or a train step stalls.

Three triggers, one bundle:

* **Uncaught exception** — ``install()`` chains a ``sys.excepthook`` that
  writes the bundle, then defers to the previous hook (traceback printing
  is untouched).
* **SIGTERM / SIGINT** — the fleet scheduler's kill and the operator's ^C
  both get a dump before the default disposition runs.  Handlers are only
  installed over the *default* ones; custom handlers are never stomped.
* **Step watchdog** — opt-in via ``PADDLE_TPU_STEP_TIMEOUT_S``: the SPMD
  train step arms a deadline before dispatch and disarms after.  A step
  that exceeds it gets the same bundle written from the watchdog thread —
  the hang becomes an artifact instead of a silent stall (round 5: 1,501 s
  inside ``jax.devices()`` with nothing to show for it).

The bundle (``paddle_tpu.crash_dump.v1``) carries the last flight-recorder
events, every thread's live span stack, and all-thread python stacks —
what happened, in what order, and where everyone is stuck.  Dumps land in
``PADDLE_TPU_DUMP_DIR`` (default: ``<tmpdir>/paddle_tpu_dumps``).
"""
from __future__ import annotations

import json
import logging
import os
import signal
import sys
import tempfile
import threading
import time
import traceback

from . import flight, trace

logger = logging.getLogger("paddle_tpu.observability")

SCHEMA = "paddle_tpu.crash_dump.v1"
# how many flight events ride in the bundle (the ring may hold more)
DUMP_TAIL = int(os.environ.get("PADDLE_TPU_DUMP_TAIL", "256"))

_install_lock = threading.Lock()
_prev_excepthook = None
_prev_signal: dict[int, object] = {}
_last_dump_path: str | None = None
# extra bundle sections: name -> zero-arg provider returning JSON-safe
# data (perfscope registers the HBM ledger here so an OOM names owners)
_sections: dict[str, object] = {}


def add_section(name: str, provider):
    """Attach a named section to every future crash bundle.  ``provider``
    is a zero-arg callable returning JSON-safe data; a provider that
    raises is skipped (a crash handler must never raise)."""
    _sections[str(name)] = provider


def dump_dir() -> str:
    return os.environ.get("PADDLE_TPU_DUMP_DIR") or os.path.join(
        tempfile.gettempdir(), "paddle_tpu_dumps")


def last_dump_path() -> str | None:
    return _last_dump_path


def thread_stacks() -> list[dict]:
    """Python stacks of every live thread (sys._current_frames)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, frame in sys._current_frames().items():
        out.append({"tid": tid, "name": names.get(tid, "?"),
                    "stack": traceback.format_stack(frame)})
    return out


def collect(reason: str, exc_info=None) -> dict:
    """The diagnostic bundle as a JSON-ready dict."""
    bundle = {
        "schema": SCHEMA,
        "reason": reason,
        "time": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "pid": os.getpid(),
        "argv": list(sys.argv),
        "flight_events": flight.tail(DUMP_TAIL),
        "open_spans": {str(tid): st
                       for tid, st in trace.open_spans().items()},
        "threads": thread_stacks(),
    }
    for name, provider in list(_sections.items()):
        try:
            bundle[name] = provider()
        except Exception:  # noqa: BLE001 — a broken section never blocks a dump
            pass
    if exc_info is not None and exc_info[0] is not None:
        etype, evalue, etb = exc_info
        bundle["exception"] = {
            "type": etype.__name__,
            "message": str(evalue),
            "traceback": traceback.format_exception(etype, evalue, etb),
        }
    return bundle


def dump(reason: str, exc_info=None, path: str | None = None) -> str | None:
    """Write the bundle; returns the path (None when the write itself
    fails — a crash handler must never raise)."""
    global _last_dump_path
    try:
        bundle = collect(reason, exc_info)
        if path is None:
            d = dump_dir()
            os.makedirs(d, exist_ok=True)
            safe = "".join(c if c.isalnum() or c in "._-" else "_"
                           for c in reason)[:64]
            path = os.path.join(
                d, f"crash_{os.getpid()}_{int(time.time() * 1e3)}_{safe}.json")
        with open(path, "w") as f:
            json.dump(bundle, f, default=repr)
        _last_dump_path = path
        logger.warning("paddle_tpu flight recorder: %s", json.dumps(
            {"event": "diagnostic_dump", "reason": reason, "path": path,
             "flight_events": len(bundle["flight_events"]),
             "threads": len(bundle["threads"])}))
        return path
    except Exception:  # pragma: no cover - last-resort guard
        try:
            traceback.print_exc(file=sys.stderr)
        except Exception:
            pass
        return None


# -- excepthook + signal installation ----------------------------------------

def _excepthook(etype, evalue, etb):
    dump("uncaught_exception", (etype, evalue, etb))
    if _prev_excepthook is not None:
        _prev_excepthook(etype, evalue, etb)
    else:  # pragma: no cover
        sys.__excepthook__(etype, evalue, etb)


def _make_signal_handler(signum):
    def handler(sig, frame):
        dump(f"signal_{signal.Signals(sig).name}")
        prev = _prev_signal.get(sig)
        if callable(prev):
            prev(sig, frame)
        elif prev == signal.SIG_DFL:
            # restore the default disposition and re-deliver so the exit
            # status still says "killed by signal"
            signal.signal(sig, signal.SIG_DFL)
            os.kill(os.getpid(), sig)
    return handler


def installed() -> bool:
    return sys.excepthook is _excepthook


def install():
    """Idempotent: chain the excepthook; take SIGTERM/SIGINT only where
    the current handler is the default (custom handlers win).  Signal
    setup silently no-ops off the main thread."""
    global _prev_excepthook
    with _install_lock:
        if sys.excepthook is not _excepthook:
            _prev_excepthook = sys.excepthook
            sys.excepthook = _excepthook
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                cur = signal.getsignal(sig)
                if cur == signal.SIG_DFL or cur is signal.default_int_handler:
                    _prev_signal[sig] = cur
                    signal.signal(sig, _make_signal_handler(sig))
            except (ValueError, OSError):  # not main thread / exotic platform
                pass


def uninstall():
    global _prev_excepthook
    with _install_lock:
        if sys.excepthook is _excepthook:
            sys.excepthook = _prev_excepthook or sys.__excepthook__
        _prev_excepthook = None
        for sig, prev in list(_prev_signal.items()):
            try:
                if prev is not None:
                    signal.signal(sig, prev)
            except (ValueError, OSError):
                pass
            _prev_signal.pop(sig, None)


def _bootstrap_from_env():
    if os.environ.get("PADDLE_TPU_CRASH_DUMP", "1").lower() not in (
            "0", "false", "no", "off"):
        install()


# -- step watchdog -----------------------------------------------------------

def step_timeout() -> float | None:
    """PADDLE_TPU_STEP_TIMEOUT_S, read per arm so tests/operators can flip
    it at runtime; None/<=0 disables."""
    raw = os.environ.get("PADDLE_TPU_STEP_TIMEOUT_S", "")
    try:
        t = float(raw)
    except ValueError:
        return None
    return t if t > 0 else None


class _StepWatchdog:
    """One daemon thread, lazily started on first arm.  arm() sets a
    deadline; disarm() clears it.  A deadline that expires while still
    armed fires ONE dump (reason step_timeout:<name>) and waits for the
    next arm — it diagnoses the hang, it does not kill the process."""

    def __init__(self):
        self._cv = threading.Condition()
        self._thread: threading.Thread | None = None
        self._deadline: float | None = None
        self._name = ""
        self._timeout = 0.0
        self.fired_count = 0

    def arm(self, name: str, timeout: float):
        with self._cv:
            self._name = name
            self._timeout = timeout
            self._deadline = time.perf_counter() + timeout
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, daemon=True,
                    name="paddle-tpu-step-watchdog")
                self._thread.start()
            self._cv.notify()

    def disarm(self):
        with self._cv:
            self._deadline = None
            self._cv.notify()

    def _run(self):
        while True:
            with self._cv:
                if self._deadline is None:
                    self._cv.wait()
                    continue
                now = time.perf_counter()
                if now < self._deadline:
                    self._cv.wait(self._deadline - now)
                    continue
                name, timeout = self._name, self._timeout
                self._deadline = None  # fire once per arm
                self.fired_count += 1
            flight.record("watchdog", "step_timeout", fn=name,
                          timeout_s=timeout)
            dump(f"step_timeout:{name}")


_watchdog = _StepWatchdog()


def arm(name: str, timeout: float | None = None) -> bool:
    """Arm the step watchdog; returns True when armed (a timeout was given
    or PADDLE_TPU_STEP_TIMEOUT_S is set).  Callers pair this with
    disarm() in a finally block."""
    t = timeout if timeout is not None else step_timeout()
    if t is None:
        return False
    _watchdog.arm(name, t)
    return True


def disarm():
    _watchdog.disarm()
