"""SLO engine — declarative objectives, burn-rate alerts, incidents.

The stack *attributes* everything (journeys partition host wall time,
perfscope partitions device time and HBM bytes) but until this module
nothing *judged* any of it.  Three layers close that gap:

* :class:`SloObjective` — a declarative statement of "good": a signal
  (``ttft_p99`` / ``queue_wait_p99`` / ``token_p99`` / ``shed_rate`` /
  ``availability``), a target fraction of good events, an optional
  latency threshold, and a selector (one tenant, one priority class, or
  ``per="tenant"|"class"`` to expand over every key the window has
  seen).
* :class:`SloEvaluator` — a PURE feed→decision object (the
  ``ScalePolicy`` shape): each :meth:`SloEvaluator.tick` reads raw
  events from a keyed :class:`~paddle_tpu.observability.journey.
  TelemetryWindow` and steps a multi-window burn-rate state machine,
  Google-SRE style — the **fast** window catches flash crowds in
  seconds, the **slow** window catches slow leaks without flapping.
  Burn rate is ``error_rate / (1 - target)``: burn 1.0 spends the error
  budget exactly at the sustainable rate; the fast rule fires at a high
  multiple, the slow rule at a low one.  Alerts hold down through a
  pending → firing → resolved lifecycle (breach/clear tick streaks,
  exactly the autoscaler's up_ticks/idle_ticks hysteresis), so unit
  tests and ``FleetSim`` drive the whole machine in virtual time.
* :class:`SloEngine` — the live wrapper: a daemon thread polls the
  gateway window at ``tick_s``, exports attainment / budget / burn
  gauges, records ``"alert"`` flight events, and on each transition to
  firing writes a bounded on-disk **incident bundle**
  (:func:`build_incident` via :class:`IncidentStore`) correlating all
  three telemetry planes — keyed window snapshots, the slowest journey
  timelines in-window, the perfscope roofline + HBM ownership ledger,
  ``fleet_stats()`` and the flight tail — one JSON per incident,
  ring-bounded, served by ``GET /debug/incidents[/<id>]`` and rendered
  by ``tools/incident_report.py``.

The firing set feeds back into the autoscaler as the optional
``firing_alerts`` policy-input field (ROADMAP item 5b's seam).
"""
from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from collections import deque

from . import flight, journey as journey_mod, registry, watchdog

__all__ = [
    "SloObjective", "SloEvaluator", "SloEngine", "IncidentStore",
    "build_incident", "SIGNALS", "INCIDENT_SCHEMA",
]

SIGNALS = ("ttft_p99", "queue_wait_p99", "token_p99", "shed_rate",
           "availability")
# latency-style signals judge each sample against threshold_s; the
# other two judge shed/outcome events directly
_LATENCY_FIELD = {"ttft_p99": "ttft_s", "queue_wait_p99": "queue_wait_s",
                  "token_p99": "token_s"}

INCIDENT_SCHEMA = "paddle_tpu.incident.v1"

SLO_ATTAINMENT = "paddle_tpu_slo_attainment"
SLO_BUDGET_REMAINING = "paddle_tpu_slo_error_budget_remaining"
SLO_BURN_RATE = "paddle_tpu_slo_burn_rate"
SLO_ALERTS = "paddle_tpu_slo_alerts_total"


class SloObjective:
    """One declarative objective: ``target`` fraction of events must be
    good over the slow window, where "good" is signal-specific (latency
    under ``threshold_s``, not shed, or outcome ok)."""

    def __init__(self, name: str, signal: str, target: float, *,
                 threshold_s: float | None = None,
                 tenant: str | None = None, priority: str | None = None,
                 per: str | None = None,
                 fast_window_s: float = 10.0, fast_burn: float = 10.0,
                 slow_window_s: float = 60.0, slow_burn: float = 2.0,
                 fire_ticks: int = 2, resolve_ticks: int = 3,
                 min_events: int = 4):
        if not name:
            raise ValueError("objective needs a name")
        if signal not in SIGNALS:
            raise ValueError(f"signal must be one of {SIGNALS}, "
                             f"got {signal!r}")
        if not 0.0 < target < 1.0:
            raise ValueError("target must be in (0, 1) — an SLO of 1.0 "
                             "has zero error budget and can never burn "
                             "at a finite rate")
        if signal in _LATENCY_FIELD:
            if threshold_s is None or threshold_s <= 0:
                raise ValueError(f"{signal} needs threshold_s > 0")
        if per not in (None, "tenant", "class"):
            raise ValueError('per must be None, "tenant" or "class"')
        if per is not None and (tenant is not None or priority is not None):
            raise ValueError("per= expands over every key; it is "
                             "mutually exclusive with a fixed tenant/"
                             "priority selector")
        if not 0 < fast_window_s < slow_window_s:
            raise ValueError("need 0 < fast_window_s < slow_window_s")
        if fast_burn <= 0 or slow_burn <= 0:
            raise ValueError("burn thresholds must be > 0")
        self.name = str(name)
        self.signal = str(signal)
        self.target = float(target)
        self.threshold_s = None if threshold_s is None else float(threshold_s)
        self.tenant = None if tenant is None else str(tenant)
        self.priority = None if priority is None else str(priority)
        self.per = per
        self.fast_window_s = float(fast_window_s)
        self.fast_burn = float(fast_burn)
        self.slow_window_s = float(slow_window_s)
        self.slow_burn = float(slow_burn)
        self.fire_ticks = max(1, int(fire_ticks))
        self.resolve_ticks = max(1, int(resolve_ticks))
        self.min_events = max(1, int(min_events))

    def snapshot(self) -> dict:
        return {
            "name": self.name, "signal": self.signal,
            "target": self.target, "threshold_s": self.threshold_s,
            "tenant": self.tenant, "priority": self.priority,
            "per": self.per,
            "fast_window_s": self.fast_window_s,
            "fast_burn": self.fast_burn,
            "slow_window_s": self.slow_window_s,
            "slow_burn": self.slow_burn,
            "fire_ticks": self.fire_ticks,
            "resolve_ticks": self.resolve_ticks,
            "min_events": self.min_events,
        }

    def counts(self, samples: list, sheds: list) -> tuple[int, int]:
        """``(good, bad)`` event counts for this objective's signal."""
        if self.signal == "shed_rate":
            return len(samples), len(sheds)
        if self.signal == "availability":
            good = sum(1 for s in samples if s.get("outcome") == "ok")
            return good, (len(samples) - good) + len(sheds)
        field = _LATENCY_FIELD[self.signal]
        vals = [s[field] for s in samples if s.get(field) is not None]
        bad = sum(1 for v in vals if v > self.threshold_s)
        return len(vals) - bad, bad


class _AlertState:
    """Per-(objective, key) hysteresis state.  Pure data — mutated only
    by the evaluator under its lock."""

    __slots__ = ("state", "breach_streak", "clear_streak", "rule", "since",
                 "burn_fast", "burn_slow", "attainment", "events")

    def __init__(self):
        self.state = "inactive"     # inactive | pending | firing
        self.breach_streak = 0
        self.clear_streak = 0
        self.rule = ""              # "fast" | "slow" once breaching
        self.since = None           # t of the pending/firing transition
        self.burn_fast = 0.0
        self.burn_slow = 0.0
        self.attainment = 1.0
        self.events = 0


class SloEvaluator:
    """Pure feed→decision burn-rate engine over a keyed TelemetryWindow.

    Call :meth:`tick` at a fixed cadence with an explicit ``now`` (or
    wall clock when live); it returns the alert *transitions* that
    happened this tick — ``pending`` / ``firing`` / ``resolved`` dicts
    — while :meth:`firing` and :meth:`state` expose the standing state.
    No threads, no I/O: FleetSim and unit tests drive it in virtual
    time, and :class:`SloEngine` wraps it for the live gateway.
    """

    def __init__(self, objectives):
        objectives = list(objectives)
        if not objectives:
            raise ValueError("need at least one SloObjective")
        names = [o.name for o in objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate objective names: {names}")
        self.objectives = objectives
        self._lock = threading.Lock()
        self._alerts: dict[tuple[str, str], _AlertState] = {}

    # -- key expansion -------------------------------------------------------
    def _keys_for(self, obj: SloObjective, window, now: float) -> list:
        """(display_key, tenant_filter, priority_filter) triples this
        objective evaluates this tick.  ``per=`` objectives expand over
        the window's live keys UNION already-tracked alert keys, so an
        alert on a tenant that stopped sending traffic still ages out
        through resolve rather than sticking in firing forever."""
        if obj.per is None:
            key = obj.tenant if obj.tenant is not None else obj.priority
            return [(key if key is not None else "all",
                     obj.tenant, obj.priority)]
        idx = 0 if obj.per == "tenant" else 1
        seen = {k[idx] for k in window.keys(now=now)}
        with self._lock:
            seen |= {key for (name, key) in self._alerts
                     if name == obj.name}
        if obj.per == "tenant":
            return [(k, k, None) for k in sorted(seen)]
        return [(k, None, k) for k in sorted(seen)]

    @staticmethod
    def _burn(obj: SloObjective, window, now: float, horizon_s: float,
              tenant, priority) -> tuple[float, float, int]:
        """(error_rate, burn, total_events) over the trailing horizon."""
        samples, sheds = window.events(
            now=now, horizon_s=horizon_s, tenant=tenant, priority=priority)
        good, bad = obj.counts(samples, sheds)
        total = good + bad
        error_rate = (bad / total) if total else 0.0
        return error_rate, error_rate / (1.0 - obj.target), total

    # -- the state machine ---------------------------------------------------
    def tick(self, window, now: float | None = None) -> list:
        """Evaluate every objective against the window; returns the
        transitions that happened this tick."""
        now = time.perf_counter() if now is None else float(now)
        transitions = []
        for obj in self.objectives:
            for key, tenant, priority in self._keys_for(obj, window, now):
                tr = self._tick_one(obj, key, tenant, priority, window, now)
                if tr is not None:
                    transitions.append(tr)
        return transitions

    def _tick_one(self, obj, key, tenant, priority, window, now):
        err_fast, burn_fast, n_fast = self._burn(
            obj, window, now, obj.fast_window_s, tenant, priority)
        err_slow, burn_slow, n_slow = self._burn(
            obj, window, now, obj.slow_window_s, tenant, priority)
        # a rule only counts when its window holds enough events to
        # mean something — min_events gates flapping on thin traffic
        rule = ""
        if n_fast >= obj.min_events and burn_fast >= obj.fast_burn:
            rule = "fast"
        elif n_slow >= obj.min_events and burn_slow >= obj.slow_burn:
            rule = "slow"
        with self._lock:
            st = self._alerts.get((obj.name, key))
            if st is None:
                if not rule and n_slow == 0:
                    return None      # nothing to track yet
                st = self._alerts[(obj.name, key)] = _AlertState()
            st.burn_fast, st.burn_slow = burn_fast, burn_slow
            st.attainment = 1.0 - err_slow
            st.events = n_slow
            prev = st.state
            if rule:
                st.breach_streak += 1
                st.clear_streak = 0
                st.rule = rule
                if st.state == "inactive":
                    st.state, st.since = "pending", now
                elif (st.state == "pending"
                      and st.breach_streak >= obj.fire_ticks):
                    st.state, st.since = "firing", now
            else:
                st.clear_streak += 1
                st.breach_streak = 0
                if st.state == "pending":
                    st.state, st.since, st.rule = "inactive", None, ""
                elif (st.state == "firing"
                      and st.clear_streak >= obj.resolve_ticks):
                    st.state, st.since = "inactive", None
            if st.state == prev:
                return None
            to = "resolved" if (prev == "firing"
                                and st.state == "inactive") else st.state
            return {"t": now, "objective": obj.name, "key": key,
                    "from": prev, "to": to, "rule": st.rule or rule,
                    "burn_fast": round(burn_fast, 4),
                    "burn_slow": round(burn_slow, 4),
                    "attainment": round(st.attainment, 6)}

    # -- reading -------------------------------------------------------------
    def firing(self) -> list:
        """The standing firing set — the autoscaler's ``firing_alerts``
        policy-input field (ROADMAP item 5b seam)."""
        with self._lock:
            return [{"objective": name, "key": key, "rule": st.rule,
                     "since": st.since}
                    for (name, key), st in sorted(self._alerts.items())
                    if st.state == "firing"]

    def state(self) -> list:
        """Last-evaluated metrics for every tracked (objective, key)."""
        with self._lock:
            return [{"objective": name, "key": key, "state": st.state,
                     "rule": st.rule, "since": st.since,
                     "burn_fast": round(st.burn_fast, 4),
                     "burn_slow": round(st.burn_slow, 4),
                     "attainment": round(st.attainment, 6),
                     "budget_remaining": round(
                         max(0.0, 1.0 - st.burn_slow), 4),
                     "events": st.events}
                    for (name, key), st in sorted(self._alerts.items())]


class IncidentStore:
    """Ring-bounded on-disk incident bundles — one JSON file each,
    written atomically (tmp + rename) so a reader racing a mid-kill
    writer always sees either nothing or complete JSON."""

    def __init__(self, dir: str | None = None, max_incidents: int = 32):
        self._dir = dir or os.environ.get("PADDLE_TPU_INCIDENT_DIR") or \
            os.path.join(tempfile.gettempdir(), "paddle_tpu_incidents")
        self.max_incidents = max(1, int(max_incidents))
        self._lock = threading.Lock()
        self._seq = 0
        self._meta: deque = deque(maxlen=self.max_incidents)

    @property
    def dir(self) -> str:
        return self._dir

    def write(self, bundle: dict) -> str:
        """Assigns an id, writes the bundle, prunes beyond the ring
        bound.  Returns the incident id."""
        with self._lock:
            self._seq += 1
            objective = str(bundle.get("incident", {})
                            .get("objective", "slo"))
            safe = "".join(c if c.isalnum() or c in "-_" else "-"
                           for c in objective)[:48]
            inc_id = f"inc-{int(time.time() * 1e3)}-{self._seq:04d}-{safe}"
            bundle.setdefault("incident", {})["id"] = inc_id
            os.makedirs(self._dir, exist_ok=True)
            path = os.path.join(self._dir, f"{inc_id}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(bundle, f, indent=2, default=str)
            os.replace(tmp, path)
            if len(self._meta) == self._meta.maxlen:
                old = self._meta[0]
                try:
                    os.remove(os.path.join(self._dir, f"{old['id']}.json"))
                except OSError:
                    pass
            self._meta.append({
                "id": inc_id,
                "objective": objective,
                "key": bundle.get("incident", {}).get("key"),
                "rule": bundle.get("incident", {}).get("rule"),
                "t": bundle.get("incident", {}).get("t"),
                "time": bundle.get("time"),
                "path": path,
            })
            return inc_id

    def list(self) -> list:
        with self._lock:
            return [dict(m) for m in self._meta]

    def get(self, inc_id: str) -> dict | None:
        with self._lock:
            match = next((m for m in self._meta if m["id"] == inc_id), None)
        if match is None:
            return None
        try:
            with open(match["path"]) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None


def build_incident(transition: dict, *, gateway=None, window=None,
                   n_journeys: int = 5) -> dict:
    """One incident bundle correlating all three telemetry planes at
    the moment an alert fired: the watchdog base (flight tail, open
    spans, thread stacks, registered sections — perfscope's HBM
    ownership ledger and the traffic recorder's ``capture_tail`` (the
    last arrivals before the burn, admitted and shed, each resolvable
    against ``/debug/requests`` by journey id) ride in via their
    ``add_section`` providers), keyed
    window snapshots, the N slowest journey timelines in-window, the
    perfscope roofline + memory report, and ``fleet_stats()``.  Every
    plane is individually guarded: a failing provider drops its section
    rather than the incident."""
    bundle = watchdog.collect(
        f"slo_alert:{transition.get('objective', '?')}")
    bundle["schema"] = INCIDENT_SCHEMA
    bundle["incident"] = {
        "objective": transition.get("objective"),
        "key": transition.get("key"),
        "rule": transition.get("rule"),
        "burn_fast": transition.get("burn_fast"),
        "burn_slow": transition.get("burn_slow"),
        "attainment": transition.get("attainment"),
        "t": transition.get("t"),
        "time": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
    }
    if window is not None:
        try:
            bundle["window"] = {
                "global": window.snapshot(),
                "by_tenant": window.snapshot(by="tenant"),
                "by_class": window.snapshot(by="class"),
            }
        except Exception:  # noqa: BLE001 — plane is optional
            pass
    try:
        recent = journey_mod.recent(256)
        recent.sort(key=lambda j: j.wall_s or 0.0, reverse=True)
        bundle["slowest_journeys"] = [
            j.timeline() for j in recent[:max(0, int(n_journeys))]]
    except Exception:  # noqa: BLE001
        pass
    try:
        from . import perfscope
        bundle["perf"] = perfscope.perf_report()
        bundle["memory"] = perfscope.memory_report()
    except Exception:  # noqa: BLE001
        pass
    if gateway is not None:
        try:
            bundle["fleet"] = gateway.fleet_stats()
        except Exception:  # noqa: BLE001
            pass
    return bundle


class SloEngine:
    """The live evaluator: attaches to a gateway, polls its keyed
    window at ``tick_s`` on a daemon thread, exports gauges, records
    ``"alert"`` flight events, and snapshots an incident bundle on each
    transition to firing.  ``tick()`` is also callable directly (tests,
    smoke lanes) — the thread is just a clock."""

    def __init__(self, gateway, objectives, *, tick_s: float = 1.0,
                 evaluator: SloEvaluator | None = None,
                 store: IncidentStore | None = None,
                 incident_dir: str | None = None, max_incidents: int = 32,
                 incident_journeys: int = 5, start: bool = True):
        # accept a GatewayStack or a bare Gateway
        self.gateway = getattr(gateway, "gateway", gateway)
        self.evaluator = evaluator or SloEvaluator(objectives)
        self.store = store or IncidentStore(incident_dir, max_incidents)
        self.tick_s = max(0.05, float(tick_s))
        self.incident_journeys = int(incident_journeys)
        self._lock = threading.Lock()
        self._transitions: deque = deque(maxlen=256)
        self._ticks = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        attach = getattr(self.gateway, "attach_slo_engine", None)
        if attach is not None:
            attach(self)
        if start:
            self._thread = threading.Thread(
                target=self._run, name="slo-engine", daemon=True)
            self._thread.start()

    # -- the clock -----------------------------------------------------------
    def _run(self):
        while not self._stop.wait(self.tick_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the clock must survive
                pass

    def tick(self, now: float | None = None) -> list:
        """One evaluation: refresh the gateway's windowed gauges, step
        the burn-rate machine, export SLO gauges, handle transitions."""
        try:
            self.gateway.window_stats()
        except Exception:  # noqa: BLE001 — gauges are best-effort
            pass
        window = self.gateway.window
        transitions = self.evaluator.tick(window, now=now)
        # publish the tail BEFORE the export/incident work below: a
        # debug reader that already sees the new alert state must also
        # see its transition (copies — incident ids attach to the
        # originals later, and the tail must not mutate under a
        # concurrent JSON dump)
        with self._lock:
            self._ticks += 1
            self._transitions.extend(dict(tr) for tr in transitions)
        reg = registry()
        att = reg.gauge(SLO_ATTAINMENT,
                        "windowed fraction of good events per objective")
        budget = reg.gauge(SLO_BUDGET_REMAINING,
                           "1 - slow-window burn rate, clamped at 0")
        burn = reg.gauge(SLO_BURN_RATE,
                         "error budget burn multiple per window")
        for row in self.evaluator.state():
            labels = {"objective": row["objective"], "key": row["key"]}
            att.set(row["attainment"], labels=labels)
            budget.set(row["budget_remaining"], labels=labels)
            burn.set(row["burn_fast"], labels=dict(labels, window="fast"))
            burn.set(row["burn_slow"], labels=dict(labels, window="slow"))
        alerts = reg.counter(SLO_ALERTS, "alert lifecycle transitions")
        for tr in transitions:
            alerts.inc(labels={"objective": tr["objective"],
                               "state": tr["to"]})
            flight.record("alert", tr["to"], objective=tr["objective"],
                          key=tr["key"], rule=tr["rule"],
                          burn_fast=tr["burn_fast"],
                          burn_slow=tr["burn_slow"],
                          attainment=tr["attainment"])
            if tr["to"] == "firing":
                try:
                    bundle = build_incident(
                        tr, gateway=self.gateway, window=window,
                        n_journeys=self.incident_journeys)
                    tr["incident_id"] = self.store.write(bundle)
                except Exception:  # noqa: BLE001 — never kill the tick
                    pass
        return transitions

    # -- reading / lifecycle -------------------------------------------------
    def firing(self) -> list:
        return self.evaluator.firing()

    def debug_state(self) -> dict:
        """The ``GET /debug/slo`` payload."""
        with self._lock:
            ticks = self._ticks
            tail = list(self._transitions)[-32:]
        return {
            "tick_s": self.tick_s,
            "ticks": ticks,
            "objectives": [o.snapshot() for o in self.evaluator.objectives],
            "alerts": self.evaluator.state(),
            "firing": self.evaluator.firing(),
            "transitions": tail,
            "incidents": self.store.list(),
        }

    def shutdown(self, timeout_s: float = 5.0):
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=timeout_s)
