"""Traffic capture — an always-on, bounded request recorder at gateway
admission, with deterministic replay and trace fitting built on top.

Journeys (PR 13) explain where one request's time went, perfscope
(PR 14) where the device's cycles went, the SLO engine (PR 16) when an
objective burned — but none of them answer "what traffic did this to
us, and can we run it again?".  This module closes that loop:

* **recorder** — :class:`TrafficCapture` keeps one entry per request
  the gateway saw (admitted OR shed) in a ring bounded by
  ``PADDLE_TPU_CAPTURE_ENTRIES``, optionally spilling rotating JSONL
  files under ``PADDLE_TPU_CAPTURE_SPILL_DIR``.  The ring and the spill
  file live under ONE lock; the spill writer is a separate thread fed a
  bounded pending list, so admission never blocks on disk — overflow
  increments ``paddle_tpu_capture_dropped_total`` instead.
* **content modes** — ``shape`` (default) stores lengths plus a prompt
  hash and provably no token ids, so production capture never retains
  user content; ``full`` stores the exact prompt token ids for bitwise
  replay (``PADDLE_TPU_CAPTURE_MODE`` or the ``capture_mode`` knob on
  ``start_gateway``).
* **deterministic replay** — every entry carries the request's sampling
  triple (temperature/top_k/seed), tenant/priority/model and arrival
  offset, so ``tools/replay_capture.py`` can re-drive a captured window
  through ``load_gen.replay_http``: greedy requests reproduce
  token-identical output, sampled ones are seed-exact (the engine's
  counter-based PRNG keys on (seed, position), not batch shape).
* **trace fitting** — :func:`fit_trace` estimates the windowed arrival
  rate curve (piecewise-constant; a flash crowd survives as a rate
  step, where a sinusoid fit would average it away) plus lognormal
  prompt/output length parameters and emits a ``make_trace``-compatible
  synthetic trace that plugs straight into
  :class:`~paddle_tpu.serving.FleetSim` — autoscale policy tuning on
  measured traffic (ROADMAP item 5a), not a guessed sinusoid.
* **incident linkage** — the process-default capture registers a
  ``capture_tail`` section through ``watchdog.add_section``, so every
  SLO incident bundle carries the last arrivals before the burn, each
  resolvable against ``/debug/requests`` by ``journey_id``.

Entry schema (one JSON-safe dict per request)::

    {t, tenant, priority, model, prompt_len, prompt_hash, max_tokens,
     deadline_s, temperature, top_k, seed, outcome, journey_id,
     conversation                     # raw id full mode, hash in shape
     [, prompt]}                      # token ids, full mode only

``t`` is seconds since the capture epoch (monotonic clock), so a window
replays with its inter-arrival times intact; ``outcome`` is
``admitted`` or the shed reason (``slo_shed``, ``draining``,
``tenant_queue_full``, ...).  Everything recorded is a host-side scalar
already on the admission path — no device reads, no host syncs, decode
stays ONE compiled program (asserted in tests/test_capture.py).
"""
from __future__ import annotations

import hashlib
import json
import math
import os
import threading
import time
from collections import deque

import numpy as np

from . import flight, registry, watchdog

__all__ = ["TrafficCapture", "get_capture", "set_capture",
           "install_incident_section", "fit_params", "fit_trace",
           "CAPTURE_ENTRIES", "CAPTURE_DROPPED"]

# -- metric names (paddle_tpu.observability registry) -------------------------
CAPTURE_ENTRIES = "paddle_tpu_capture_entries_total"
CAPTURE_DROPPED = "paddle_tpu_capture_dropped_total"

MODES = ("shape", "full")
# how many tail arrivals ride in an incident bundle's capture_tail
_TAIL_N = max(4, int(os.environ.get("PADDLE_TPU_CAPTURE_TAIL", "32")))
# pending spill lines the writer may fall behind by before drops start
_PENDING_MAX = 4096


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, ""))
    except ValueError:
        return default


def _prompt_hash(ids, text) -> str:
    """Stable 64-bit content fingerprint: same prompt -> same hash, and
    (shape mode) nothing recoverable from it."""
    h = hashlib.blake2b(digest_size=8)
    if ids is not None:
        h.update(np.asarray(ids, np.int64).tobytes())
    elif text:
        h.update(str(text).encode("utf-8", "replace"))
    return h.hexdigest()


class TrafficCapture:
    """Bounded ring of admission-time request entries + optional
    rotating JSONL spill.

    Args:
        max_entries: ring bound (default ``PADDLE_TPU_CAPTURE_ENTRIES``,
            2048).  The ring NEVER exceeds it; spill-less evictions and
            a lagging spill writer count into ``capture_dropped_total``
            instead of blocking the recorder.
        mode: ``shape`` (default; lengths + hash, no token ids) or
            ``full`` (exact prompt ids for bitwise replay) — env default
            ``PADDLE_TPU_CAPTURE_MODE``.
        spill_dir: directory for the rotating JSONL spill (env default
            ``PADDLE_TPU_CAPTURE_SPILL_DIR``; None/"" disables).  Every
            recorded entry is appended to ``capture.jsonl`` by the
            writer thread; at ``spill_max_bytes`` the file rotates to
            ``capture.jsonl.1`` .. ``.{spill_files}``.
    """

    def __init__(self, max_entries: int | None = None,
                 mode: str | None = None, spill_dir: str | None = None,
                 spill_max_bytes: int | None = None, spill_files: int = 2):
        if max_entries is None:
            max_entries = _env_int("PADDLE_TPU_CAPTURE_ENTRIES", 2048)
        mode = (mode or os.environ.get("PADDLE_TPU_CAPTURE_MODE")
                or "shape").lower()
        if mode not in MODES:
            raise ValueError(f"capture mode must be one of {MODES}, "
                             f"got {mode!r}")
        if spill_dir is None:
            spill_dir = os.environ.get("PADDLE_TPU_CAPTURE_SPILL_DIR") or None
        if spill_max_bytes is None:
            spill_max_bytes = _env_int(
                "PADDLE_TPU_CAPTURE_SPILL_BYTES", 4 << 20)
        self.max_entries = max(1, int(max_entries))
        self.mode = mode
        self.spill_dir = spill_dir
        self.spill_max_bytes = max(1, int(spill_max_bytes))
        self.spill_files = max(1, int(spill_files))
        # ONE lock over the ring, the counters, the pending spill list
        # AND the spill file state (handle, size, rotation count): the
        # recorder and the writer thread share nothing outside it
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._ring: deque = deque()
        self._pending: list[str] = []
        self._recorded = 0
        self._dropped = 0
        self._spilled = 0
        self._rotations = 0
        self._epoch = time.perf_counter()
        self._file = None
        self._file_bytes = 0
        self._stop = False
        self._writer: threading.Thread | None = None

    # -- recording (gateway admission path) ----------------------------------
    def record(self, *, tenant: str, priority: str, outcome: str,
               prompt=None, text=None, prompt_len: int | None = None,
               max_tokens: int = 0, deadline_s: float | None = None,
               temperature: float = 0.0, top_k: int = 0, seed: int = 0,
               model: str | None = None, journey_id: str = "",
               conversation: str | None = None,
               t: float | None = None) -> dict:
        """Append one entry; never blocks on disk, never raises into
        admission.  ``prompt`` is the token-id sequence when the caller
        has one (stored only in ``full`` mode); ``t`` overrides the
        arrival offset for virtual-time feeds (bench/sim).
        ``conversation`` gets the prompt's privacy treatment: the raw id
        is stored only in ``full`` mode, ``shape`` mode keeps its hash —
        warm-turn grouping stays analyzable, the identifier does not
        leak."""
        ids = None if prompt is None else [int(x) for x in prompt]
        conv = None
        if conversation is not None:
            conv = (str(conversation) if self.mode == "full" else
                    hashlib.blake2b(str(conversation).encode("utf-8"),
                                    digest_size=8).hexdigest())
        entry = {
            "t": round(time.perf_counter() - self._epoch
                       if t is None else float(t), 4),
            "tenant": str(tenant),
            "priority": str(priority),
            "model": model,
            "prompt_len": int(len(ids) if ids is not None
                              else (prompt_len or 0)),
            "prompt_hash": _prompt_hash(ids, text),
            "max_tokens": int(max_tokens),
            "deadline_s": None if deadline_s is None else float(deadline_s),
            "temperature": float(temperature),
            "top_k": int(top_k),
            "seed": int(seed),
            "outcome": str(outcome),
            "journey_id": str(journey_id),
            "conversation": conv,
        }
        if self.mode == "full" and ids is not None:
            entry["prompt"] = ids
        line = (json.dumps(entry) + "\n") if self.spill_dir else None
        dropped = 0
        with self._cv:
            self._ring.append(entry)
            self._recorded += 1
            while len(self._ring) > self.max_entries:
                self._ring.popleft()
                if not self.spill_dir:
                    dropped += 1        # no spill: the entry is gone
            if line is not None:
                if len(self._pending) >= _PENDING_MAX:
                    dropped += 1        # writer lagging: shed the line
                else:
                    self._pending.append(line)
                    if self._writer is None or not self._writer.is_alive():
                        self._writer = threading.Thread(
                            target=self._spill_loop, daemon=True,
                            name="paddle-tpu-capture-spill")
                        self._writer.start()
                    self._cv.notify()
            self._dropped += dropped
        reg = registry()
        reg.counter(CAPTURE_ENTRIES, "captured gateway arrivals").inc(
            1.0, labels={"outcome": outcome})
        if dropped:
            reg.counter(CAPTURE_DROPPED,
                        "capture entries lost to ring/spill overflow").inc(
                float(dropped))
        return entry

    # -- spill writer thread -------------------------------------------------
    def _spill_loop(self):
        while True:
            with self._cv:
                while not self._pending and not self._stop:
                    self._cv.wait()
                batch, self._pending = self._pending, []
                stop = self._stop
                if batch:
                    try:
                        self._write_batch_locked(batch)
                    except OSError:
                        # a dead disk never kills capture: the ring
                        # stays authoritative, the lines are dropped
                        self._dropped += len(batch)
                self._cv.notify_all()   # wake flush() waiters
                if stop:
                    if self._file is not None:
                        try:
                            self._file.close()
                        except OSError:
                            pass
                        self._file = None
                    return

    def _write_batch_locked(self, lines: list[str]):
        # caller holds self._lock (the writer thread inside the CV)
        if self._file is None:
            os.makedirs(self.spill_dir, exist_ok=True)
            path = os.path.join(self.spill_dir, "capture.jsonl")
            self._file = open(path, "a", encoding="utf-8")
            self._file_bytes = self._file.tell()
        data = "".join(lines)
        self._file.write(data)
        self._file.flush()
        self._file_bytes += len(data)
        self._spilled += len(lines)
        if self._file_bytes >= self.spill_max_bytes:
            self._rotate_locked()

    def _rotate_locked(self):
        # caller holds self._lock
        self._file.close()
        self._file = None
        base = os.path.join(self.spill_dir, "capture.jsonl")
        for i in range(self.spill_files, 0, -1):
            src = base if i == 1 else f"{base}.{i - 1}"
            if os.path.exists(src):
                os.replace(src, f"{base}.{i}")
        self._rotations += 1
        self._file_bytes = 0
        flight.record("capture", "rotate", file=base,
                      rotation=self._rotations)

    def flush(self, timeout: float = 10.0) -> bool:
        """Block until the spill writer drained everything pending
        (True) or the timeout passed.  No-op without a spill dir."""
        if not self.spill_dir:
            return True
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._pending:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.notify()
                self._cv.wait(min(left, 0.25))
            if self._file is not None:
                try:
                    self._file.flush()
                except OSError:
                    pass
        return True

    def close(self):
        """Stop the writer (flushing what's pending) and close the
        spill file.  The ring stays readable."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()
            writer = self._writer
        if writer is not None:
            writer.join(timeout=10)

    # -- query surfaces ------------------------------------------------------
    def entries(self, last: int | None = None, tenant: str | None = None,
                outcome: str | None = None,
                conversation: str | None = None) -> list[dict]:
        """Snapshot of the ring, oldest first, optionally filtered by
        tenant / outcome / conversation and tail-limited to ``last``.
        The ``conversation`` filter matches what was stored — the raw id
        in ``full`` mode, its hash in ``shape`` mode — and accepts
        either form (the raw id is re-hashed for the comparison)."""
        with self._lock:
            out = list(self._ring)
        if tenant is not None:
            out = [e for e in out if e["tenant"] == tenant]
        if outcome is not None:
            out = [e for e in out if e["outcome"] == outcome]
        if conversation is not None:
            want = {conversation,
                    hashlib.blake2b(str(conversation).encode("utf-8"),
                                    digest_size=8).hexdigest()}
            out = [e for e in out if e.get("conversation") in want]
        if last is not None:
            out = out[-max(0, int(last)):]
        return [dict(e) for e in out]

    def stats(self) -> dict:
        with self._lock:
            return {
                "mode": self.mode,
                "max_entries": self.max_entries,
                "entries": len(self._ring),
                "recorded": self._recorded,
                "dropped": self._dropped,
                "spill": None if not self.spill_dir else {
                    "dir": self.spill_dir,
                    "spilled": self._spilled,
                    "rotations": self._rotations,
                    "max_bytes": self.spill_max_bytes,
                },
            }

    def debug_state(self, last: int = 64, tenant: str | None = None,
                    outcome: str | None = None,
                    conversation: str | None = None) -> dict:
        """The ``GET /debug/capture`` payload."""
        out = self.stats()
        out["filtered"] = {"last": last, "tenant": tenant,
                          "outcome": outcome, "conversation": conversation}
        out["window"] = self.entries(last=last, tenant=tenant,
                                     outcome=outcome,
                                     conversation=conversation)
        return out

    def tail(self, n: int | None = None) -> dict:
        """The ``capture_tail`` incident-bundle section: the last N
        arrivals before the bundle was cut, with the per-tenant
        admit/shed mix.  Prompt ids never ride into a bundle — the tail
        is always shape-view, whatever the capture mode."""
        n = _TAIL_N if n is None else int(n)
        with self._lock:
            raw = list(self._ring)[-n:]
        entries = [{k: v for k, v in e.items() if k != "prompt"}
                   for e in raw]
        counts: dict[str, dict] = {}
        for e in entries:
            c = counts.setdefault(e["tenant"], {"admitted": 0, "shed": 0})
            c["admitted" if e["outcome"] == "admitted" else "shed"] += 1
        span = (round(entries[-1]["t"] - entries[0]["t"], 4)
                if len(entries) > 1 else 0.0)
        return {"mode": self.mode, "entries": entries, "counts": counts,
                "window_s": span}

    def clear(self):
        with self._lock:
            self._ring.clear()
            self._recorded = 0
            self._dropped = 0


# -- process default ----------------------------------------------------------

_default_lock = threading.Lock()
_default: TrafficCapture | None = None


def install_incident_section(cap: TrafficCapture):
    """Make ``cap`` the source of the ``capture_tail`` section in every
    future incident/crash bundle (the ``watchdog.add_section`` seam —
    ``slo.build_incident`` starts from ``watchdog.collect``, so the
    section rides every bundle automatically)."""
    watchdog.add_section("capture_tail", cap.tail)


def get_capture() -> TrafficCapture:
    """The process-default recorder (created on first use from the env
    knobs); every Gateway without explicit capture knobs records here."""
    global _default
    with _default_lock:
        if _default is None:
            _default = TrafficCapture()
            install_incident_section(_default)
        return _default


def set_capture(cap: TrafficCapture | None) -> TrafficCapture | None:
    """Swap the process default (tests; knob-built captures keep their
    gateway-local identity and don't go through here)."""
    global _default
    with _default_lock:
        _default = cap
        if cap is not None:
            install_incident_section(cap)
        return cap


# -- trace fitting ------------------------------------------------------------

def fit_params(entries, *, bin_s: float | None = None,
               duration_s: float | None = None) -> dict:
    """Estimate the traffic model behind a capture: a piecewise-constant
    windowed arrival-rate curve, lognormal prompt/output length
    parameters (MLE on the logs), the tenant mix, and — when the rate
    curve steps hard enough — the flash window.

    Works on shape-mode entries: only ``t``/``prompt_len``/
    ``max_tokens``/``tenant``/``deadline_s`` are read.
    """
    ts = sorted(float(e["t"]) for e in entries)
    if len(ts) < 2:
        raise ValueError(f"need >= 2 captured arrivals to fit a trace, "
                         f"got {len(ts)}")
    t0 = ts[0]
    duration = float(duration_s) if duration_s is not None else \
        (ts[-1] - t0) * (1.0 + 1.0 / len(ts))   # tail-corrected span
    duration = max(duration, 1e-6)
    if bin_s is None:
        bin_s = min(30.0, max(0.5, duration / 24.0))
    n_bins = max(1, int(math.ceil(duration / bin_s)))
    counts = [0] * n_bins
    for t in ts:
        counts[min(n_bins - 1, int((t - t0) / bin_s))] += 1
    bins = [{"t0": round(i * bin_s, 4), "t1": round((i + 1) * bin_s, 4),
             "qps": round(c / bin_s, 4)} for i, c in enumerate(counts)]

    def lognorm(values):
        logs = np.log(np.maximum(np.asarray(values, np.float64), 1.0))
        return {"mu": round(float(logs.mean()), 4),
                "sigma": round(float(logs.std()), 4),
                "p50": int(round(math.exp(float(logs.mean()))))}

    tenants: dict[str, int] = {}
    deadlines = []
    for e in entries:
        tenants[e.get("tenant") or ""] = tenants.get(
            e.get("tenant") or "", 0) + 1
        if e.get("deadline_s") is not None:
            deadlines.append(float(e["deadline_s"]))
    n = len(entries)
    rates = [b["qps"] for b in bins]
    base = float(np.median(rates))
    peak = max(rates)
    flash = None
    if base > 0 and peak >= 2.0 * base:
        # the flash window is the LONGEST consecutive run of hot bins
        # (>= 2x the median rate): with fine bins, Poisson noise makes
        # isolated bins hot — a first-to-last-hot-bin span would smear
        # the window across them
        best = run = None
        for b in bins:
            if b["qps"] >= 2.0 * base:
                run = [run[0], b] if run else [b, b]
                if best is None or (run[1]["t1"] - run[0]["t0"] >
                                    best[1]["t1"] - best[0]["t0"]):
                    best = list(run)
            else:
                run = None
        flash = {"t0": best[0]["t0"], "t1": best[1]["t1"],
                 "mult": round(peak / base, 2)}
    return {
        "arrivals": n,
        "duration_s": round(duration, 4),
        "bin_s": round(bin_s, 4),
        "bins": bins,
        "base_qps": round(base, 4),
        "peak_qps": round(peak, 4),
        "flash": flash,
        "prompt": lognorm([e["prompt_len"] for e in entries]),
        "out": lognorm([e["max_tokens"] for e in entries]),
        "tenants": {k: round(v / n, 4) for k, v in sorted(tenants.items())},
        "deadline_s": (round(float(np.median(deadlines)), 4)
                       if deadlines else None),
    }


def fit_trace(entries, *, seed: int = 0, bin_s: float | None = None,
              duration_s: float | None = None, prompt_max: int = 512,
              out_max: int = 256, params: dict | None = None) -> list:
    """Emit a ``make_trace``-compatible synthetic trace fitted to a
    capture: arrivals drawn by thinning against the capture's binned
    rate curve (the flash window survives as a rate step), lengths from
    the fitted lognormals, tenants from the measured mix.  Entries are
    ``{"t", "prompt_len", "max_tokens"[, "deadline_s"][, "tenant"]}`` —
    the exact schema :class:`~paddle_tpu.serving.FleetSim` and
    ``load_gen.replay_http`` consume."""
    p = params if params is not None else fit_params(
        entries, bin_s=bin_s, duration_s=duration_s)
    rs = np.random.RandomState(seed)
    bins = p["bins"]
    bw = p["bin_s"]
    duration = p["duration_s"]
    rate_max = max(p["peak_qps"], 1e-6)

    def rate(t: float) -> float:
        return bins[min(len(bins) - 1, int(t / bw))]["qps"]

    tenant_names = [k for k in p["tenants"] if k]
    tenant_cdf = np.cumsum([p["tenants"][k] for k in tenant_names]) \
        if tenant_names else None
    trace = []
    t = 0.0
    while True:
        t += float(rs.exponential(1.0 / rate_max))
        if t >= duration:
            break
        if rs.uniform() * rate_max > rate(t):
            continue                     # thinned
        entry = {
            "t": round(t, 4),
            "prompt_len": int(np.clip(rs.lognormal(
                p["prompt"]["mu"], p["prompt"]["sigma"]), 1, prompt_max)),
            "max_tokens": int(np.clip(rs.lognormal(
                p["out"]["mu"], p["out"]["sigma"]), 1, out_max)),
        }
        if p["deadline_s"] is not None:
            entry["deadline_s"] = p["deadline_s"]
        if tenant_names:
            entry["tenant"] = tenant_names[int(
                np.searchsorted(tenant_cdf, rs.uniform() * tenant_cdf[-1]))]
        trace.append(entry)
    return trace
