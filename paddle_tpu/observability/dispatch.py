"""Op-dispatch telemetry — the counters behind ``@defop``.

Every framework op funnels through ``core.op.apply_op``; when telemetry is
on, that hub calls :func:`record` with the op name and host wall-time.  The
eager-vs-traced split rides on ``jax.core.trace_state_clean()``: inside any
jit/vjp trace the op executes as graph construction (its host time is trace
overhead, not kernel time), outside it is a real eager dispatch — the same
distinction the reference draws between dygraph kernel launches and static
program building.
"""
from __future__ import annotations

from . import metrics as metrics_mod
from . import registry

# metric names (see docs/observability.md for the naming scheme)
OP_DISPATCH_TOTAL = "paddle_tpu_op_dispatch_total"
OP_HOST_SECONDS = "paddle_tpu_op_host_seconds_total"


def _trace_state_clean() -> bool:
    import jax
    try:
        return jax.core.trace_state_clean()
    except Exception:  # pragma: no cover - future jax relocations
        return True


def record(name: str, seconds: float):
    """One op dispatch: count it, split by mode, accumulate host time."""
    mode = "eager" if _trace_state_clean() else "traced"
    reg = registry()
    reg.counter(OP_DISPATCH_TOTAL,
                "framework op dispatches through apply_op").inc(
        1.0, labels={"op": name, "mode": mode})
    reg.counter(OP_HOST_SECONDS,
                "cumulative host wall-time inside apply_op").inc(
        seconds, labels={"op": name})


def dispatch_counts(mode: str | None = None) -> dict[str, float]:
    """{op name: dispatch count}, optionally filtered by mode."""
    c = registry().get(OP_DISPATCH_TOTAL)
    out: dict[str, float] = {}
    if not isinstance(c, metrics_mod.Counter):
        return out
    for labels, v in c.series():
        if mode is not None and labels.get("mode") != mode:
            continue
        out[labels.get("op", "?")] = out.get(labels.get("op", "?"), 0.0) + v
    return out
