"""PyLayer — user-defined autograd ops (reference: paddle/fluid/eager/pylayer/ +
python/paddle/autograd/py_layer.py).

A PyLayer's `backward` is arbitrary Python, so it records a GradNode whose
"vjp" calls the user's backward on concrete tensors.  The functional/jit path
should instead use `jax.custom_vjp` directly (exposed as custom_vjp here).
"""
from __future__ import annotations

import jax.numpy as jnp

from . import autograd
from .tensor import Tensor


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self._saved = tuple(tensors)

    def saved_tensor(self):
        return self._saved


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        inputs = [a for a in args if isinstance(a, Tensor)] + \
                 [v for v in kwargs.values() if isinstance(v, Tensor)]
        grad_on = autograd.is_grad_enabled()
        diff_inputs = [t for t in inputs if not t.stop_gradient] if grad_on else []

        with autograd.no_grad():
            out = cls.forward(ctx, *args, **kwargs)

        single = not isinstance(out, (tuple, list))
        outs = [out] if single else list(out)
        outs = [o if isinstance(o, Tensor) else Tensor(o) for o in outs]

        if diff_inputs:
            avals = [(tuple(o.shape), o._value.dtype) for o in outs]

            diff_mask = [not t.stop_gradient for t in inputs]

            def _normalize(gin):
                gin = (gin,) if isinstance(gin, Tensor) or gin is None \
                    else tuple(gin)
                if len(gin) == len(inputs):
                    # one grad per tensor input: select the differentiable ones
                    gin = [g for g, m in zip(gin, diff_mask) if m]
                return gin

            def vjp_fn(cts):
                cts = (cts,) if len(outs) == 1 else cts
                ct_tensors = tuple(Tensor(jnp.asarray(c), _internal=True)
                                   for c in cts)
                with autograd.no_grad():
                    gin = _normalize(cls.backward(ctx, *ct_tensors))
                out_grads = []
                for g, t in zip(gin, diff_inputs):
                    out_grads.append(jnp.zeros_like(t._value) if g is None
                                     else g._value)
                return out_grads

            def taped_vjp(ct_tensors):
                # create_graph path: grad mode is ON (backward()'s guard), so
                # every taped op in the user's backward records — the
                # returned grads are differentiable through the cotangents
                # AND the tensors the user saved in ctx
                gin = _normalize(cls.backward(ctx, *ct_tensors))
                out_grads = []
                for g, t in zip(gin, diff_inputs):
                    if g is None:
                        g = Tensor(jnp.zeros_like(t._value),
                                   stop_gradient=True, _internal=True)
                    out_grads.append(g)
                return out_grads

            node = autograd.GradNode(vjp_fn, diff_inputs, len(outs), avals,
                                     name=cls.__name__, taped_vjp=taped_vjp)
            for i, o in enumerate(outs):
                o._grad_node = node
                o._grad_slot = i
                o.stop_gradient = False
        return outs[0] if single else tuple(outs)
