from . import dtype, place, random, autograd  # noqa: F401
from .tensor import Tensor, to_tensor  # noqa: F401
from .autograd import no_grad, enable_grad, grad, is_grad_enabled, set_grad_enabled  # noqa: F401
from .place import (  # noqa: F401
    Place, CPUPlace, TPUPlace, CUDAPlace, CUDAPinnedPlace, set_device, get_device,
    is_compiled_with_cuda, is_compiled_with_rocm, is_compiled_with_xpu,
    is_compiled_with_tpu, is_compiled_with_distribute, device_count,
)
from .dtype import (  # noqa: F401
    bool_, uint8, int8, int16, int32, int64, float16, bfloat16, float32, float64,
    complex64, complex128, set_default_dtype, get_default_dtype,
)
from .random import seed, get_rng_state, set_rng_state  # noqa: F401
