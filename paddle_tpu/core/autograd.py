"""Eager autograd engine.

The reference has two C++ autograd engines (paddle/fluid/eager/backward.cc:105
``RunBackward`` — a topological walk over ``GradNodeBase`` graphs; legacy
paddle/fluid/imperative/basic_engine.cc).  Here the graph is built per-op from
``jax.vjp``: every differentiable eager op stores its VJP closure (which holds the
residuals, like the reference's TensorWrapper saved-tensors) in a :class:`GradNode`.
``backward()`` walks nodes in reverse execution order — a valid topological order
for an eagerly-recorded graph — mirroring RunBackward's dual-queue walk without
needing an in-degree map.

The jit/compiled training path does NOT use this engine: there gradients come from
``jax.grad`` over a functional step (the analog of static-graph ``append_backward``).
"""
from __future__ import annotations

import contextlib
import itertools
import threading
from typing import Any, Callable, Sequence

import numpy as np
import jax
import jax.numpy as jnp


class _GradState(threading.local):
    def __init__(self):
        self.enabled = True


_grad_state = _GradState()
_node_counter = itertools.count()


def is_grad_enabled() -> bool:
    return _grad_state.enabled


def set_grad_enabled(mode: bool):
    _grad_state.enabled = bool(mode)


class no_grad(contextlib.ContextDecorator):
    """paddle.no_grad — usable as context manager and decorator."""

    def __enter__(self):
        self._prev = _grad_state.enabled
        _grad_state.enabled = False
        return self

    def __exit__(self, *exc):
        _grad_state.enabled = self._prev
        return False


class enable_grad(contextlib.ContextDecorator):
    def __enter__(self):
        self._prev = _grad_state.enabled
        _grad_state.enabled = True
        return self

    def __exit__(self, *exc):
        _grad_state.enabled = self._prev
        return False


_saved_tensor_hooks = None


class GradNode:
    """One recorded op: holds the VJP closure and edges to input tensors.

    ≈ GradNodeBase (paddle/fluid/eager/grad_node_info.h:168): ``inputs`` are the
    slot edges, ``vjp_fn`` plays the role of the generated grad-op body plus its
    saved TensorWrappers.
    """

    __slots__ = ("seq", "vjp_fn", "inputs", "n_outputs", "out_avals", "name",
                 "_packed")

    def __init__(self, vjp_fn, inputs, n_outputs, out_avals, name=""):
        self.seq = next(_node_counter)
        self.inputs = inputs          # list[Tensor] (only those requiring grad)
        self.n_outputs = n_outputs
        self.out_avals = out_avals    # list[(shape, dtype)] for zero cotangents
        self.name = name
        self._packed = None
        hooks = _saved_tensor_hooks
        if hooks is not None:
            # saved-tensor hooks (reference saved_tensors_hooks.py, the
            # activation-offload hook pair): the jax.vjp closure is a
            # pytree whose array leaves ARE the saved residuals — pack
            # them now, unpack lazily at backward time
            import jax.tree_util as _jtu
            pack, _ = hooks
            leaves, treedef = _jtu.tree_flatten(vjp_fn)
            was_array = [hasattr(x, "dtype") for x in leaves]
            packed = [pack(x) if a else x
                      for x, a in zip(leaves, was_array)]
            self._packed = (treedef, packed, was_array, hooks)
            self.vjp_fn = None
        else:
            self.vjp_fn = vjp_fn

    def _materialized_vjp(self):
        if self._packed is not None:
            import jax.tree_util as _jtu
            treedef, packed, was_array, (_, unpack) = self._packed
            leaves = [unpack(x) if a else x
                      for x, a in zip(packed, was_array)]
            return _jtu.tree_unflatten(treedef, leaves)
        return self.vjp_fn

    def released(self) -> bool:
        return self.vjp_fn is None and self._packed is None

    def release(self):
        self.vjp_fn = None
        self._packed = None


def _zero_cotangent(shape, dtype):
    d = jnp.dtype(dtype)
    if not jnp.issubdtype(d, jnp.floating) and not jnp.issubdtype(d, jnp.complexfloating):
        return np.zeros(shape, dtype=jax.dtypes.float0)
    return jnp.zeros(shape, dtype=d)


def backward(tensors: Sequence[Any], grad_tensors: Sequence[Any] | None = None,
             retain_graph: bool = False, sink: dict | None = None,
             capture: set | None = None):
    """Run the backward pass from `tensors` (≈ egr::Backward, backward.cc:105).

    sink/capture serve paddle.grad: with `sink` given, gradients are collected
    into ``sink[id(tensor)]`` for leaves and for tensors whose id is in
    `capture`, and NO Tensor.grad is mutated anywhere in the graph.
    """
    from .tensor import Tensor  # circular: Tensor imports nothing from here at module top

    tensors = list(tensors)
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)

    # grads keyed per-(node, output-slot), plus leaf accumulation on the Tensor.
    out_grads: dict[tuple[int, int], Any] = {}
    node_by_id: dict[int, GradNode] = {}

    def _sink_add(t: Tensor, g):
        if g.dtype != t._value.dtype:
            g = g.astype(t._value.dtype)
        prev = sink.get(id(t))
        sink[id(t)] = g if prev is None else prev + g

    def seed_grad(t: Tensor, g):
        if g is None:
            if t._value.size != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs; "
                    f"got shape {tuple(t.shape)}")
            g = jnp.ones_like(t._value)
        else:
            g = g._value if isinstance(g, Tensor) else jnp.asarray(g)
        captured = capture is not None and id(t) in capture
        if captured:
            _sink_add(t, g)
        if t._grad_node is None:
            if not captured:
                _accumulate_leaf(t, g)
        else:
            node = t._grad_node
            node_by_id[id(node)] = node
            key = (id(node), t._grad_slot)
            out_grads[key] = g if key not in out_grads else out_grads[key] + g

    def _accumulate_leaf(t: Tensor, g):
        if t.stop_gradient:
            return
        from .selected_rows import SelectedRows
        if isinstance(g, SelectedRows):
            # sparse embedding grads: keep the rows/values form on the leaf
            # (selected_rows.h contract); mixing with a dense grad densifies
            if sink is not None:
                _sink_add(t, g.to_dense())
                return
            if t._grad is None:
                t._grad = g
            elif isinstance(t._grad, SelectedRows):
                t._grad = t._grad.concat(g)
            else:
                t._grad = Tensor(t._grad._value + g.to_dense(),
                                 stop_gradient=True)
            return
        if sink is not None:
            _sink_add(t, g)
            return
        if g.dtype != t._value.dtype:
            g = g.astype(t._value.dtype)
        if t._grad is None:
            t._grad = Tensor(g, stop_gradient=True)
        elif isinstance(t._grad, SelectedRows):
            t._grad = Tensor(t._grad.to_dense() + g, stop_gradient=True)
        else:
            t._grad = Tensor(t._grad._value + g, stop_gradient=True)

    for t, g in zip(tensors, grad_tensors):
        seed_grad(t, g)

    # Discover the reachable subgraph.
    frontier = list(node_by_id.values())
    seen = set(node_by_id)
    while frontier:
        node = frontier.pop()
        for inp in node.inputs:
            parent = inp._grad_node
            if parent is not None and id(parent) not in seen:
                seen.add(id(parent))
                node_by_id[id(parent)] = parent
                frontier.append(parent)

    # Reverse execution order == topological order for an eager tape.
    order = sorted(node_by_id.values(), key=lambda n: n.seq, reverse=True)

    for node in order:
        if node.released():
            raise RuntimeError(
                "trying to backward through the graph a second time; "
                "pass retain_graph=True to Tensor.backward() if needed")
        cts = []
        has_any = False
        for slot in range(node.n_outputs):
            g = out_grads.pop((id(node), slot), None)
            if g is None:
                shape, dtype = node.out_avals[slot]
                g = _zero_cotangent(shape, dtype)
            else:
                has_any = True
            cts.append(g)
        if not has_any:
            continue
        ct = cts[0] if node.n_outputs == 1 else tuple(cts)
        in_grads = node._materialized_vjp()(ct)
        if not retain_graph:
            node.release()
        for inp, g in zip(node.inputs, in_grads):
            captured = capture is not None and id(inp) in capture
            if captured:
                _sink_add(inp, g)
            if inp._grad_node is None:
                if not captured:
                    _accumulate_leaf(inp, g)
            else:
                key = (id(inp._grad_node), inp._grad_slot)
                out_grads[key] = g if key not in out_grads else out_grads[key] + g


def grad(outputs, inputs, grad_outputs=None, retain_graph=None, create_graph=False,
         allow_unused=False):
    """paddle.grad — functional gradient of eager outputs w.r.t. inputs.

    Implemented by running :func:`backward` on a detached view of leaf grads.
    create_graph (double backward) is served by the functional `jax.grad` path
    instead and rejected here.
    """
    from .tensor import Tensor

    if create_graph:
        raise NotImplementedError(
            "create_graph=True in eager mode is not supported; use the functional "
            "API (paddle_tpu.incubate.autograd or jax.grad over a pure function)")
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    from .tensor import Tensor
    sink: dict[int, Any] = {}
    backward(outputs, grad_outputs, retain_graph=bool(retain_graph),
             sink=sink, capture={id(t) for t in inputs})
    result = []
    for t in inputs:
        g = sink.get(id(t))
        if g is None and not allow_unused:
            raise RuntimeError(
                "one of the inputs has no gradient; pass allow_unused=True "
                "to get None for it")
        result.append(None if g is None else Tensor(g, stop_gradient=True,
                                                    _internal=True))
    return result
