"""Eager autograd engine.

The reference has two C++ autograd engines (paddle/fluid/eager/backward.cc:105
``RunBackward`` — a topological walk over ``GradNodeBase`` graphs; legacy
paddle/fluid/imperative/basic_engine.cc).  Here the graph is built per-op from
``jax.vjp``: every differentiable eager op stores its VJP closure (which holds the
residuals, like the reference's TensorWrapper saved-tensors) in a :class:`GradNode`.
``backward()`` walks nodes in reverse execution order — a valid topological order
for an eagerly-recorded graph — mirroring RunBackward's dual-queue walk without
needing an in-degree map.

The jit/compiled training path does NOT use this engine: there gradients come from
``jax.grad`` over a functional step (the analog of static-graph ``append_backward``).
"""
from __future__ import annotations

import contextlib
import itertools
import threading
from typing import Any, Callable, Sequence

import numpy as np
import jax
import jax.numpy as jnp


class _GradState(threading.local):
    def __init__(self):
        self.enabled = True


_grad_state = _GradState()
_node_counter = itertools.count()


def is_grad_enabled() -> bool:
    return _grad_state.enabled


def set_grad_enabled(mode: bool):
    _grad_state.enabled = bool(mode)


class no_grad(contextlib.ContextDecorator):
    """paddle.no_grad — usable as context manager and decorator."""

    def __enter__(self):
        self._prev = _grad_state.enabled
        _grad_state.enabled = False
        return self

    def __exit__(self, *exc):
        _grad_state.enabled = self._prev
        return False


class enable_grad(contextlib.ContextDecorator):
    def __enter__(self):
        self._prev = _grad_state.enabled
        _grad_state.enabled = True
        return self

    def __exit__(self, *exc):
        _grad_state.enabled = self._prev
        return False


_saved_tensor_hooks = None


class GradNode:
    """One recorded op: holds the VJP closure and edges to input tensors.

    ≈ GradNodeBase (paddle/fluid/eager/grad_node_info.h:168): ``inputs`` are the
    slot edges, ``vjp_fn`` plays the role of the generated grad-op body plus its
    saved TensorWrappers.
    """

    __slots__ = ("seq", "vjp_fn", "inputs", "n_outputs", "out_avals", "name",
                 "_packed", "closure", "taped_vjp")

    def __init__(self, vjp_fn, inputs, n_outputs, out_avals, name="",
                 closure=None, taped_vjp=None):
        self.seq = next(_node_counter)
        self.inputs = inputs          # list[Tensor] (only those requiring grad)
        self.n_outputs = n_outputs
        self.out_avals = out_avals    # list[(shape, dtype)] for zero cotangents
        self.name = name
        # the op's pure fn of its differentiable primals — double backward
        # (create_graph=True) re-runs jax.vjp over it THROUGH apply_op so
        # the grad computation itself lands on the tape (reference
        # dygraph/base.py:432-465 grad(create_graph=True))
        self.closure = closure
        # create_graph fallback for nodes whose backward is arbitrary Python
        # built from taped ops (PyLayer): called with Tensor cotangents
        # under grad mode so the user's backward records onto the tape
        self.taped_vjp = taped_vjp
        self._packed = None
        hooks = _saved_tensor_hooks
        if hooks is not None:
            # saved-tensor hooks (reference saved_tensors_hooks.py, the
            # activation-offload hook pair): the jax.vjp closure is a
            # pytree whose array leaves ARE the saved residuals — pack
            # them now, unpack lazily at backward time
            import jax.tree_util as _jtu
            pack, _ = hooks
            leaves, treedef = _jtu.tree_flatten(vjp_fn)
            was_array = [hasattr(x, "dtype") for x in leaves]
            packed = [pack(x) if a else x
                      for x, a in zip(leaves, was_array)]
            self._packed = (treedef, packed, was_array, hooks)
            self.vjp_fn = None
        else:
            self.vjp_fn = vjp_fn

    def _materialized_vjp(self):
        if self._packed is not None:
            import jax.tree_util as _jtu
            treedef, packed, was_array, (_, unpack) = self._packed
            leaves = [unpack(x) if a else x
                      for x, a in zip(packed, was_array)]
            return _jtu.tree_unflatten(treedef, leaves)
        return self.vjp_fn

    def released(self) -> bool:
        return self.vjp_fn is None and self._packed is None

    def release(self):
        self.vjp_fn = None
        self._packed = None
        self.closure = None   # drop captured raw inputs with the residuals
        self.taped_vjp = None


def _zero_cotangent(shape, dtype):
    d = jnp.dtype(dtype)
    if not jnp.issubdtype(d, jnp.floating) and not jnp.issubdtype(d, jnp.complexfloating):
        return np.zeros(shape, dtype=jax.dtypes.float0)
    return jnp.zeros(shape, dtype=d)


def backward(tensors: Sequence[Any], grad_tensors: Sequence[Any] | None = None,
             retain_graph: bool = False, sink: dict | None = None,
             capture: set | None = None, create_graph: bool = False):
    """Run the backward pass from `tensors` (≈ egr::Backward, backward.cc:105).

    sink/capture serve paddle.grad: with `sink` given, gradients are collected
    into ``sink[id(tensor)]`` for leaves and for tensors whose id is in
    `capture`, and NO Tensor.grad is mutated anywhere in the graph.

    create_graph: run every VJP through apply_op so the backward pass is
    itself recorded on the tape — gradients come back as differentiable
    Tensors wired to the cotangents AND the original primals (double
    backward; reference dygraph/base.py:432-465).
    """
    from .tensor import Tensor  # circular: Tensor imports nothing from here at module top

    tensors = list(tensors)
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    retain_graph = retain_graph or create_graph

    # grads keyed per-(node, output-slot), plus leaf accumulation on the Tensor.
    # With create_graph the values are taped Tensors; otherwise raw jnp arrays.
    out_grads: dict[tuple[int, int], Any] = {}
    node_by_id: dict[int, GradNode] = {}

    def _acc(a, b):
        """Cotangent accumulation that never drops the tape: `raw + Tensor`
        would coerce the Tensor through __jax_array__ into a constant, so
        put the Tensor on the left (its __add__ records the op)."""
        if isinstance(b, Tensor) and not isinstance(a, Tensor):
            return b + a
        return a + b

    def _sink_add(t: Tensor, g):
        if g.dtype != t._value.dtype:
            g = g.astype(t._value.dtype)
        prev = sink.get(id(t))
        sink[id(t)] = g if prev is None else _acc(prev, g)

    def seed_grad(t: Tensor, g):
        if g is None:
            if t._value.size != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs; "
                    f"got shape {tuple(t.shape)}")
            g = jnp.ones_like(t._value)
        elif isinstance(g, Tensor):
            # create_graph: a taped grad_tensors seed must stay on the tape
            g = g if create_graph else g._value
        else:
            g = jnp.asarray(g)
        captured = capture is not None and id(t) in capture
        if captured:
            _sink_add(t, g)
        if t._grad_node is None:
            if not captured:
                _accumulate_leaf(t, g)
        else:
            node = t._grad_node
            node_by_id[id(node)] = node
            key = (id(node), t._grad_slot)
            out_grads[key] = g if key not in out_grads else \
                _acc(out_grads[key], g)

    def _accumulate_leaf(t: Tensor, g):
        if t.stop_gradient:
            return
        from .selected_rows import SelectedRows
        if isinstance(g, SelectedRows):
            # sparse embedding grads: keep the rows/values form on the leaf
            # (selected_rows.h contract); mixing with a dense grad densifies
            if sink is not None:
                _sink_add(t, g.to_dense())
                return
            if t._grad is None:
                t._grad = g
            elif isinstance(t._grad, SelectedRows):
                t._grad = t._grad.concat(g)
            else:
                t._grad = Tensor(t._grad._value + g.to_dense(),
                                 stop_gradient=True)
            return
        if sink is not None:
            _sink_add(t, g)
            return
        if g.dtype != t._value.dtype:
            g = g.astype(t._value.dtype)
        if isinstance(g, Tensor):
            # create_graph path: keep the taped Tensor as .grad so further
            # differentiation through param.grad works
            if t._grad is None:
                t._grad = g
            else:
                # to_dense() yields a raw jnp array; wrap it so _acc keeps
                # the taped g on the left (raw + Tensor would constant-fold
                # g through __jax_array__) and .grad stays a Tensor
                prev = Tensor(t._grad.to_dense(), stop_gradient=True) \
                    if isinstance(t._grad, SelectedRows) else t._grad
                t._grad = _acc(prev, g)
            return
        if t._grad is None:
            t._grad = Tensor(g, stop_gradient=True)
        elif isinstance(t._grad, SelectedRows):
            t._grad = Tensor(t._grad.to_dense() + g, stop_gradient=True)
        else:
            t._grad = Tensor(t._grad._value + g, stop_gradient=True)

    def _walk():
        for t, g in zip(tensors, grad_tensors):
            seed_grad(t, g)

        # Discover the reachable subgraph.
        frontier = list(node_by_id.values())
        seen = set(node_by_id)
        while frontier:
            node = frontier.pop()
            for inp in node.inputs:
                parent = inp._grad_node
                if parent is not None and id(parent) not in seen:
                    seen.add(id(parent))
                    node_by_id[id(parent)] = parent
                    frontier.append(parent)

        # Reverse execution order == topological order for an eager tape.
        order = sorted(node_by_id.values(), key=lambda n: n.seq, reverse=True)

        for node in order:
            if node.released():
                raise RuntimeError(
                    "trying to backward through the graph a second time; "
                    "pass retain_graph=True to Tensor.backward() if needed")
            cts = []
            has_any = False
            for slot in range(node.n_outputs):
                g = out_grads.pop((id(node), slot), None)
                if g is None:
                    shape, dtype = node.out_avals[slot]
                    g = _zero_cotangent(shape, dtype)
                else:
                    has_any = True
                cts.append(g)
            if not has_any:
                continue
            ct = cts[0] if node.n_outputs == 1 else tuple(cts)
            if create_graph and node.closure is None \
                    and node.taped_vjp is None:
                # a node with neither a pure closure nor a tape-able user
                # backward (SelectedRows lookup) cannot be re-linearized:
                # raising beats silently returning first-order-only grads
                # (wrong Hessians)
                raise NotImplementedError(
                    f"create_graph=True through op {node.name!r} is not "
                    f"supported: its backward is not a pure traced closure "
                    f"(sparse/SelectedRows path). Express it with regular "
                    f"tensor ops to differentiate twice.")
            if create_graph and node.closure is None:
                # PyLayer: run the USER's backward under the tape with
                # Tensor cotangents — every taped op it executes records a
                # GradNode, so the returned grads are differentiable
                # through both the cotangents and the saved tensors
                # (reference: codegen'd differentiable grad nodes,
                # eager/backward.cc:105 over generated grad ops).
                cts_t = []
                for slot, c in enumerate(cts):
                    if not isinstance(c, Tensor):
                        if getattr(c, "dtype", None) == jax.dtypes.float0:
                            shape, dtype = node.out_avals[slot]
                            c = jnp.zeros(shape, dtype)
                        c = Tensor(jnp.asarray(c), stop_gradient=True,
                                   _internal=True)
                    cts_t.append(c)
                in_grads = node.taped_vjp(tuple(cts_t))
            elif create_graph:
                # Tape the grad computation: grad = vjp(closure, primals)(ct) is a
                # pure jnp function of (ct, primals), so running it through
                # apply_op records a second-order-differentiable op whose edges
                # reach the cotangents and the original inputs.
                from .op import apply_op
                node_closure = node.closure

                def _grad_fn(ct_, *primals, _f=node_closure):
                    res = jax.vjp(_f, *primals)[1](ct_)
                    # unpack 1-tuples: a plain tuple output makes the recorded
                    # node's own vjp expect a tuple cotangent, but the walk
                    # hands single-output nodes a bare array
                    return res[0] if len(res) == 1 else res

                in_grads = apply_op(_grad_fn, node.name + "_grad",
                                    (ct, *node.inputs), {})
                if not isinstance(in_grads, (tuple, list)):
                    in_grads = (in_grads,)
            else:
                in_grads = node._materialized_vjp()(ct)
            if not retain_graph:
                node.release()
            for inp, g in zip(node.inputs, in_grads):
                captured = capture is not None and id(inp) in capture
                if captured:
                    _sink_add(inp, g)
                if inp._grad_node is None:
                    if not captured:
                        _accumulate_leaf(inp, g)
                else:
                    key = (id(inp._grad_node), inp._grad_slot)
                    out_grads[key] = g if key not in out_grads else \
                        _acc(out_grads[key], g)

    # create_graph: the whole pass — VJP replays AND cotangent accumulation
    # (Tensor adds when a primal fans out) — must tape with grad mode ON and
    # autocast OFF.  A surrounding no_grad would record nothing (silently
    # stop_gradient grads despite create_graph=True); a surrounding
    # auto_cast(O2) would cast replayed '<op>_grad' ops and grad
    # accumulations to bf16, diverging from the original-dtype vjp path.
    with contextlib.ExitStack() as guards:
        if create_graph:
            from ..amp.auto_cast import auto_cast
            guards.enter_context(enable_grad())
            guards.enter_context(auto_cast(enable=False))
        _walk()


def grad(outputs, inputs, grad_outputs=None, retain_graph=None, create_graph=False,
         allow_unused=False):
    """paddle.grad — functional gradient of eager outputs w.r.t. inputs
    (reference dygraph/base.py:432-465).

    Implemented by running :func:`backward` with a sink dict so no .grad is
    mutated.  With create_graph=True the backward pass itself is recorded on
    the tape (each VJP re-run through apply_op), so the returned grads are
    differentiable — grad-of-grad / gradient penalties work in eager mode.
    """
    from .tensor import Tensor

    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if retain_graph is None:
        retain_graph = create_graph
    sink: dict[int, Any] = {}
    backward(outputs, grad_outputs, retain_graph=bool(retain_graph),
             sink=sink, capture={id(t) for t in inputs},
             create_graph=create_graph)
    result = []
    for t in inputs:
        g = sink.get(id(t))
        if g is None and not allow_unused:
            raise RuntimeError(
                "one of the inputs has no gradient; pass allow_unused=True "
                "to get None for it")
        if g is None:
            result.append(None)
        elif isinstance(g, Tensor):
            result.append(g)          # taped (create_graph path)
        else:
            result.append(Tensor(g, stop_gradient=True, _internal=True))
    return result
