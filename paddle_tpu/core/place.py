"""Device/Place API.

The reference models devices as ``Place`` objects (paddle/phi/common/place.h) plus a
``paddle.device`` module (set_device/get_device).  Here a Place resolves to a JAX
device; device/memory management itself rides on PJRT, so this layer is bookkeeping
plus explicit host↔device transfer points.
"""
from __future__ import annotations

import functools

import jax


class Place:
    """Base place: ``Place("tpu", 0)``."""

    def __init__(self, device_type: str, device_id: int = 0):
        self._type = device_type
        self._id = device_id

    @property
    def device_type(self) -> str:
        return self._type

    def get_device_id(self) -> int:
        return self._id

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self._type == other._type
            and self._id == other._id
        )

    def __hash__(self):
        return hash((self._type, self._id))

    def __repr__(self):
        return f"Place({self._type}:{self._id})"

    def jax_device(self):
        """Resolve to the concrete jax.Device (None → default)."""
        devs = _devices_by_type(self._type)
        if not devs:
            raise RuntimeError(f"no {self._type} devices visible to JAX")
        return devs[min(self._id, len(devs) - 1)]


class CPUPlace(Place):
    def __init__(self):
        super().__init__("cpu", 0)


class TPUPlace(Place):
    def __init__(self, device_id: int = 0):
        super().__init__("tpu", device_id)


# Alias kept so reference-era code naming CUDAPlace keeps working; it resolves to
# the accelerator actually present (TPU here).
class CUDAPlace(Place):
    def __init__(self, device_id: int = 0):
        super().__init__("accelerator", device_id)


class CUDAPinnedPlace(CPUPlace):
    pass


@functools.cache
def _accelerator_platform() -> str:
    for d in jax.devices():
        if d.platform != "cpu":
            return d.platform
    return "cpu"


def _devices_by_type(device_type: str):
    if device_type in ("accelerator", "gpu", "cuda", "tpu", "axon"):
        plat = _accelerator_platform()
        devs = [d for d in jax.devices() if d.platform == plat]
        if devs:
            return devs
        return jax.devices()
    return [d for d in jax.devices() if d.platform == device_type] or None


_current_place: Place | None = None


def set_device(device: str) -> Place:
    """paddle.set_device("tpu") / ("cpu") / ("tpu:1")."""
    global _current_place
    if isinstance(device, Place):
        _current_place = device
        return device
    name, _, idx = device.partition(":")
    name = {"gpu": "tpu", "cuda": "tpu", "xpu": "tpu"}.get(name, name)
    place = CPUPlace() if name == "cpu" else Place("accelerator", int(idx or 0))
    _current_place = place
    return place


def get_device() -> str:
    p = _get_current_place()
    return f"{p.device_type}:{p.get_device_id()}" if p.device_type != "cpu" else "cpu"


def _get_current_place() -> Place:
    global _current_place
    if _current_place is None:
        plat = _accelerator_platform()
        _current_place = CPUPlace() if plat == "cpu" else Place("accelerator", 0)
    return _current_place


def default_jax_device():
    return _get_current_place().jax_device()


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return True


def is_compiled_with_distribute() -> bool:
    return True


def device_count() -> int:
    return jax.device_count()
