"""Global RNG state.

The reference keeps per-device stateful generators (paddle/phi/core/generator.h,
``paddle.seed``).  JAX randomness is functional, so the framework keeps one global
key plus a fold-in counter: eager ops draw fresh keys from here; jitted functional
code installs a traced key with :func:`push_key` so randomness is reproducible and
trace-safe (no concrete key is baked into a compiled program).
"""
from __future__ import annotations

import contextlib
import threading

import jax


class _RNGState(threading.local):
    def __init__(self):
        # key creation is lazy: touching the backend at import time would
        # force device init before the user can pick a platform
        self._key = None
        self.counter = 0
        # Stack of externally installed (possibly traced) keys — the jit path.
        self.stack: list = []

    @property
    def key(self):
        if self._key is None:
            self._key = jax.random.key(0)
        return self._key

    @key.setter
    def key(self, k):
        self._key = k


_state = _RNGState()


_seed = 0  # last framework seed (host-side RNG consumers read this)


def seed(value: int):
    """paddle.seed — reseed the global generator."""
    global _seed
    _seed = int(value)
    _state.key = jax.random.key(int(value))
    _state.counter = 0
    return _state


def next_key():
    """Draw a fresh PRNG key.

    Inside a :func:`push_key` scope the key is folded out of the installed
    (traced) key, so the enclosing jit stays pure; otherwise it advances the
    global eager state.
    """
    _state.counter += 1
    if _state.stack:
        return jax.random.fold_in(_state.stack[-1], _state.counter)
    _state.key, sub = jax.random.split(_state.key)
    return sub


@contextlib.contextmanager
def push_key(key):
    """Install `key` (may be a tracer) as the randomness source for this scope."""
    _state.stack.append(key)
    saved = _state.counter
    _state.counter = 0
    try:
        yield
    finally:
        _state.stack.pop()
        _state.counter = saved


def get_rng_state():
    return (_state.key, _state.counter)


def set_rng_state(state):
    _state.key, _state.counter = state
