"""SelectedRows — sparse row-wise gradients for embedding tables.

Reference: paddle/phi/core/selected_rows.h + phi/kernels/selected_rows/
(31 kernel files): `Embedding(sparse=True)` produces a (rows, values)
gradient so the optimizer touches only the rows a batch actually used —
the difference between O(batch·D) and O(V·D) update cost for
recommendation-scale vocabularies.

TPU-native scope: the EAGER tape carries SelectedRows grads end-to-end
(lookup vjp → Tensor.grad → optimizer lazy row update).  The compiled SPMD
path keeps dense grads on purpose — there GSPMD shards the table and XLA
already emits the scatter-add fused with the update; sparse bookkeeping
would force dynamic shapes into the program.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np


class SelectedRows:
    """rows [K] int32/64, values [K, D]; duplicate rows allowed and
    accumulate on apply (selected_rows.h `rows_` may repeat until merged)."""

    __slots__ = ("rows", "values", "height")

    def __init__(self, rows, values, height: int):
        self.rows = rows
        self.values = values
        self.height = int(height)

    @property
    def shape(self):
        return (self.height,) + tuple(self.values.shape[1:])

    @property
    def dtype(self):
        return self.values.dtype

    def concat(self, other: "SelectedRows") -> "SelectedRows":
        """Gradient accumulation (phi MergeAdd semantics, deferred)."""
        if other.height != self.height:
            raise ValueError("SelectedRows height mismatch")
        return SelectedRows(jnp.concatenate([self.rows, other.rows]),
                            jnp.concatenate([self.values, other.values]),
                            self.height)

    def merged(self) -> "SelectedRows":
        """Unique rows with summed values (phi funcs::MergeAdd).  Eager-only
        (concrete shapes)."""
        rows = np.asarray(self.rows)
        uniq, inv = np.unique(rows, return_inverse=True)
        vals = jnp.zeros((len(uniq),) + tuple(self.values.shape[1:]),
                         self.values.dtype)
        vals = vals.at[jnp.asarray(inv)].add(self.values)
        return SelectedRows(jnp.asarray(uniq), vals, self.height)

    def to_dense(self):
        out = jnp.zeros(self.shape, self.values.dtype)
        return out.at[self.rows].add(self.values)

    def astype(self, dtype):
        return SelectedRows(self.rows, self.values.astype(dtype),
                            self.height)

    def __repr__(self):
        return (f"SelectedRows(height={self.height}, "
                f"nnz_rows={self.rows.shape[0]}, "
                f"dim={tuple(self.values.shape[1:])})")


def sparse_embedding_lookup(weight, ids, padding_idx=None):
    """Embedding lookup whose weight-gradient is a SelectedRows — the
    `Embedding(sparse=True)` path (phi embedding_sparse_grad_kernel.cu)."""
    from . import autograd
    from .op import _wrap_outputs
    from .tensor import Tensor

    w = weight._value
    idv = ids._value
    out = jnp.take(w, jnp.clip(idv, 0, w.shape[0] - 1), axis=0)
    if padding_idx is not None:
        out = jnp.where((idv == padding_idx)[..., None], 0.0, out)

    if not autograd.is_grad_enabled() or weight.stop_gradient:
        return Tensor(out, _internal=True)

    height = w.shape[0]
    dim = w.shape[1]

    def vjp_fn(ct):
        # grads flow to the rows the FORWARD actually read (clipped), never
        # to raw out-of-range ids (negative ids would otherwise wrap and
        # corrupt unrelated rows)
        rows = jnp.clip(idv.reshape(-1), 0, height - 1)
        vals = ct.reshape(-1, dim)
        if padding_idx is not None:
            vals = jnp.where((idv.reshape(-1) == padding_idx)[:, None],
                             0.0, vals)
        return (SelectedRows(rows, vals, height),)

    node = autograd.GradNode(vjp_fn, [weight], 1,
                             [(out.shape, out.dtype)],
                             name="sparse_embedding_lookup")
    return _wrap_outputs(out, node)
