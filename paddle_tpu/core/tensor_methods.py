"""Install the remaining reference Tensor methods.

The reference patches ~225 functions onto Tensor
(python/paddle/tensor/__init__.py tensor_method_func +
monkey_patch_math_varbase); defop's tensor_method covers most here, and
this module binds the long tail whose functions already exist at the
paddle_tpu top level (tensor-first signatures, so plain attribute
binding gives the method form), plus the in-place `*_` variants."""
from __future__ import annotations

__all__ = ["install_tensor_methods"]

_BIND = [
    "add_n", "addmm", "as_complex", "as_real", "broadcast_shape",
    "broadcast_tensors", "bucketize", "cholesky_solve", "chunk", "concat",
    "cond", "diff", "eig", "eigvals", "eigvalsh", "expand_as",
    "floor_mod", "gcd", "heaviside", "histogram", "is_complex",
    "is_empty", "is_floating_point", "is_integer", "is_tensor", "lcm",
    "logcumsumexp", "logit", "lstsq", "lu", "lu_unpack", "multi_dot",
    "nanquantile", "qr", "rank", "reshape_", "reverse", "scatter_",
    "scatter_nd", "shard_index", "slice", "solve", "split", "squeeze_",
    "stack", "strided_slice", "take", "tensordot", "triangular_solve",
    "unbind", "unsqueeze_", "unstack", "vsplit", "where",
]

_INPLACE = {  # method name -> out-of-place function
    "erfinv_": "erfinv",
    "flatten_": "flatten",
    "lerp_": "lerp",
    "put_along_axis_": "put_along_axis",
}


def install_tensor_methods():
    import paddle_tpu as paddle
    from .tensor import Tensor

    for name in _BIND:
        fn = getattr(paddle, name, None)
        if fn is None:
            fn = getattr(paddle.linalg, name, None)
        if fn is not None and not hasattr(Tensor, name):
            setattr(Tensor, name, fn)

    # ONE in-place pattern for the whole codebase: ops/math._make_inplace
    # keeps the autograd tape alive (grad node + slot carried into the
    # replaced buffer, stop_gradient propagated) — a bare _replace_(None)
    # would silently sever gradients
    from ..ops.math import _make_inplace

    for mname, base in _INPLACE.items():
        if not hasattr(Tensor, mname):
            _make_inplace(getattr(paddle, base), mname)
