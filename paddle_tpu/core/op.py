"""Op definition machinery — the PHI registry analog.

The reference routes every eager op through generated C++ glue: python-C fn →
dygraph_function (grad-node construction) → phi kernel (SURVEY §3.1).  Here one
decorator does all three jobs:

* ``@defop`` turns a raw jnp-level function into a framework op: it unwraps
  Tensor arguments, runs the computation, wraps results back into Tensors.
* If grads are enabled and any input requires grad, the op is executed through
  ``jax.vjp`` and the returned VJP closure becomes the op's GradNode (residual
  saving ≈ TensorWrapper; generated grad node ≈ the vjp closure).
* The raw function stays reachable as ``op.raw`` so the functional/jit path and
  Pallas-backed kernels can bypass the eager wrapper entirely.

An op registry keyed by name mirrors phi::KernelFactory for introspection and the
OpTest harness.
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.tree_util as jtu

from . import autograd
from .tensor import Tensor

OP_REGISTRY: dict[str, Callable] = {}

# FLAGS_check_nan_inf (paddle_tpu.flags): per-op output scan, parity with
# framework/details/nan_inf_utils_detail.cc:341 CheckVarHasNanOrInf
CHECK_NAN_INF = False

# op-dispatch telemetry (paddle_tpu.observability): synced by
# observability.enable(); apply_op pays one boolean check per call when off
TELEMETRY = False


def _scan_nan_inf(name, out):
    import jax
    import jax.numpy as jnp

    vals = out if isinstance(out, (tuple, list)) else (out,)
    for i, v in enumerate(vals):
        if isinstance(v, jax.core.Tracer):
            # inside jit the scan can't branch on values; jax_debug_nans is
            # the in-jit counterpart (SURVEY §5.2)
            continue
        if hasattr(v, "dtype") and jnp.issubdtype(v.dtype, jnp.floating):
            # FLAGS_check_nan_inf debug scan: the sync IS the feature
            # (materialize to decide whether to crash), tracers skipped
            # above, and the whole scan is gated off the hot path
            bad = ~jnp.isfinite(v)
            if bool(bad.any()):  # tpu-lint: ok(trace-hygiene)
                n_nan = int(jnp.isnan(v).sum())  # tpu-lint: ok(trace-hygiene)
                n_inf = int(jnp.isinf(v).sum())  # tpu-lint: ok(trace-hygiene)
                # error path only (never per-op): the crash dump's flight
                # tail carries the op provenance of the first bad value
                from ..observability import flight
                flight.record("nan_inf", name, output=i, nan=n_nan,
                              inf=n_inf, shape=str(tuple(v.shape)))
                raise RuntimeError(
                    f"Operator {name} output {i} contains "
                    f"{n_nan} NaN and {n_inf} Inf values "
                    f"(FLAGS_check_nan_inf is set)")


def _is_tensor(x):
    return isinstance(x, Tensor)


def _wrap_outputs(out, node):
    """Wrap raw op results back into Tensors, attaching grad-node slots."""
    if isinstance(out, tuple) and hasattr(out, "_fields"):
        out = tuple(out)  # namedtuple results (jnp.linalg.svd/qr/...)
    stop = node is None

    def wrap(slot, val):
        t = Tensor(val, stop_gradient=stop, _internal=True)
        if node is not None:
            t._grad_node = node
            t._grad_slot = slot
        return t

    if isinstance(out, (tuple, list)):
        wrapped = type(out)(
            wrap(i, v) if not isinstance(v, (tuple, list)) else
            type(v)(wrap(i, u) for u in v)  # ragged outputs unsupported for grad
            for i, v in enumerate(out)
        )
        return wrapped
    return wrap(0, out)


def apply_op(fn, name, args, kwargs):
    if not TELEMETRY:
        return _apply_op(fn, name, args, kwargs)
    import time as _time

    from ..observability import dispatch as _dispatch
    t0 = _time.perf_counter()
    try:
        return _apply_op(fn, name, args, kwargs)
    finally:
        _dispatch.record(name, _time.perf_counter() - t0)


def _apply_op(fn, name, args, kwargs):
    leaves, treedef = jtu.tree_flatten((args, kwargs), is_leaf=_is_tensor)
    # dual-mode dispatch (reference tensor APIs append ops in static
    # mode): a static-graph Variable anywhere defers this op onto the
    # Program's DAG instead of executing eagerly (static/graph.py)
    if any(type(lv).__name__ == "Variable"
           and getattr(lv, "kind", None) in ("feed", "op", "param", "const")
           for lv in leaves):
        from ..static import graph as _sgraph
        gpos = [i for i, lv in enumerate(leaves)
                if isinstance(lv, _sgraph.Variable)]

        def deferred(*tensors):
            lv2 = list(leaves)
            for i, t in zip(gpos, tensors):
                lv2[i] = t
            a2, k2 = jtu.tree_unflatten(treedef, lv2)
            return apply_op(fn, name, a2, k2)

        return _sgraph.op_var(name, deferred, [leaves[i] for i in gpos])
    tensor_pos = [i for i, l in enumerate(leaves) if _is_tensor(l)]
    raw = list(leaves)
    for i in tensor_pos:
        raw[i] = leaves[i]._value

    # AMP autocast at the op boundary (≈ eager_amp_auto_cast.h in the reference).
    # The cast happens INSIDE the traced computation (see closure below) so the
    # VJP sees original-dtype primals and backward cotangents keep their dtypes.
    from ..amp.auto_cast import amp_state, should_cast
    mode = should_cast(name)
    if mode is None:
        amp_cast = None
    else:
        import jax.numpy as jnp
        low = amp_state().dtype

        def amp_cast(v):
            if mode == "low" and v.dtype == jnp.float32:
                return v.astype(low)
            if mode == "high" and v.dtype in (jnp.float16, jnp.bfloat16):
                return v.astype(jnp.float32)
            return v

    grad_on = autograd.is_grad_enabled()
    diff_pos = [i for i in tensor_pos if grad_on and not leaves[i].stop_gradient]

    if not diff_pos:
        vals = raw if amp_cast is None else \
            [amp_cast(v) if i in tensor_pos else v for i, v in enumerate(raw)]
        a, k = jtu.tree_unflatten(treedef, vals)
        out = fn(*a, **k)
        if CHECK_NAN_INF:
            _scan_nan_inf(name, out)
        return _wrap_outputs(out, None)

    def closure(*dvals):
        vals = list(raw)
        for p, dv in zip(diff_pos, dvals):
            vals[p] = dv
        if amp_cast is not None:
            for i in tensor_pos:
                vals[i] = amp_cast(vals[i])
        a, k = jtu.tree_unflatten(treedef, vals)
        out = fn(*a, **k)
        # normalize: multi-result primitive binds return lists, linalg ops
        # return namedtuples; backward sends tuple cotangents and jax.vjp
        # requires matching tree types
        if isinstance(out, list) or (isinstance(out, tuple) and
                                     hasattr(out, "_fields")):
            out = tuple(out)
        return out

    primals = [raw[p] for p in diff_pos]
    out, vjp_fn = jax.vjp(closure, *primals)

    outs_flat = list(out) if isinstance(out, (tuple, list)) else [out]
    avals = [(v.shape, v.dtype) for v in outs_flat]
    node = autograd.GradNode(
        vjp_fn, [leaves[p] for p in diff_pos], len(outs_flat), avals,
        name=name, closure=closure)
    if CHECK_NAN_INF:
        _scan_nan_inf(name, out)
    return _wrap_outputs(out, node)


def defop(fn=None, *, name=None, tensor_method=None):
    """Declare a framework op from a raw jnp function.

    tensor_method: name (or list of names) to also install as Tensor method(s).
    """
    if fn is None:
        return functools.partial(defop, name=name, tensor_method=tensor_method)

    op_name = name or fn.__name__

    @functools.wraps(fn)
    def op(*args, **kwargs):
        return apply_op(fn, op_name, args, kwargs)

    op.raw = fn
    op.op_name = op_name
    OP_REGISTRY[op_name] = op

    if tensor_method:
        names = tensor_method if isinstance(tensor_method, (list, tuple)) else [tensor_method]
        for m in names:
            setattr(Tensor, m, op)
    return op


def register_tensor_method(name):
    """Install an already-built callable as a Tensor method."""
    def deco(f):
        setattr(Tensor, name, f)
        return f
    return deco
