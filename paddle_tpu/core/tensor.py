"""Tensor — the user-facing array type.

Wraps an immutable ``jax.Array`` and adds the reference Tensor's eager semantics
(paddle/phi/core/dense_tensor.h + pybind eager_method.cc): ``stop_gradient``
(default True, like the reference), ``.grad`` accumulation, ``backward()``,
in-place-looking mutation by value rebinding, ``state``ful naming, and numpy
interop.  Compute never lives here — ops come from ``paddle_tpu.ops`` via the
``defop`` machinery; under ``jit`` the same methods trace straight into XLA.
"""
from __future__ import annotations

import itertools
from typing import Any

import numpy as np
import jax
import jax.numpy as jnp

from . import dtype as dtype_mod
from . import place as place_mod

_name_counter = itertools.count()


class Tensor:
    __slots__ = ("_value", "_grad", "_grad_node", "_grad_slot", "stop_gradient",
                 "name", "persistable", "_partition_spec", "_process_mesh",
                 "__weakref__")

    def __init__(self, data: Any = None, dtype=None, place=None,
                 stop_gradient: bool = True, name: str | None = None,
                 _internal: bool = False):
        if _internal:
            value = data
        elif isinstance(data, jax.ShapeDtypeStruct):
            # abstract (meta) construction: the tensor carries shape/dtype
            # only — used by nn.abstract_build for AOT capacity planning
            value = data if dtype is None else \
                jax.ShapeDtypeStruct(data.shape, dtype_mod.to_jax(dtype))
        else:
            if isinstance(data, Tensor):
                value = data._value
            elif isinstance(data, (jax.Array, jnp.ndarray)):
                value = data
            else:
                arr = np.asarray(data)
                if (dtype is None and arr.dtype == np.float64
                        and not isinstance(data, (np.ndarray, np.generic))):
                    # Python floats / float lists default to the global default
                    # dtype (float32), matching paddle.to_tensor semantics;
                    # explicit numpy float64 arrays keep their dtype.
                    arr = arr.astype(dtype_mod.get_default_dtype())
                value = jnp.asarray(arr)
            if dtype is not None:
                value = value.astype(dtype_mod.to_jax(dtype))
            if place is not None and isinstance(place, place_mod.Place):
                value = jax.device_put(value, place.jax_device())
        self._value = value
        self._grad = None
        self._grad_node = None
        self._grad_slot = 0
        self.stop_gradient = stop_gradient
        self.persistable = False
        # GSPMD placement tag (jax.sharding.PartitionSpec) — the analog of the
        # reference's TensorDistAttr (distributed/auto_parallel/dist_attr.h)
        self._partition_spec = None
        self.name = name or f"generated_tensor_{next(_name_counter)}"

    # -- core properties ----------------------------------------------------
    @property
    def value(self):
        return self._value

    @property
    def shape(self) -> list[int]:
        return list(self._value.shape)

    @property
    def ndim(self) -> int:
        return self._value.ndim

    @property
    def dtype(self):
        return np.dtype(self._value.dtype)

    @property
    def size(self) -> int:
        import math
        v = self._value
        return int(v.size) if hasattr(v, "size") else \
            math.prod(v.shape)

    @property
    def place(self) -> place_mod.Place:
        try:
            dev = list(self._value.devices())[0]
            if dev.platform == "cpu":
                return place_mod.CPUPlace()
            return place_mod.Place("accelerator", dev.id)
        except Exception:  # tracer — no concrete device
            return place_mod._get_current_place()

    @property
    def is_leaf(self) -> bool:
        return self._grad_node is None

    @property
    def grad(self):
        return self._grad

    @grad.setter
    def grad(self, g):
        self._grad = g if (g is None or isinstance(g, Tensor)) else Tensor(g)

    @property
    def T(self):
        return Tensor(self._value.T, stop_gradient=True, _internal=True) \
            if self.stop_gradient and self._grad_node is None else self.t()

    # -- autograd -----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph: bool = False):
        from . import autograd
        autograd.backward([self], [grad_tensor], retain_graph=retain_graph)

    def clear_grad(self):
        self._grad = None

    clear_gradient = clear_grad

    def detach(self) -> "Tensor":
        t = Tensor(self._value, stop_gradient=True, _internal=True)
        t.name = self.name + ".detach"
        return t

    def detach_(self) -> "Tensor":
        self._grad_node = None
        self.stop_gradient = True
        return self

    def _replace_(self, new_value, node=None, slot=0):
        """In-place mutation primitive: rebind value (+ graph edge)."""
        self._value = new_value
        if node is not None or self._grad_node is not None:
            self._grad_node = node
            self._grad_slot = slot
        return self

    def _snapshot(self) -> "Tensor":
        """Pre-mutation view sharing value and graph edge — recorded as the
        *input* of in-place ops so the grad graph stays acyclic."""
        t = Tensor(self._value, stop_gradient=self.stop_gradient, _internal=True)
        t._grad_node = self._grad_node
        t._grad_slot = self._grad_slot
        return t

    # -- conversion ---------------------------------------------------------
    def numpy(self) -> np.ndarray:
        return np.asarray(self._value)

    def item(self, *args):
        return self._value.item(*args)

    def tolist(self):
        return np.asarray(self._value).tolist()

    def numel(self) -> int:
        return int(self._value.size)

    def element_size(self) -> int:
        return self.dtype.itemsize

    def astype(self, dtype) -> "Tensor":
        from .op import apply_op
        return apply_op(lambda x: x.astype(dtype_mod.to_jax(dtype)), "cast",
                        (self,), {})

    cast = astype

    def clone(self) -> "Tensor":
        from .op import apply_op
        return apply_op(lambda x: x + 0, "clone", (self,), {})

    def cpu(self) -> "Tensor":
        return Tensor(jax.device_put(self._value, jax.devices("cpu")[0]),
                      stop_gradient=self.stop_gradient, _internal=True)

    def to(self, target=None, dtype=None, blocking=None) -> "Tensor":
        t = self
        if isinstance(target, str) and target not in dtype_mod._ALIASES:
            name, _, idx = target.partition(":")
            dev = place_mod.Place(name, int(idx or 0))
            t = Tensor(jax.device_put(t._value, dev.jax_device()),
                       stop_gradient=t.stop_gradient, _internal=True)
        elif isinstance(target, place_mod.Place):
            t = Tensor(jax.device_put(t._value, target.jax_device()),
                       stop_gradient=t.stop_gradient, _internal=True)
        elif target is not None and dtype is None:
            dtype = target
        if dtype is not None:
            t = t.astype(dtype)
        return t

    def pin_memory(self):
        return self.cpu()

    # -- python protocol ----------------------------------------------------
    def __jax_array__(self):
        return self._value

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def _scalar(self):
        # paddle permits python-scalar conversion of any single-element tensor
        return self._value.reshape(()) if self._value.ndim else self._value

    def __bool__(self):
        return bool(self._scalar())

    def __int__(self):
        return int(self._scalar())

    def __float__(self):
        return float(self._scalar())

    def __index__(self):
        return int(self._scalar())

    def __format__(self, spec):
        if self.ndim == 0:
            # formatting a scalar for display is a host sync by contract
            return format(self.item(), spec)  # tpu-lint: ok(trace-hygiene)
        return format(str(self), spec)

    def __getitem__(self, idx):
        from .op import apply_op
        idx = tuple(idx) if isinstance(idx, (tuple, list)) else (idx,)
        idx = tuple(i._value if isinstance(i, Tensor) else i for i in idx)
        return apply_op(lambda x: x[idx], "getitem", (self,), {})

    def __setitem__(self, idx, val):
        from .op import apply_op
        idx = tuple(idx) if isinstance(idx, (tuple, list)) else (idx,)
        idx = tuple(i._value if isinstance(i, Tensor) else i for i in idx)
        out = apply_op(lambda x, v: x.at[idx].set(v), "setitem",
                       (self._snapshot(), val if isinstance(val, Tensor) else
                        Tensor(val, dtype=self.dtype)), {})
        self._replace_(out._value, out._grad_node, out._grad_slot)
        self.stop_gradient = out.stop_gradient and self.stop_gradient

    def __repr__(self):
        try:
            body = np.array2string(np.asarray(self._value), precision=8,
                                   separator=", ")
        except Exception:
            body = f"<traced {self._value}>"
        return (f"Tensor(shape={self.shape}, dtype={dtype_mod.dtype_name(self.dtype)}, "
                f"place={self.place}, stop_gradient={self.stop_gradient},\n"
                f"       {body})")

    __str__ = __repr__

    def __hash__(self):
        return id(self)

    # NB: __eq__ is element-wise (installed by ops.logic); hash stays identity
    # like the reference's Tensor.


def to_tensor(data, dtype=None, place=None, stop_gradient=True) -> Tensor:
    """paddle.to_tensor parity (python/paddle/tensor/creation.py)."""
    if isinstance(data, Tensor) and dtype is None and place is None:
        t = Tensor(data._value, stop_gradient=stop_gradient, _internal=True)
        return t
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)
