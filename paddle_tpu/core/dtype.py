"""Dtype system.

Mirrors the reference's dtype enumeration (paddle/phi/common/data_type.h) as thin
aliases onto JAX/numpy dtypes.  A paddle dtype is represented as a canonical
``numpy.dtype`` instance so equality/hashing work the way user code expects
(``t.dtype == paddle.float32``).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import ml_dtypes

# Canonical dtype objects, in the order of phi::DataType.
bool_ = np.dtype(np.bool_)
uint8 = np.dtype(np.uint8)
int8 = np.dtype(np.int8)
int16 = np.dtype(np.int16)
int32 = np.dtype(np.int32)
int64 = np.dtype(np.int64)
float16 = np.dtype(np.float16)
bfloat16 = np.dtype(ml_dtypes.bfloat16)
float32 = np.dtype(np.float32)
float64 = np.dtype(np.float64)
complex64 = np.dtype(np.complex64)
complex128 = np.dtype(np.complex128)

_ALIASES = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "fp16": float16,
    "half": float16,
    "bfloat16": bfloat16,
    "bf16": bfloat16,
    "float32": float32,
    "fp32": float32,
    "float": float32,
    "float64": float64,
    "fp64": float64,
    "double": float64,
    "complex64": complex64,
    "complex128": complex128,
}

_FLOATING = {float16, bfloat16, float32, float64}
_INTEGRAL = {uint8, int8, int16, int32, int64}


def convert_dtype(dtype) -> np.dtype:
    """Normalise any user-supplied dtype spec into a canonical numpy dtype."""
    if dtype is None:
        raise TypeError("dtype must not be None")
    if isinstance(dtype, str):
        key = dtype.lower()
        if key.startswith("paddle."):
            key = key[len("paddle."):]
        if key not in _ALIASES:
            raise TypeError(f"unsupported dtype string {dtype!r}")
        return _ALIASES[key]
    return np.dtype(dtype)


def dtype_name(dtype) -> str:
    d = convert_dtype(dtype)
    if d == bool_:
        return "bool"
    return d.name


def is_floating_point(dtype) -> bool:
    return convert_dtype(dtype) in _FLOATING


def is_integer(dtype) -> bool:
    d = convert_dtype(dtype)
    return d in _INTEGRAL or d == bool_


_default_dtype = float32


def set_default_dtype(dtype):
    """paddle.set_default_dtype — affects float tensor creation defaults."""
    global _default_dtype
    d = convert_dtype(dtype)
    if d not in (float16, bfloat16, float32, float64):
        raise TypeError(f"default dtype must be floating, got {d}")
    _default_dtype = d


def get_default_dtype() -> np.dtype:
    return _default_dtype


def to_jax(dtype):
    """Canonical dtype → dtype usable by jnp."""
    return jnp.dtype(convert_dtype(dtype))


def promote_types(a, b):
    return np.promote_types(convert_dtype(a), convert_dtype(b))
