"""paddle.tensor — the tensor-function namespace (reference:
python/paddle/tensor/__init__.py re-exports math/creation/manipulation/
linalg/logic/random/search/stat/attribute/einsum submodules).

Here the implementations live in paddle_tpu.ops; this package provides
the reference's import paths (`import paddle.tensor as T; T.math.add`,
`from paddle.tensor.creation import arange`) over the same functions.
"""
from .. import ops as _ops
from ..ops import creation, linalg, logic, manipulation, search  # noqa: F401
from ..ops import math  # noqa: F401
from ..ops import random_ops as random  # noqa: F401
from ..ops import reduction as stat  # noqa: F401

# every public tensor function is importable from paddle.tensor directly,
# like the reference's flat re-export
from ..ops.creation import *      # noqa: F401,F403
from ..ops.linalg import *        # noqa: F401,F403
from ..ops.logic import *         # noqa: F401,F403
from ..ops.manipulation import *  # noqa: F401,F403
from ..ops.math import *          # noqa: F401,F403
from ..ops.random_ops import *    # noqa: F401,F403
from ..ops.reduction import *     # noqa: F401,F403
from ..ops.search import *        # noqa: F401,F403
from ..ops.extended import *      # noqa: F401,F403
from ..ops.linalg import einsum   # noqa: F401
