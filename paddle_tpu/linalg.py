"""paddle.linalg namespace parity (python/paddle/linalg.py re-exports the
tensor linalg op family)."""
from .ops.linalg import (  # noqa: F401
    bincount,
    bmm,
    cholesky,
    cholesky_solve,
    corrcoef,
    cov,
    cross,
    det,
    dist,
    dot,
    eig,
    eigh,
    eigvals,
    eigvalsh,
    histogram,
    inverse,
    lstsq,
    matmul,
    matrix_power,
    matrix_rank,
    multi_dot,
    mv,
    norm,
    pinv,
    qr,
    slogdet,
    solve,
    svd,
    triangular_solve,
)

inv = inverse
from .ops.extended import lu, lu_unpack  # noqa: E402,F401
from .ops.linalg import cond  # noqa: E402,F401
