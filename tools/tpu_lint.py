#!/usr/bin/env python
"""tpu-lint CLI — static TPU-hazard analysis with a ratchet baseline.

    python tools/tpu_lint.py paddle_tpu/ --baseline tools/tpu_lint_baseline.json

Thin wrapper over :mod:`paddle_tpu.analysis` that loads the analysis
package *standalone* (it is stdlib-only and uses intra-package relative
imports exclusively), so linting never imports paddle_tpu or jax — the
gate runs in milliseconds and works even when the runtime deps are
broken, which is exactly when you want CI signal.
"""
from __future__ import annotations

import importlib.util
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_analysis():
    """Import paddle_tpu/analysis as the standalone package `_tpu_lint`
    (dodges paddle_tpu/__init__.py and its jax import)."""
    if "paddle_tpu" in sys.modules:  # already imported (tests): use it
        import paddle_tpu.analysis as analysis
        return analysis
    pkg_dir = os.path.join(_ROOT, "paddle_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        "_tpu_lint", os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_tpu_lint"] = mod
    spec.loader.exec_module(mod)
    return mod


def main(argv=None) -> int:
    analysis = _load_analysis()
    cli = __import__(analysis.__name__ + ".cli",
                     fromlist=["main"])
    return cli.main(argv)


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
