"""Trace-driven load generator — diurnal + flash-crowd + heavy-tail.

A Poisson-constant-QPS sweep (the PR 8 bench shape) never exercises the
autoscaler: real traffic has a daily swing, step-function flash crowds,
and heavy-tailed prompt/output lengths whose long requests pin slots
long after the arrival burst has passed.  This module generates exactly
that, seeded and deterministic:

* **arrivals** — a nonhomogeneous Poisson process (thinning): a
  sinusoidal diurnal swing (``base_qps * (1 + diurnal_amp * sin)``)
  with a step-function flash-crowd window pinning the rate to
  ``flash_mult * base_qps`` for ``flash_duration_s`` starting at
  ``flash_at`` of the trace — Black Friday in miniature.
* **lengths** — lognormal prompt and output token counts (heavy tail:
  p99/p50 of several x), clipped to the serving window.

The SAME trace drives both consumers:

* :class:`paddle_tpu.serving.FleetSim` — virtual-time closed-loop
  simulation (tier-1-testable policy evaluation, the bench
  ``autoscale`` block's attainment-vs-replica-seconds curves);
* this file's CLI — real HTTP load against a gateway::

      python tools/load_gen.py --url http://127.0.0.1:PORT \
          --duration 30 --qps 4 --flash-mult 6 --seed 0

  replays the trace wall-clock (one thread per in-flight request,
  bounded), then prints a JSON summary (completed/shed/error counts,
  client-measured TTFT percentiles, achieved QPS).
"""
from __future__ import annotations

import argparse
import http.client
import json
import math
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

__all__ = ["make_trace", "make_conversation_trace", "replay_http"]


def make_trace(duration_s: float = 60.0, base_qps: float = 4.0,
               seed: int = 0, *,
               diurnal_period_s: float | None = None,
               diurnal_amp: float = 0.4,
               flash_at: float = 0.5, flash_mult: float = 6.0,
               flash_duration_s: float = 8.0,
               prompt_mean: float = 16.0, prompt_sigma: float = 0.8,
               out_mean: float = 12.0, out_sigma: float = 0.7,
               prompt_max: int = 512, out_max: int = 256,
               deadline_s: float | None = None,
               adapters: list | None = None,
               adapter_skew: float = 0.8) -> list:
    """Seeded trace: [{"t", "prompt_len", "max_tokens"[, "deadline_s"]}].

    ``diurnal_period_s`` defaults to the trace duration (one full day's
    swing per trace); ``flash_at`` is the flash crowd's start as a
    fraction of the duration.  Lengths are lognormal around the given
    means — the p99 request is many times the p50, so a handful of
    requests dominate slot occupancy exactly like production.

    With ``adapters`` (LoRA adapter names), each entry carries a
    ``model`` field: the FIRST adapter gets ``adapter_skew`` of the
    traffic, the rest split the remainder uniformly — the skewed
    multi-adapter shape the router's locality tiebreak serves
    (``replay_http`` forwards ``model`` on the wire).
    """
    if duration_s <= 0 or base_qps <= 0:
        raise ValueError("duration_s and base_qps must be positive")
    rs = np.random.RandomState(seed)
    period = float(diurnal_period_s or duration_s)
    flash_t0 = flash_at * duration_s
    flash_t1 = flash_t0 + flash_duration_s

    def rate(t: float) -> float:
        # the flash crowd is a STEP to flash_mult x base — it overrides
        # the diurnal swing rather than compounding with it, so a
        # caller controls the overload depth exactly
        if flash_t0 <= t < flash_t1:
            return max(base_qps * flash_mult, 1e-6)
        return max(base_qps * (1.0 + diurnal_amp *
                               math.sin(2.0 * math.pi * t / period)), 1e-6)

    rate_max = base_qps * (1.0 + abs(diurnal_amp)) * max(1.0, flash_mult)
    trace = []
    t = 0.0
    while True:
        t += float(rs.exponential(1.0 / rate_max))
        if t >= duration_s:
            break
        if rs.uniform() * rate_max > rate(t):
            continue                     # thinned
        prompt_len = int(np.clip(
            rs.lognormal(math.log(prompt_mean), prompt_sigma), 1,
            prompt_max))
        max_tokens = int(np.clip(
            rs.lognormal(math.log(out_mean), out_sigma), 1, out_max))
        entry = {"t": round(t, 4), "prompt_len": prompt_len,
                 "max_tokens": max_tokens}
        if deadline_s is not None:
            entry["deadline_s"] = float(deadline_s)
        if adapters:
            if len(adapters) == 1 or rs.uniform() < adapter_skew:
                entry["model"] = adapters[0]
            else:
                entry["model"] = adapters[
                    1 + int(rs.randint(len(adapters) - 1))]
        trace.append(entry)
    return trace


def make_conversation_trace(duration_s: float = 60.0,
                            base_qps: float = 1.0, seed: int = 0, *,
                            turns_mean: float = 3.0, turns_max: int = 12,
                            think_mean_s: float = 2.0,
                            think_sigma: float = 0.6,
                            first_turn_mean: float = 24.0,
                            turn_mean: float = 8.0,
                            turn_sigma: float = 0.6,
                            out_mean: float = 8.0, out_sigma: float = 0.5,
                            prompt_max: int = 512, out_max: int = 128,
                            vocab: int = 1000,
                            deadline_s: float | None = None) -> list:
    """Seeded MULTI-TURN trace: conversations arrive Poisson at
    ``base_qps``; each runs a geometric number of turns (mean
    ``turns_mean``, capped ``turns_max``) separated by lognormal think
    times — the warm-turn shape the KV tier serves (docs/serving.md
    "KV tiering & conversations").

    Every entry carries explicit ``prompt`` token ids and a
    ``conversation`` id, and turn N+1's prompt EXTENDS turn N's — the
    user turn plus a seeded stand-in for the assistant reply are
    appended to the running history — so the prefix property that makes
    warm turns cheap holds by construction and the whole trace is
    reproducible from ``seed``.  Entries are ``replay_http``- and
    FleetSim-compatible (the superset schema: ``t``, ``prompt``,
    ``prompt_len``, ``max_tokens``, ``conversation``, ``turn``).  A
    conversation whose history would outgrow ``prompt_max`` simply
    ends early (the serving window is the real budget too).
    """
    if duration_s <= 0 or base_qps <= 0:
        raise ValueError("duration_s and base_qps must be positive")
    if turns_mean < 1.0:
        raise ValueError("turns_mean must be >= 1")
    rs = np.random.RandomState(seed)
    entries = []
    t = 0.0
    cidx = 0
    while True:
        t += float(rs.exponential(1.0 / base_qps))
        if t >= duration_s:
            break
        cidx += 1
        cid = f"conv-{seed}-{cidx}"
        n_turns = int(np.clip(rs.geometric(1.0 / turns_mean),
                              1, turns_max))
        first_len = int(np.clip(
            rs.lognormal(math.log(first_turn_mean), turn_sigma), 1,
            prompt_max))
        history = [int(x) for x in rs.randint(1, vocab, first_len)]
        tt = t
        for turn in range(n_turns):
            max_tokens = int(np.clip(
                rs.lognormal(math.log(out_mean), out_sigma), 1, out_max))
            if len(history) + max_tokens > prompt_max:
                break
            entry = {"t": round(tt, 4), "prompt": list(history),
                     "prompt_len": len(history),
                     "max_tokens": max_tokens,
                     "conversation": cid, "turn": turn}
            if deadline_s is not None:
                entry["deadline_s"] = float(deadline_s)
            entries.append(entry)
            # the stand-in reply + the next user turn extend the history
            # (a real client appends the ACTUAL reply; the stand-in
            # keeps the trace seed-reproducible — the shared prefix is
            # the previous PROMPT either way)
            reply = [int(x) for x in rs.randint(1, vocab, max_tokens)]
            user_len = int(np.clip(
                rs.lognormal(math.log(turn_mean), turn_sigma), 1,
                prompt_max))
            user = [int(x) for x in rs.randint(1, vocab, user_len)]
            history = history + reply + user
            tt += float(rs.lognormal(math.log(think_mean_s), think_sigma))
    entries.sort(key=lambda e: (e["t"], e["conversation"], e["turn"]))
    return entries


def replay_http(url: str, trace, *, vocab: int = 1000, seed: int = 0,
                tenant: str = "load", timeout_s: float = 600.0,
                max_in_flight: int = 256, speed: float = 1.0,
                collect_tokens: bool = False) -> dict:
    """Replay a trace against a live gateway, wall-clock-faithful: each
    entry fires at its ``t`` offset (late dispatch is recorded, never
    skipped).  Returns the client-side summary.

    Accepts both schemas: plain :func:`make_trace` output (synthetic
    prompts are drawn from ``seed``/``vocab``, one ``tenant`` for the
    whole run) AND the traffic-capture superset — per-entry ``prompt``
    (exact token ids, full-mode capture), ``tenant``, ``priority``,
    ``model``, ``temperature``/``top_k``/``seed``, so a captured window
    replays with its original attribution and sampling.  ``speed``
    compresses the inter-arrival clock (2.0 = twice as fast);
    ``collect_tokens`` adds per-request ``results`` (trace order, with
    the returned token ids) for determinism checks.
    """
    from urllib.parse import urlparse
    u = urlparse(url)
    host, port = u.hostname, u.port
    if speed <= 0:
        raise ValueError("speed must be positive")
    rs = np.random.RandomState(seed)
    # synthetic prompts draw from ONE stream in trace order, so a legacy
    # make_trace replay keeps its exact historical prompt sequence; a
    # captured entry's own ids always win
    prompts = [e.get("prompt")
               or [int(x) for x in rs.randint(1, vocab, e["prompt_len"])]
               for e in trace]
    out, lock = [], threading.Lock()
    gate = threading.Semaphore(max_in_flight)

    def one(i, entry, prompt):
        try:
            payload = {"prompt": prompt, "max_tokens": entry["max_tokens"]}
            if entry.get("deadline_s") is not None:
                payload["deadline_ms"] = int(entry["deadline_s"] * 1e3)
            for k in ("temperature", "top_k", "seed", "model", "priority",
                      "conversation"):
                if entry.get(k) is not None:
                    payload[k] = entry[k]
            conn = http.client.HTTPConnection(host, port,
                                              timeout=timeout_s)
            t0 = time.perf_counter()
            try:
                conn.request(
                    "POST", "/v1/completions", json.dumps(payload).encode(),
                    {"Content-Type": "application/json",
                     "X-Tenant": entry.get("tenant") or tenant})
                r = conn.getresponse()
                body = r.read()
                ttft = time.perf_counter() - t0   # blocking: full wall
                toks = (json.loads(body)["choices"][0]["token_ids"]
                        if r.status == 200 else [])
                rec = {"i": i, "status": r.status, "wall_s": ttft,
                       "tokens": len(toks)}
                if collect_tokens:
                    rec["token_ids"] = [int(x) for x in toks]
                with lock:
                    out.append(rec)
            finally:
                conn.close()
        except Exception as e:  # noqa: BLE001 — count as a failed sample
            with lock:
                out.append({"i": i, "status": -1, "wall_s": None,
                            "tokens": 0,
                            "error": f"{type(e).__name__}: {e}"})
        finally:
            gate.release()

    threads = []
    t_start = time.perf_counter()
    for i, (entry, prompt) in enumerate(zip(trace, prompts)):
        delay = entry["t"] / speed - (time.perf_counter() - t_start)
        if delay > 0:
            time.sleep(delay)
        gate.acquire()
        th = threading.Thread(target=one, args=(i, entry, prompt))
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=timeout_s)
    wall = time.perf_counter() - t_start
    walls = sorted(o["wall_s"] for o in out
                   if o["status"] == 200 and o["wall_s"] is not None)
    completed = sum(1 for o in out if o["status"] == 200)
    shed = sum(1 for o in out if o["status"] == 429)
    errors = [o for o in out if o["status"] not in (200, 429)]
    pct = (lambda q: round(float(np.percentile(walls, q)) * 1e3, 1)
           if walls else None)
    summary = {
        "requests": len(trace), "completed": completed, "shed": shed,
        "errors": len(errors),
        "achieved_qps": round(completed / wall, 2) if wall else 0.0,
        "tokens": sum(o["tokens"] for o in out),
        "wall_ms": {"p50": pct(50), "p99": pct(99)},
        "duration_s": round(wall, 2), "speed": speed,
    }
    if collect_tokens:
        summary["results"] = sorted(out, key=lambda o: o["i"])
    return summary


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url", required=True,
                    help="gateway base URL (http://host:port)")
    ap.add_argument("--duration", type=float, default=30.0)
    ap.add_argument("--qps", type=float, default=4.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--diurnal-amp", type=float, default=0.4)
    ap.add_argument("--flash-at", type=float, default=0.5)
    ap.add_argument("--flash-mult", type=float, default=6.0)
    ap.add_argument("--flash-duration", type=float, default=8.0)
    ap.add_argument("--prompt-mean", type=float, default=16.0)
    ap.add_argument("--out-mean", type=float, default=12.0)
    ap.add_argument("--prompt-max", type=int, default=64)
    ap.add_argument("--out-max", type=int, default=32)
    ap.add_argument("--deadline-s", type=float, default=None)
    ap.add_argument("--adapters", default=None, metavar="A,B,...",
                    help="comma-separated LoRA adapter names: entries "
                    "carry model= with --adapter-skew of the traffic "
                    "on the first name")
    ap.add_argument("--adapter-skew", type=float, default=0.8)
    ap.add_argument("--tenant", default="load")
    ap.add_argument("--vocab", type=int, default=1000)
    ap.add_argument("--speed", type=float, default=1.0,
                    help="time-compression factor (2.0 = replay at 2x)")
    ap.add_argument("--trace", default=None, metavar="FILE",
                    help="replay a saved trace/capture JSON (a list of "
                    "entries, or a /debug/capture dump) instead of "
                    "generating one")
    ap.add_argument("--conversations", action="store_true",
                    help="generate a multi-turn conversation trace "
                    "(make_conversation_trace) instead of independent "
                    "arrivals — exercises /v1/chat-style prefix reuse "
                    "via the `conversation` field")
    ap.add_argument("--turns-mean", type=float, default=3.0,
                    help="mean turns per conversation (--conversations)")
    args = ap.parse_args()
    if args.trace:
        with open(args.trace, encoding="utf-8") as f:
            trace = json.load(f)
        if isinstance(trace, dict):      # a /debug/capture dump
            trace = trace.get("window", [])
        if trace:                        # rebase: first arrival fires now
            t0 = min(e["t"] for e in trace)
            trace = [dict(e, t=round(e["t"] - t0, 4))
                     for e in sorted(trace, key=lambda e: e["t"])]
        print(f"# trace: {len(trace)} arrivals from {args.trace}",
              file=sys.stderr)
    elif args.conversations:
        trace = make_conversation_trace(
            args.duration, args.qps, args.seed,
            turns_mean=args.turns_mean,
            first_turn_mean=args.prompt_mean, turn_mean=args.out_mean,
            out_mean=args.out_mean, prompt_max=args.prompt_max,
            out_max=args.out_max, vocab=args.vocab,
            deadline_s=args.deadline_s)
        n_conv = len({e["conversation"] for e in trace})
        print(f"# trace: {len(trace)} turns across {n_conv} "
              f"conversations over {args.duration}s", file=sys.stderr)
    else:
        trace = make_trace(
            args.duration, args.qps, args.seed,
            diurnal_amp=args.diurnal_amp, flash_at=args.flash_at,
            flash_mult=args.flash_mult,
            flash_duration_s=args.flash_duration,
            prompt_mean=args.prompt_mean, out_mean=args.out_mean,
            prompt_max=args.prompt_max, out_max=args.out_max,
            deadline_s=args.deadline_s,
            adapters=(args.adapters.split(",") if args.adapters
                      else None),
            adapter_skew=args.adapter_skew)
        print(f"# trace: {len(trace)} arrivals over {args.duration}s "
              f"(flash x{args.flash_mult} at {args.flash_at:.0%})",
              file=sys.stderr)
    summary = replay_http(args.url, trace, vocab=args.vocab,
                          seed=args.seed, tenant=args.tenant,
                          speed=args.speed)
    print(json.dumps(summary))
    return 0 if summary["errors"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
