"""Chaos smoke lane: kill-and-resume must be a WORKING path, end to end,
in real processes (ISSUE 5 satellite; same contract as telemetry_smoke.py —
the lane runs even when the pytest subset has pre-existing failures).

    python tools/chaos_smoke.py              # subset + chaos lane
    python tools/chaos_smoke.py tests/x.py   # explicit subset only

The lane runs three telemetry-on subprocesses over one checkpoint dir:

1. **ref** — an uninterrupted 2-epoch hapi fit; writes its per-batch loss
   series.
2. **interrupt** — the same fit, but the process SIGTERMs *itself*
   mid-epoch; the preemption hook converts the signal into an emergency
   checkpoint at the next step boundary and the fit stops cleanly.
3. **resume** — a fresh process runs ``fit(resume="auto")`` and finishes
   the run.

The parent asserts completion and that ``interrupt + resume`` losses are
bit-identical to ``ref`` — the acceptance criterion for preemption-safe
training on CPU.

A second, ELASTIC lane (ISSUE 6) runs the SPMD path across a topology
change: a dp=2 process (2 simulated CPU devices via XLA_FLAGS) SIGTERMs
itself mid-run, and a dp=1 process with a different device count resumes
the same checkpoint through the elastic restore path — pre-kill losses
bit-identical to the dp=2 reference, post-resume losses matching it to
tolerance, zero new jit signatures on the target mesh.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

DEFAULT_SUBSET = [
    "tests/test_robustness.py",
    "tests/test_checkpoint.py",
    "tests/test_elastic.py",
]

CHILD = r"""
import json
import os
import signal
import sys

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.hapi import Model
from paddle_tpu.hapi.callbacks import Callback, CheckpointCallback

mode, ckpt_dir, out_path = sys.argv[1], sys.argv[2], sys.argv[3]


class DS(paddle.io.Dataset):
    def __getitem__(self, i):
        rs = np.random.RandomState(i)
        return rs.randn(4).astype("float32"), rs.randn(2).astype("float32")

    def __len__(self):
        return 16


class Recorder(Callback):
    def __init__(self):
        super().__init__()
        self.losses = []

    def on_train_batch_end(self, step, logs=None):
        self.losses.append(float(logs["loss"]))


class SigtermSelf(Callback):
    # SIGTERM this process mid-epoch (batch 6 of 8 = epoch 1, step 1)

    def __init__(self, at=6):
        super().__init__()
        self.at = at
        self.n = 0

    def on_train_batch_begin(self, step, logs=None):
        self.n += 1
        if self.n == self.at:
            os.kill(os.getpid(), signal.SIGTERM)


paddle.seed(0)
net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
model = Model(net)
model.prepare(optimizer=paddle.optimizer.Adam(
    parameters=model.parameters(), learning_rate=1e-2), loss=nn.MSELoss())

rec = Recorder()
ckpt = CheckpointCallback(ckpt_dir, data_seed=5)
cbs = [rec, ckpt]
resume = None
if mode == "interrupt":
    cbs.append(SigtermSelf())
elif mode == "resume":
    resume = "auto"

model.fit(DS(), epochs=2, batch_size=4, verbose=0, shuffle=True,
          callbacks=cbs, resume=resume)

if mode == "interrupt":
    assert ckpt.preempted, "SIGTERM did not convert into a preemption"
    assert ckpt.saver.steps(), "no emergency checkpoint committed"
if mode == "resume":
    from paddle_tpu import observability as obs
    assert obs.enabled(), "PADDLE_TPU_TELEMETRY=1 must bootstrap telemetry"

with open(out_path, "w") as f:
    json.dump(rec.losses, f)
print(f"chaos child [{mode}]: {len(rec.losses)} batches", file=sys.stderr)
"""


CHILD_MESH = r"""
import json
import os
import signal
import sys
import time

import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.distributed as dist
from paddle_tpu.framework import preemption
from paddle_tpu.framework.checkpoint import AsyncCheckpointSaver

mode, ckpt_dir, out_path = sys.argv[1], sys.argv[2], sys.argv[3]
dp = int(os.environ.get("CHAOS_MESH_DP", "1"))

paddle.seed(0)
net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
opt = paddle.optimizer.Adam(parameters=net.parameters(), learning_rate=1e-2)
mesh = dist.build_mesh([dp], ["dp"]) if dp > 1 else None
step = dist.make_train_step(net, opt, loss_fn=nn.MSELoss(), mesh=mesh)
saver = AsyncCheckpointSaver(ckpt_dir)
step.attach_saver(saver)

rs = np.random.RandomState(0)
batches = [(rs.randn(4, 4).astype("float32"),
            rs.randn(4, 2).astype("float32")) for _ in range(8)]

start = 0
if mode == "mesh-resume":
    # elastic restore: the checkpoint was written on a DIFFERENT mesh
    st, snap = saver.restore_latest_valid()
    assert snap is not None, "no checkpoint to resume from"
    step.load_state_dict(snap)
    start = step.optimizer._step_count

losses = []
with preemption.guard():
    for i in range(start, len(batches)):
        if mode == "mesh-interrupt" and i == 4:
            os.kill(os.getpid(), signal.SIGTERM)  # a REAL preemption
            for _ in range(400):
                if preemption.requested():
                    break
                time.sleep(0.005)
            assert preemption.requested(), "SIGTERM was not converted"
        try:
            losses.append(float(step(*batches[i])))
        except preemption.TrainingPreempted:
            break

if mode == "mesh-interrupt":
    assert saver.steps(), "no emergency checkpoint committed"
if mode == "mesh-resume":
    from paddle_tpu import observability as obs
    assert obs.enabled(), "PADDLE_TPU_TELEMETRY=1 must bootstrap telemetry"
    assert len(step._jitted._signatures) == 1, "elastic resume retraced"

with open(out_path, "w") as f:
    json.dump({"losses": losses, "start": start, "dp": dp}, f)
print(f"chaos mesh child [{mode} dp={dp}]: steps {start}..."
      f"{start + len(losses) - 1}", file=sys.stderr)
"""


def _run_child(mode: str, ckpt_dir: str, out: str, env, root) -> int:
    src = CHILD_MESH if mode.startswith("mesh-") else CHILD
    return subprocess.call(
        [sys.executable, "-c", src, mode, ckpt_dir, out],
        env=env, cwd=root)


def chaos_lane(env, root) -> int:
    with tempfile.TemporaryDirectory() as tmp:
        ref, p1, p2 = (os.path.join(tmp, n) for n in
                       ("ref.json", "part1.json", "part2.json"))
        if _run_child("ref", os.path.join(tmp, "ck_ref"), ref, env, root):
            print("chaos lane: ref run FAILED", file=sys.stderr)
            return 1
        ck = os.path.join(tmp, "ck")
        if _run_child("interrupt", ck, p1, env, root):
            print("chaos lane: interrupted run FAILED", file=sys.stderr)
            return 1
        if _run_child("resume", ck, p2, env, root):
            print("chaos lane: resume run FAILED", file=sys.stderr)
            return 1
        losses_ref = json.load(open(ref))
        losses_got = json.load(open(p1)) + json.load(open(p2))
        if losses_got != losses_ref:
            print("chaos lane: PARITY BROKE —\n"
                  f"  ref    = {losses_ref}\n"
                  f"  resume = {losses_got}", file=sys.stderr)
            return 1
        print(f"chaos lane ok: {len(json.load(open(p1)))} batches before "
              f"SIGTERM + {len(json.load(open(p2)))} after resume == "
              f"{len(losses_ref)} uninterrupted, bit-identical",
              file=sys.stderr)
        return 0


def _mesh_env(env, dp: int):
    """Child env simulating a dp-sized CPU mesh (elastic lane: each child
    gets its OWN device count, so mesh A and mesh B are real topologies in
    real processes)."""
    e = dict(env)
    flags = e.get("XLA_FLAGS", "")
    flags = " ".join(f for f in flags.split()
                     if "xla_force_host_platform_device_count" not in f)
    e["XLA_FLAGS"] = (flags +
                      f" --xla_force_host_platform_device_count={max(dp, 1)}"
                      ).strip()
    e["CHAOS_MESH_DP"] = str(dp)
    return e


def mesh_lane(env, root) -> int:
    """Elastic mesh-A -> mesh-B lane (ISSUE 6): train on dp=2, SIGTERM
    the process, resume the SAME checkpoint on dp=1 in a fresh process
    with a different device count.  Asserts the pre-kill prefix is
    bit-identical to the uninterrupted dp=2 reference and the post-resume
    tail matches it to tolerance (cross-dp reduction order differs by
    ~1 ulp on CPU — the relayout itself is byte-lossless, which
    tests/test_elastic.py asserts bitwise)."""
    with tempfile.TemporaryDirectory() as tmp:
        ref, p1, p2 = (os.path.join(tmp, n) for n in
                       ("mref.json", "mpart1.json", "mpart2.json"))
        if _run_child("mesh-ref", os.path.join(tmp, "ck_ref"), ref,
                      _mesh_env(env, 2), root):
            print("mesh lane: dp=2 reference run FAILED", file=sys.stderr)
            return 1
        ck = os.path.join(tmp, "ck")
        if _run_child("mesh-interrupt", ck, p1, _mesh_env(env, 2), root):
            print("mesh lane: interrupted dp=2 run FAILED", file=sys.stderr)
            return 1
        if _run_child("mesh-resume", ck, p2, _mesh_env(env, 1), root):
            print("mesh lane: dp=1 elastic resume FAILED", file=sys.stderr)
            return 1
        r, a, b = (json.load(open(p)) for p in (ref, p1, p2))
        losses_ref, pre, post = r["losses"], a["losses"], b["losses"]
        # the interrupted step's own loss is consumed by TrainingPreempted,
        # so the series is ref[:4] + (one trained-but-unreported step) +
        # the resumed tail
        ok = (pre == losses_ref[:len(pre)] and
              b["start"] == len(pre) + 1 and
              len(pre) + 1 + len(post) == len(losses_ref))
        import math
        tail_ref = losses_ref[b["start"]:]
        ok = ok and all(math.isclose(x, y, rel_tol=1e-4, abs_tol=1e-6)
                        for x, y in zip(post, tail_ref))
        if not ok:
            print("mesh lane: ELASTIC PARITY BROKE —\n"
                  f"  ref(dp2)        = {losses_ref}\n"
                  f"  pre-kill(dp2)   = {pre}\n"
                  f"  resumed(dp1)    = {post}", file=sys.stderr)
            return 1
        print(f"mesh lane ok: {len(pre)} dp=2 steps bit-identical, SIGTERM, "
              f"{len(post)} dp=1 steps after elastic resume match the dp=2 "
              "reference", file=sys.stderr)
        return 0


def main() -> int:
    explicit = bool(sys.argv[1:])
    targets = sys.argv[1:] or DEFAULT_SUBSET
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "PADDLE_TPU_TELEMETRY": "1"})
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cmd = [sys.executable, "-m", "pytest", "-q", "-m", "not slow",
           "-p", "no:cacheprovider", *targets]
    print("chaos smoke subset:", " ".join(cmd), file=sys.stderr)
    rc = subprocess.call(cmd, env=env, cwd=root)
    if not explicit:
        print("chaos smoke: SIGTERM/resume lane", file=sys.stderr)
        lane_rc = chaos_lane(env, root)
        if lane_rc != 0:
            print("chaos lane FAILED", file=sys.stderr)
        rc = rc or lane_rc
        print("chaos smoke: elastic mesh-A->mesh-B lane", file=sys.stderr)
        mesh_rc = mesh_lane(env, root)
        if mesh_rc != 0:
            print("mesh lane FAILED", file=sys.stderr)
        rc = rc or mesh_rc
    return rc


if __name__ == "__main__":
    sys.exit(main())
