"""Chaos smoke lane: kill-and-resume must be a WORKING path, end to end,
in real processes (ISSUE 5 satellite; same contract as telemetry_smoke.py —
the lane runs even when the pytest subset has pre-existing failures).

    python tools/chaos_smoke.py              # subset + chaos lane
    python tools/chaos_smoke.py tests/x.py   # explicit subset only

The lane runs three telemetry-on subprocesses over one checkpoint dir:

1. **ref** — an uninterrupted 2-epoch hapi fit; writes its per-batch loss
   series.
2. **interrupt** — the same fit, but the process SIGTERMs *itself*
   mid-epoch; the preemption hook converts the signal into an emergency
   checkpoint at the next step boundary and the fit stops cleanly.
3. **resume** — a fresh process runs ``fit(resume="auto")`` and finishes
   the run.

The parent asserts completion and that ``interrupt + resume`` losses are
bit-identical to ``ref`` — the acceptance criterion for preemption-safe
training on CPU.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

DEFAULT_SUBSET = [
    "tests/test_robustness.py",
    "tests/test_checkpoint.py",
]

CHILD = r"""
import json
import os
import signal
import sys

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.hapi import Model
from paddle_tpu.hapi.callbacks import Callback, CheckpointCallback

mode, ckpt_dir, out_path = sys.argv[1], sys.argv[2], sys.argv[3]


class DS(paddle.io.Dataset):
    def __getitem__(self, i):
        rs = np.random.RandomState(i)
        return rs.randn(4).astype("float32"), rs.randn(2).astype("float32")

    def __len__(self):
        return 16


class Recorder(Callback):
    def __init__(self):
        super().__init__()
        self.losses = []

    def on_train_batch_end(self, step, logs=None):
        self.losses.append(float(logs["loss"]))


class SigtermSelf(Callback):
    # SIGTERM this process mid-epoch (batch 6 of 8 = epoch 1, step 1)

    def __init__(self, at=6):
        super().__init__()
        self.at = at
        self.n = 0

    def on_train_batch_begin(self, step, logs=None):
        self.n += 1
        if self.n == self.at:
            os.kill(os.getpid(), signal.SIGTERM)


paddle.seed(0)
net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
model = Model(net)
model.prepare(optimizer=paddle.optimizer.Adam(
    parameters=model.parameters(), learning_rate=1e-2), loss=nn.MSELoss())

rec = Recorder()
ckpt = CheckpointCallback(ckpt_dir, data_seed=5)
cbs = [rec, ckpt]
resume = None
if mode == "interrupt":
    cbs.append(SigtermSelf())
elif mode == "resume":
    resume = "auto"

model.fit(DS(), epochs=2, batch_size=4, verbose=0, shuffle=True,
          callbacks=cbs, resume=resume)

if mode == "interrupt":
    assert ckpt.preempted, "SIGTERM did not convert into a preemption"
    assert ckpt.saver.steps(), "no emergency checkpoint committed"
if mode == "resume":
    from paddle_tpu import observability as obs
    assert obs.enabled(), "PADDLE_TPU_TELEMETRY=1 must bootstrap telemetry"

with open(out_path, "w") as f:
    json.dump(rec.losses, f)
print(f"chaos child [{mode}]: {len(rec.losses)} batches", file=sys.stderr)
"""


def _run_child(mode: str, ckpt_dir: str, out: str, env, root) -> int:
    return subprocess.call(
        [sys.executable, "-c", CHILD, mode, ckpt_dir, out],
        env=env, cwd=root)


def chaos_lane(env, root) -> int:
    with tempfile.TemporaryDirectory() as tmp:
        ref, p1, p2 = (os.path.join(tmp, n) for n in
                       ("ref.json", "part1.json", "part2.json"))
        if _run_child("ref", os.path.join(tmp, "ck_ref"), ref, env, root):
            print("chaos lane: ref run FAILED", file=sys.stderr)
            return 1
        ck = os.path.join(tmp, "ck")
        if _run_child("interrupt", ck, p1, env, root):
            print("chaos lane: interrupted run FAILED", file=sys.stderr)
            return 1
        if _run_child("resume", ck, p2, env, root):
            print("chaos lane: resume run FAILED", file=sys.stderr)
            return 1
        losses_ref = json.load(open(ref))
        losses_got = json.load(open(p1)) + json.load(open(p2))
        if losses_got != losses_ref:
            print("chaos lane: PARITY BROKE —\n"
                  f"  ref    = {losses_ref}\n"
                  f"  resume = {losses_got}", file=sys.stderr)
            return 1
        print(f"chaos lane ok: {len(json.load(open(p1)))} batches before "
              f"SIGTERM + {len(json.load(open(p2)))} after resume == "
              f"{len(losses_ref)} uninterrupted, bit-identical",
              file=sys.stderr)
        return 0


def main() -> int:
    explicit = bool(sys.argv[1:])
    targets = sys.argv[1:] or DEFAULT_SUBSET
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "PADDLE_TPU_TELEMETRY": "1"})
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cmd = [sys.executable, "-m", "pytest", "-q", "-m", "not slow",
           "-p", "no:cacheprovider", *targets]
    print("chaos smoke subset:", " ".join(cmd), file=sys.stderr)
    rc = subprocess.call(cmd, env=env, cwd=root)
    if not explicit:
        print("chaos smoke: SIGTERM/resume lane", file=sys.stderr)
        lane_rc = chaos_lane(env, root)
        if lane_rc != 0:
            print("chaos lane FAILED", file=sys.stderr)
        rc = rc or lane_rc
    return rc


if __name__ == "__main__":
    sys.exit(main())
