"""Profile a BASELINE.md model's train step on the real chip and print a
per-op time breakdown from the xplane trace (the only timing source we
trust through the remote-dispatch tunnel — see docs/PERF.md).

Usage: python tools/profile_model.py [resnet|gpt|bert] [--steps N]
"""
from __future__ import annotations

import collections
import glob
import os
import re
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _build_resnet(batch=64, size=224, data_format="NCHW"):
    import jax.numpy as jnp

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    import paddle_tpu.nn as nn
    from paddle_tpu.vision.models import resnet50

    paddle.seed(0)
    with nn.channels_last(data_format == "NHWC"):
        model = resnet50(num_classes=1000)
    crit = nn.CrossEntropyLoss()
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=model.parameters(),
                                    weight_decay=1e-4)
    step = dist.make_train_step(model, opt, loss_fn=crit,
                                compute_dtype="bfloat16")
    rng = np.random.RandomState(0)
    shape = (batch, 3, size, size) if data_format == "NCHW" \
        else (batch, size, size, 3)
    x = jnp.asarray(rng.standard_normal(shape).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 1000, (batch,)).astype(np.int64))
    return step, (x, y)


def _build_gpt(batch=16, seq=1024):
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.models import (GPTPretrainingCriterion, build_gpt,
                                   gpt_config)

    cfg = gpt_config("gpt2-small-en", max_position_embeddings=1024,
                     hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    paddle.seed(0)
    model = build_gpt(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 weight_decay=0.01)
    step = dist.make_train_step(model, opt,
                                loss_fn=GPTPretrainingCriterion(),
                                compute_dtype="bfloat16")
    ids = np.random.RandomState(0).randint(
        0, cfg.vocab_size, size=(batch, seq + 1)).astype(np.int64)
    return step, (ids[:, :-1], ids[:, 1:])


def _build_bert(batch=16, seq=512):
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.models import (BertPretrainingCriterion, bert_config,
                                   build_bert)

    cfg = bert_config("bert-base-uncased", hidden_dropout_prob=0.0,
                      attention_dropout_prob=0.0)
    paddle.seed(0)
    model = build_bert(cfg)
    crit = BertPretrainingCriterion()

    def loss_fn(out, labels, nsp_labels):
        mlm, nsp = out
        return crit(mlm, nsp, labels, nsp_labels)

    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    step = dist.make_train_step(model, opt, loss_fn=loss_fn, num_labels=2,
                                compute_dtype="bfloat16")
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int64)
    labels = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int64)
    nsp = rng.randint(0, 2, (batch,)).astype(np.int64)
    return step, (ids, labels, nsp)


def _build_ppyoloe(batch=8, size=640):
    import jax.numpy as jnp

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.vision.models import PPYOLOE, PPYOLOELoss

    paddle.seed(0)
    model = PPYOLOE(num_classes=80)
    loss_fn = PPYOLOELoss(model)
    opt = paddle.optimizer.Momentum(learning_rate=0.01, momentum=0.9,
                                    parameters=model.parameters(),
                                    weight_decay=5e-4)
    step = dist.make_train_step(model, opt, loss_fn=loss_fn, num_labels=2,
                                compute_dtype="bfloat16")
    rng = np.random.RandomState(0)
    x = jnp.asarray(
        rng.standard_normal((batch, 3, size, size)).astype(np.float32))
    gtb = jnp.asarray(np.stack([np.array([[4, 4, 300, 300],
                                          [64, 32, 400, 500]],
                                         "float32")] * batch))
    gtl = jnp.asarray(np.stack([np.array([1, 3], "int64")] * batch))
    return step, (x, gtb, gtl)


def profile(step, args, steps=5, outdir=None):
    import jax

    loss = step(*args)
    float(loss)  # compile + settle
    outdir = outdir or tempfile.mkdtemp(prefix="xprof_")
    with jax.profiler.trace(outdir):
        for _ in range(steps):
            loss = step(*args)
        float(loss)
    return outdir


def report(outdir, steps, top=40):
    import jax

    paths = glob.glob(os.path.join(outdir, "**", "*.xplane.pb"),
                      recursive=True)
    assert paths, f"no xplane under {outdir}"
    data = jax.profiler.ProfileData.from_file(paths[-1])
    plane = None
    for p in data.planes:
        if "TPU" in p.name or "/device" in p.name.lower():
            plane = p
            break
    assert plane is not None, [p.name for p in data.planes]
    # ONLY the sync "XLA Ops" line is the device critical path; the
    # "Async XLA Ops" line overlaps compute (copy-start DMA engines)
    op_total = collections.Counter()
    op_count = collections.Counter()
    total = async_total = 0.0
    for line in plane.lines:
        if line.name == "Async XLA Ops":
            async_total = sum(e.duration_ns for e in line.events) / 1e6
        if line.name != "XLA Ops":
            continue
        for ev in line.events:
            dur = ev.duration_ns / 1e6
            op_total[ev.name] += dur
            op_count[ev.name] += 1
            total += dur
    print(f"device compute {total:.1f} ms over {steps} steps "
          f"-> {total / steps:.2f} ms/step "
          f"(async DMA engine-time {async_total / steps:.1f} ms/step)")
    groups = collections.Counter()
    for name, t in op_total.items():
        base = name.split(" = ")[0].lstrip("%")
        groups[re.sub(r"[.\d]+$", "", base)] += t
    print("\n-- grouped by op kind (ms/step) --")
    for name, t in groups.most_common(20):
        print(f"{t / steps:8.3f}  {name}")
    print("\n-- top single ops (ms/step) --")
    for name, t in op_total.most_common(12):
        print(f"{t / steps:8.3f}  {name[:140]}")
    return op_total


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "resnet"
    steps = 5
    if "--steps" in sys.argv:
        steps = int(sys.argv[sys.argv.index("--steps") + 1])
    fmt = "NHWC" if "--nhwc" in sys.argv else "NCHW"
    if which == "resnet":
        step, args = _build_resnet(data_format=fmt)
    elif which == "gpt":
        step, args = _build_gpt()
    elif which == "bert":
        step, args = _build_bert()
    elif which == "ppyoloe":
        step, args = _build_ppyoloe()
    else:
        raise SystemExit(f"unknown model {which}")
    t0 = time.perf_counter()
    outdir = profile(step, args, steps=steps)
    print(f"trace in {outdir} ({time.perf_counter() - t0:.1f}s wall)")
    report(outdir, steps)
