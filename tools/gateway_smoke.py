"""Gateway smoke lane: the multi-tenant HTTP front door end-to-end on the
CPU backend with telemetry forced ON (ISSUE 8 satellite; tier-1 runs the
pytest suite telemetry-off, so this lane keeps the gateway's metric and
flight wiring from silently rotting).

Boots a tiny-model engine + gateway on localhost and drives mixed-tenant
traffic — one greedy tenant flooding past its queue cap, one light
interactive tenant sending small sequential requests — then asserts:

* fair-share isolation: every light-tenant request completes with a
  bounded wall time while the greedy flood is in flight, and the greedy
  overflow is shed with 429s;
* telemetry: gateway counters/gauges/histograms are exported through
  /metrics (Prometheus text) and the flight recorder carries
  admit/dispatch/shed events;
* the continuous-batching invariant holds through the gateway (decode
  stays ONE compiled program);
* clean shutdown: server, gateway and engine tear down without leaving
  queued work or live slots.

    python tools/gateway_smoke.py

Exit code 0 on success; any failed invariant raises.
"""
from __future__ import annotations

import http.client
import json
import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PADDLE_TPU_TELEMETRY", "1")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _post(port, payload, tenant, timeout=600):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", "/v1/completions", json.dumps(payload).encode(),
                     {"Content-Type": "application/json",
                      "X-Tenant": tenant})
        r = conn.getresponse()
        return r.status, r.read()
    finally:
        conn.close()


def main() -> int:
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import observability as obs
    from paddle_tpu.models import build_gpt, gpt_config
    from paddle_tpu.observability import flight
    from paddle_tpu.serving import Engine
    from paddle_tpu.serving.gateway import TenantConfig, start_gateway
    from paddle_tpu.serving.gateway import gateway as gw_mod

    assert obs.enabled(), "telemetry must be ON for this lane"
    obs.registry().reset()

    cfg = gpt_config("gpt-tiny", max_position_embeddings=128,
                     hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    paddle.seed(0)
    model = build_gpt(cfg)
    model.eval()
    engine = Engine(model, max_slots=2, max_len=48, max_queue=8)
    tenants = [TenantConfig("greedy", priority="batch", max_queue=5),
               TenantConfig("light", priority="interactive", weight=4.0)]
    rs = np.random.RandomState(0)
    stack = start_gateway([engine], own_engines=True, tenants=tenants)
    try:
        port = stack.port
        greedy_status = []
        lock = threading.Lock()

        def greedy_one(i):
            st, _ = _post(port, {"prompt": [int(t) for t in
                                            rs.randint(1, cfg.vocab_size,
                                                       6)],
                                 "max_tokens": 10}, "greedy")
            with lock:
                greedy_status.append(st)

        flood = [threading.Thread(target=greedy_one, args=(i,))
                 for i in range(14)]
        for t in flood:
            t.start()
        time.sleep(0.2)

        light_wall = []
        for i in range(4):
            t0 = time.perf_counter()
            st, raw = _post(port, {"prompt": [7, 3, i + 1],
                                   "max_tokens": 2}, "light")
            light_wall.append(time.perf_counter() - t0)
            assert st == 200, (st, raw)
            body = json.loads(raw)
            assert len(body["choices"][0]["token_ids"]) == 2, body
        for t in flood:
            t.join(timeout=600)

        ok = greedy_status.count(200)
        shed = sum(1 for s in greedy_status if s == 429)
        assert ok + shed == 14, greedy_status
        assert shed >= 1, f"greedy overflow was never shed: {greedy_status}"
        assert ok >= 1, f"greedy starved outright: {greedy_status}"
        assert max(light_wall) < 60.0, light_wall

        # -- telemetry through the wire (/metrics) ---------------------------
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        conn.request("GET", "/metrics")
        text = conn.getresponse().read().decode()
        conn.close()
        for series in (gw_mod.GATEWAY_REQUESTS, gw_mod.GATEWAY_QUEUE_DEPTH,
                       gw_mod.GATEWAY_TTFT, gw_mod.GATEWAY_SHED,
                       "paddle_tpu_serving_ttft_seconds"):
            assert series in text, f"{series} missing from /metrics"
        # the dispatcher's reaper retires handles just after the HTTP
        # response is written; wait for it to settle before sampling
        reg = obs.registry()
        req_c = reg.get(gw_mod.GATEWAY_REQUESTS)

        def _completed():
            return sum(v for labels, v in req_c.series()
                       if labels.get("outcome") == "completed")

        deadline = time.perf_counter() + 10
        while _completed() < ok + 4 and time.perf_counter() < deadline:
            time.sleep(0.02)
        assert _completed() == ok + 4, (_completed(), ok, req_c.series())
        shed_c = reg.get(gw_mod.GATEWAY_SHED)
        assert shed_c is not None and shed_c.total() == shed, \
            (shed, shed_c.series() if shed_c else None)
        kinds = {e["name"] for e in flight.events("gateway")}
        assert {"admit", "dispatch", "shed"} <= kinds, kinds

        # -- continuous batching held through the gateway --------------------
        st = engine.stats()
        assert st["decode_compiles"] == 1, st
        assert st["active_slots"] == 0 and st["queue_depth"] == 0, st
        health = stack.gateway.healthz()
        assert health["alive"] and health["queued"] == 0, health
        summary = {"gateway_smoke": "ok", "greedy_ok": ok,
                   "greedy_shed": shed,
                   "light_wall_max_ms": round(max(light_wall) * 1e3, 1),
                   "tokens": int(st["tokens"]),
                   "decode_steps": int(st["decode_steps"])}
    finally:
        stack.close()

    # clean shutdown: a post-close request must fail at connect (the
    # listener is gone), the engine pool must be drained and stopped
    try:
        _post(stack.port, {"prompt": [1], "max_tokens": 1}, "x", timeout=2)
        raise AssertionError("server still accepting after close()")
    except (ConnectionError, OSError):
        pass
    assert not engine.health()["alive"], engine.health()
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
