"""Round-5 BERT frontier: batch sweep + bf16-state A/B (chip, wall-clock
like bench.py's metric)."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np


def build(batch, seq=512, bf16_state=False):
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.models import (BertPretrainingCriterion, bert_config,
                                   build_bert)

    cfg = bert_config("bert-base-uncased", hidden_dropout_prob=0.0,
                      attention_dropout_prob=0.0)
    paddle.seed(0)
    model = build_bert(cfg)
    if bf16_state:
        model.to(dtype="bfloat16")
    crit = BertPretrainingCriterion()

    def loss_fn(out, labels, nsp_labels):
        mlm, nsp = out
        return crit(mlm, nsp, labels, nsp_labels)

    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    step = dist.make_train_step(
        model, opt, loss_fn=loss_fn, num_labels=2,
        compute_dtype=None if bf16_state else "bfloat16")
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int64)
    labels = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int64)
    nsp = rng.randint(0, 2, (batch,)).astype(np.int64)
    return step, (ids, labels, nsp)


def run(tag, batch, bf16_state=False, steps=10):
    import jax
    step, args = build(batch, bf16_state=bf16_state)
    loss = step(*args)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(*args)
    lv = float(loss)
    dt = time.perf_counter() - t0
    tps = batch * 512 * steps / dt
    mfu = tps * 6 * 110e6 / 197e12
    print(f"{tag}: batch={batch} {tps:,.0f} tok/s mfu={mfu:.3f} "
          f"loss={lv:.4f}", flush=True)


if __name__ == "__main__":
    for a in sys.argv[1:]:
        if a.startswith("b"):
            run(a, int(a[1:]))
        elif a.startswith("s"):   # bf16 state
            run(a, int(a[1:]), bf16_state=True)
