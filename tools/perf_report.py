"""Render a running server's device-perfscope state as console tables.

Pulls ``GET /debug/perf`` (the per-program roofline) and
``GET /debug/memory`` (the HBM ownership ledger) from a live gateway —
or from saved JSON — and prints the text form: one row per compiled
program (dispatches, sampled device ms, estimated share of device time,
MFU, HBM-bandwidth fraction) and one row per HBM owner (bytes, share of
tracked, nested sub-accounts), with the backend allocator's
``bytes_in_use`` and the unattributed remainder when the platform
reports them.  The visual twin of ``tools/journey_report.py`` for the
device side of the house.

    python tools/perf_report.py --url http://127.0.0.1:8000
    python tools/perf_report.py --perf-json perf.json --memory-json mem.json

``--trace out.json`` additionally writes the IN-PROCESS perfscope
device lane (``cat: "device"`` chrome events — only meaningful when
samples were recorded in this process).

stdlib-only; no jax, no paddle_tpu import needed for the URL/file modes.
"""
from __future__ import annotations

import argparse
import json
import sys
import urllib.request

__all__ = ["format_perf", "format_memory", "fetch"]


def fetch(url: str, path: str, timeout: float = 30.0) -> dict:
    full = f"{url.rstrip('/')}{path}"
    with urllib.request.urlopen(full, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8"))


def _fmt_bytes(n) -> str:
    n = float(n or 0)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:,.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024
    return f"{n:,.1f} GiB"


def _pct(x) -> str:
    return "-" if x is None else f"{100 * x:6.2f}%"


def format_perf(rep: dict) -> list[str]:
    """``/debug/perf`` JSON -> console roofline table lines."""
    lines = [
        f"device perfscope  sample_every={rep.get('sample_every', 0)}  "
        f"peak={rep.get('peak_flops', 0) / 1e12:.1f} TFLOP/s  "
        f"hbm={rep.get('peak_hbm_bw', 0) / 1e9:.0f} GB/s",
        f"  {'program':<24} {'disp':>6} {'sampled':>7} "
        f"{'device_ms':>10} {'share':>7} {'MFU':>8} {'BW':>8}",
    ]
    for p in rep.get("programs", ()):
        lines.append(
            f"  {p['program']:<24} {p['dispatches']:>6} {p['sampled']:>7} "
            f"{1e3 * (p['device_s'] or 0.0):>10.2f} "
            f"{_pct(p.get('share')):>7} {_pct(p.get('mfu')):>8} "
            f"{_pct(p.get('hbm_bw_frac')):>8}")
    if len(lines) == 2:
        lines.append("  (no programs registered — is sampling on and "
                     "telemetry live?)")
    return lines


def format_memory(mem: dict) -> list[str]:
    """``/debug/memory`` JSON -> console ownership table lines."""
    owners = mem.get("owners", {})
    total = mem.get("total_tracked", 0) or 0
    lines = [f"hbm ledger  tracked={_fmt_bytes(total)}",
             f"  {'owner':<24} {'bytes':>14} {'share':>7}"]
    for owner, nb in sorted(owners.items(), key=lambda kv: -kv[1]):
        share = (nb / total) if total else None
        lines.append(f"  {owner:<24} {_fmt_bytes(nb):>14} "
                     f"{_pct(share):>7}")
    for owner, nb in sorted(mem.get("nested", {}).items()):
        lines.append(f"  {'+ ' + owner:<24} {_fmt_bytes(nb):>14} "
                     f"{'nested':>7}")
    backend = mem.get("backend") or {}
    if "bytes_in_use" in backend:
        lines.append(f"  {'backend bytes_in_use':<24} "
                     f"{_fmt_bytes(backend['bytes_in_use']):>14}")
        lines.append(f"  {'unattributed':<24} "
                     f"{_fmt_bytes(mem.get('unattributed', 0)):>14}")
    else:
        lines.append("  (backend reports no allocator stats on this "
                     "platform)")
    return lines


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--url", help="gateway base url, e.g. "
                     "http://127.0.0.1:8000 (reads /debug/perf + "
                     "/debug/memory)")
    src.add_argument("--perf-json", help="saved /debug/perf payload")
    ap.add_argument("--memory-json", help="saved /debug/memory payload "
                    "(with --perf-json)")
    ap.add_argument("--trace", help="also write the in-process perfscope "
                    "device lane as a chrome trace (imports paddle_tpu)")
    args = ap.parse_args(argv)

    if args.url:
        perf = fetch(args.url, "/debug/perf")
        mem = fetch(args.url, "/debug/memory")
    else:
        with open(args.perf_json) as f:
            perf = json.load(f)
        mem = None
        if args.memory_json:
            with open(args.memory_json) as f:
                mem = json.load(f)

    for line in format_perf(perf):
        print(line)
    if mem is not None:
        print()
        for line in format_memory(mem):
            print(line)
    if args.trace:
        from paddle_tpu.observability import perfscope
        events = perfscope.chrome_events()
        with open(args.trace, "w") as f:
            json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
        print(f"\n{len(events)} device-lane events -> {args.trace}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
