"""Fit a synthetic trace to captured gateway traffic.

Reads a traffic capture (a live gateway's ``/debug/capture``, a saved
dump, or a JSONL spill) and estimates the traffic model behind it —
windowed arrival-rate curve, flash window, lognormal prompt/output
length parameters, tenant mix — via ``capture.fit_params``.  With
``--out`` it also writes the ``capture.fit_trace`` synthetic trace,
which is ``make_trace``-compatible: feed it to
``paddle_tpu.serving.FleetSim`` for autoscale policy tuning on measured
traffic, or back through ``tools/load_gen.py --trace`` for live load.

    # print the fitted parameters of a gateway's recent traffic
    python tools/fit_capture.py --url http://127.0.0.1:PORT

    # fit a saved capture and emit a replayable synthetic trace
    python tools/fit_capture.py --file capture.jsonl \
        --out fitted_trace.json --seed 1
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from paddle_tpu.observability.capture import (  # noqa: E402
    fit_params, fit_trace)
from tools.replay_capture import fetch_capture, load_file  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--url", default=None,
                     help="gateway to pull the capture from")
    src.add_argument("--file", default=None,
                     help="saved capture dump / entry list / JSONL spill")
    ap.add_argument("--tenant", default=None,
                    help="fit only this tenant's entries")
    ap.add_argument("--bin-s", type=float, default=None,
                    help="rate-curve bin width (default: span/24)")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="also write a fitted synthetic trace here")
    ap.add_argument("--seed", type=int, default=0,
                    help="seed for the fitted trace's arrivals/lengths")
    args = ap.parse_args()
    entries = (load_file(args.file) if args.file
               else fetch_capture(args.url, tenant=args.tenant))
    if args.tenant:
        entries = [e for e in entries if e.get("tenant") == args.tenant]
    params = fit_params(entries, bin_s=args.bin_s)
    if args.out:
        trace = fit_trace(entries, seed=args.seed, params=params)
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(trace, f)
        print(f"# wrote {len(trace)} fitted arrivals to {args.out}",
              file=sys.stderr)
    print(json.dumps(params, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
