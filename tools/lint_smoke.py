#!/usr/bin/env python
"""Standalone tpu-lint gate lane (ISSUE 7 satellite).

    python tools/lint_smoke.py            # ratchet gate over paddle_tpu/
    python tools/lint_smoke.py --self     # + analyzer self-checks

Runs ``tools/tpu_lint.py paddle_tpu/ --baseline tools/tpu_lint_baseline
.json`` in its own interpreter so the gate fires even when pytest
subsets have unrelated failures (the same posture as telemetry_smoke /
chaos_smoke — which also invokes this lane).  ``--self`` additionally
proves the gate can still *fail*: a seeded host-sync violation in a
scratch file must flip the exit code, and the ratchet must refuse to
grow the baseline over it.
"""
from __future__ import annotations

import os
import subprocess
import sys
import tempfile

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_BASELINE = os.path.join("tools", "tpu_lint_baseline.json")

_SEED = '''\
import jax
import jax.numpy as jnp


@jax.jit
def seeded_bad_step(x):
    y = jnp.sum(x)
    return jax.device_get(y)
'''


def _lint(*paths, flags=()) -> int:
    cmd = [sys.executable, os.path.join("tools", "tpu_lint.py"),
           "paddle_tpu", *paths, "--baseline", _BASELINE, *flags]
    print("lint smoke:", " ".join(cmd), file=sys.stderr)
    return subprocess.call(cmd, cwd=_ROOT)


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    rc = _lint()
    if rc != 0:
        print("lint smoke: ratchet gate FAILED (new findings above)",
              file=sys.stderr)
        return rc
    if "--self" in argv:
        with tempfile.TemporaryDirectory() as tmp:
            bad = os.path.join(tmp, "seeded_violation.py")
            with open(bad, "w") as f:
                f.write(_SEED)
            if _lint(bad) != 1:
                print("lint smoke: seeded violation NOT caught",
                      file=sys.stderr)
                return 1
            if _lint(bad, flags=("--update-baseline",)) == 0:
                print("lint smoke: ratchet allowed the baseline to GROW",
                      file=sys.stderr)
                return 1
        print("lint smoke: self-checks ok (seeded violation caught, "
              "ratchet held)", file=sys.stderr)
    print("lint smoke: ok", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
