"""Per-op micro-benchmark harness.

Reference: paddle/fluid/operators/benchmark/op_tester.cc (runs one op
repeatedly, prints "Speed" lines) feeding the CI latency gate
tools/check_op_benchmark_result.py.

Usage:
    python tools/op_bench.py                    # all configs, JSON lines
    python tools/op_bench.py --ops matmul conv2d
    python tools/op_bench.py --output base.json
Each line: {"op": ..., "config": ..., "speed_us": ..., "device": ...}.
Compare two runs with tools/check_op_benchmark_result.py.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

# runnable as `python tools/op_bench.py` from the repo root: the script dir
# is tools/, so the package root must be put on the path explicitly
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _configs():
    """Configs hold tensor SHAPES, not tensors: arguments are materialized
    lazily per selected op (float64 host randn for the big vocab shapes
    alone would be multiple GB)."""
    import jax
    import jax.numpy as jnp

    cfgs = {}

    def add(op, config, fn, *shapes):
        cfgs[f"{op}/{config}"] = (op, config, fn, shapes)

    add("matmul", "4096x4096x4096",
        lambda a, b: a @ b, (4096, 4096), (4096, 4096))
    add("matmul", "batch16_1024x768x3072",
        lambda a, b: jnp.einsum("bsh,hf->bsf", a, b),
        (16, 1024, 768), (768, 3072))
    add("softmax", "16x1024x50304",
        lambda a: jax.nn.softmax(a, axis=-1), (16, 1024, 50304))
    add("layernorm", "16x1024x2048",
        lambda a: (a - a.mean(-1, keepdims=True))
        / jnp.sqrt(a.var(-1, keepdims=True) + 1e-5), (16, 1024, 2048))
    add("gelu", "16x1024x8192", jax.nn.gelu, (16, 1024, 8192))
    add("conv2d", "32x3x224x224_k7s2",
        lambda x, w: jax.lax.conv_general_dilated(
            x, w, (2, 2), "SAME", dimension_numbers=("NCHW", "OIHW", "NCHW")),
        (32, 3, 224, 224), (64, 3, 7, 7))
    add("reduce_sum", "16x1024x50304",
        lambda a: a.sum(), (16, 1024, 50304))

    def _flash(q):
        from paddle_tpu.kernels.flash_attention import flash_attention_bhtd
        return flash_attention_bhtd(q, q, q, causal=True)
    add("flash_attention", "192x1024x64", _flash, (192, 1024, 64))
    return cfgs


def _materialize(shapes):
    import jax.numpy as jnp

    r = np.random.RandomState(0)
    # float32 host draws: float64 at vocab-sized shapes is pointless bulk
    return tuple(jnp.asarray(r.standard_normal(s).astype(np.float32),
                             jnp.bfloat16) for s in shapes)


def bench_op(fn, args, iters: int = 20, warmup: int = 2) -> float:
    """Median-of-three timing of `iters` executions, us/call.

    The fence transfers ONE element sliced on-device: block_until_ready is
    not a reliable sync on remote-dispatch backends, and fetching the full
    output would time device-to-host bandwidth instead of the op.
    """
    import jax

    def _fence(out):
        leaf = jax.tree.leaves(out)[0]
        np.asarray(leaf.ravel()[0:1])

    jitted = jax.jit(fn)
    for _ in range(max(1, warmup)):
        out = jitted(*args)
    _fence(out)
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = jitted(*args)
        _fence(out)
        times.append((time.perf_counter() - t0) / iters)
    return float(np.median(times) * 1e6)


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--ops", nargs="*", default=None)
    parser.add_argument("--iters", type=int, default=20)
    parser.add_argument("--output", default=None)
    args = parser.parse_args(argv)

    import jax

    device = jax.devices()[0]
    results = []
    for key, (op, config, fn, shapes) in sorted(_configs().items()):
        if args.ops and op not in args.ops:
            continue
        try:
            tensors = _materialize(shapes)
            us = bench_op(fn, tensors, iters=args.iters)
            del tensors
            row = {"op": op, "config": config, "speed_us": round(us, 2),
                   "device": str(getattr(device, "device_kind", device))}
        except Exception as e:  # report, keep going (op_tester.cc contract)
            row = {"op": op, "config": config, "error": repr(e)[:200]}
        results.append(row)
        print(json.dumps(row), flush=True)
    if args.output:
        with open(args.output, "w") as f:
            json.dump(results, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
