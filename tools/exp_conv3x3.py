"""Stage-B gate: pallas direct 3x3 conv (NHWC, stride 1, SAME) with
BN-apply+relu prologue and BN-stats epilogue, vs XLA's conv on the same
work.  Decides whether the fused-bottleneck-block plan is viable.

Kernel: grid (Cout blocks, N blocks); x block = [bn, H, W, C] full
spatial; in-kernel zero-pad H/W by 1, then 9 shifted [bn*H*W, C] @
[C, bc] dots accumulate.
"""
from __future__ import annotations

import functools
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(x_ref, s_ref, b_ref, w_ref, o_ref, st_ref, *, H, W):
    j = pl.program_id(1)
    x = x_ref[...]  # [bn, H, W, C]
    bn, _, _, c = x.shape
    bc = w_ref.shape[3]
    sf = s_ref[...].astype(jnp.float32).reshape(1, 1, 1, c)
    bf = b_ref[...].astype(jnp.float32).reshape(1, 1, 1, c)
    xn = jnp.maximum(x.astype(jnp.float32) * sf + bf, 0).astype(x.dtype)
    xp = jnp.pad(xn, ((0, 0), (1, 1), (1, 1), (0, 0)))
    acc = jnp.zeros((bn * H * W, bc), jnp.float32)
    for di in range(3):
        for dj in range(3):
            xs = jax.lax.slice(xp, (0, di, dj, 0), (bn, di + H, dj + W, c))
            acc = acc + jax.lax.dot_general(
                xs.reshape(bn * H * W, c), w_ref[di, dj],
                (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    o_ref[...] = acc.reshape(bn, H, W, bc).astype(o_ref.dtype)
    ps = jnp.sum(acc, axis=0, keepdims=True)
    pq = jnp.sum(acc * acc, axis=0, keepdims=True)
    stat = jnp.concatenate([ps, pq], axis=0)

    @pl.when(j == 0)
    def _():
        st_ref[...] = stat

    @pl.when(j > 0)
    def _():
        st_ref[...] += stat


def fused3x3(x, s, b, w, bn_blk=8, bc=None):
    n, H, W, c = x.shape
    co = w.shape[3]
    bc = bc or co
    bn_blk = min(bn_blk, n)
    assert n % bn_blk == 0 and co % bc == 0
    y, st = pl.pallas_call(
        functools.partial(_kernel, H=H, W=W),
        grid=(co // bc, n // bn_blk),
        in_specs=[pl.BlockSpec((bn_blk, H, W, c), lambda i, j: (j, 0, 0, 0)),
                  pl.BlockSpec((1, c), lambda i, j: (0, 0)),
                  pl.BlockSpec((1, c), lambda i, j: (0, 0)),
                  pl.BlockSpec((3, 3, c, bc), lambda i, j: (0, 0, 0, i))],
        out_specs=[pl.BlockSpec((bn_blk, H, W, bc),
                                lambda i, j: (j, 0, 0, i)),
                   pl.BlockSpec((2, bc), lambda i, j: (0, i))],
        out_shape=[jax.ShapeDtypeStruct((n, H, W, co), x.dtype),
                   jax.ShapeDtypeStruct((2, co), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
            vmem_limit_bytes=110 * 1024 * 1024),
    )(x, s.reshape(1, -1), b.reshape(1, -1), w)
    return y, st


def xla_chain(x, s, b, w):
    xn = jnp.maximum(x.astype(jnp.float32) * s + b, 0).astype(x.dtype)
    y = jax.lax.conv_general_dilated(
        xn, w, (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    yf = y.astype(jnp.float32)
    return y, jnp.sum(yf, (0, 1, 2)), jnp.sum(yf * yf, (0, 1, 2))


def main():
    from exp_dtime import dtime

    r = np.random.RandomState(0)
    shapes = [(64, 56, 56, 64, 64), (64, 28, 28, 128, 128),
              (64, 14, 14, 256, 256), (64, 7, 7, 512, 512)]
    for n, H, W, c, co in shapes:
        x = jnp.asarray(r.standard_normal((n, H, W, c)).astype(np.float32),
                        jnp.bfloat16)
        s = jnp.asarray(r.standard_normal(c).astype(np.float32)) * .1 + 1
        b = jnp.asarray(r.standard_normal(c).astype(np.float32)) * .1
        w = jnp.asarray(r.standard_normal((3, 3, c, co)).astype(np.float32)
                        / np.sqrt(9 * c), jnp.bfloat16)
        yx, sx, qx = jax.jit(xla_chain)(x, s, b, w)
        t_x = dtime(xla_chain, (x, s, b, w))
        line = (f"N={n} {H}x{W} C={c}->{co}  xla={t_x:7.1f}us "
                f"(roofline {2 * 9 * n * H * W * c * co / 197e12 * 1e6:5.1f})")
        for bnb in (2, 4, 8, 16):
            if n % bnb:
                continue
            try:
                fn = functools.partial(fused3x3, bn_blk=bnb)
                yf, st = jax.jit(fn)(x, s, b, w)
                err = float(jnp.max(jnp.abs(yf.astype(jnp.float32)
                                            - yx.astype(jnp.float32))))
                t = dtime(fn, (x, s, b, w))
                line += f" | bn{bnb}:{t:7.1f} (err {err:.2g})"
            except Exception as e:
                line += f" | bn{bnb}:ERR({type(e).__name__})"
        print(line, flush=True)


if __name__ == "__main__":
    main()
