"""Render a window of request journeys as a chrome trace.

Pulls JSON timelines from a running gateway's debug surface (or a saved
JSON file) and writes a ``chrome://tracing`` / Perfetto-loadable trace
where every request is one track of phase blocks — the visual answer to
"where did this request's 480 ms go?".

    python tools/journey_report.py --url http://127.0.0.1:8000 --last 64 \
        -o /tmp/journeys.trace.json
    python tools/journey_report.py --json saved_requests.json -o out.json

The events use the SAME format and clock base as the PR 2 observability
spans (``"ph": "X"``, ``ts`` in perf_counter microseconds, ``"cat":
"journey"``), so a trace produced IN-PROCESS (``--merge-spans``, or the
profiler's ``export_chrome_tracing``) interleaves journeys with the
serving spans and counter tracks on one timeline.  Cross-process (the
``--url`` mode) the clock base still comes from each timeline's
``mono0`` field, so journeys from one gateway process stay mutually
aligned.

Also prints a per-phase attribution summary (total + share per phase
across the window) — the text form of the
``paddle_tpu_gateway_window_phase_share`` gauge.

stdlib-only; no jax, no paddle_tpu import needed for the URL/file modes.
"""
from __future__ import annotations

import argparse
import json
import sys
import urllib.request

__all__ = ["chrome_events_from_timelines", "summarize", "fetch_window"]


def chrome_events_from_timelines(timelines, pid: int = 0) -> list[dict]:
    """JSON journey timelines (the ``/debug/requests`` shape) -> chrome
    'X' events.  ``ts`` = (mono0 + offset) in microseconds — the
    perf_counter base the observability span ring also uses, so the two
    event streams merge onto one timeline in-process."""
    events = []
    for tl in timelines:
        base = float(tl.get("mono0") or 0.0) * 1e6
        tid = tl.get("id", "?")
        for seg in tl.get("phases", ()):
            args = dict(seg.get("attrs") or {})
            args["journey"] = tid
            if tl.get("outcome"):
                args["outcome"] = tl["outcome"]
            events.append({
                "name": seg["phase"], "ph": "X",
                "ts": base + float(seg["t_ms"]) * 1e3,
                "dur": float(seg["dur_ms"]) * 1e3,
                "pid": pid, "tid": tid, "cat": "journey", "args": args,
            })
    return events


def summarize(timelines) -> dict:
    """Per-phase attribution totals across a window of timelines:
    {phase: {"ms": total, "share": fraction-of-attributed-time}}."""
    totals: dict[str, float] = {}
    for tl in timelines:
        for seg in tl.get("phases", ()):
            totals[seg["phase"]] = totals.get(seg["phase"], 0.0) + \
                float(seg["dur_ms"])
    grand = sum(totals.values())
    return {name: {"ms": round(ms, 3),
                   "share": round(ms / grand, 4) if grand else 0.0}
            for name, ms in sorted(totals.items(),
                                   key=lambda kv: -kv[1])}


def fetch_window(url: str, last: int = 64, timeout: float = 30.0) -> list:
    """GET <url>/debug/requests?last=N -> list of JSON timelines."""
    full = f"{url.rstrip('/')}/debug/requests?last={int(last)}"
    with urllib.request.urlopen(full, timeout=timeout) as resp:
        payload = json.loads(resp.read().decode("utf-8"))
    return payload.get("requests", [])


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--url", help="gateway base url, e.g. "
                     "http://127.0.0.1:8000 (reads /debug/requests)")
    src.add_argument("--json", dest="json_path",
                     help="saved /debug/requests payload (or a bare list "
                     "of timelines)")
    ap.add_argument("--last", type=int, default=64,
                    help="window size for --url (default 64)")
    ap.add_argument("-o", "--out", default="journeys.trace.json",
                    help="chrome trace output path")
    ap.add_argument("--merge-spans", action="store_true",
                    help="also merge the IN-PROCESS observability span "
                    "ring into the trace (imports paddle_tpu; only "
                    "meaningful when journeys were recorded in this "
                    "process)")
    args = ap.parse_args(argv)

    if args.url:
        timelines = fetch_window(args.url, args.last)
    else:
        with open(args.json_path) as f:
            payload = json.load(f)
        timelines = (payload.get("requests", payload)
                     if isinstance(payload, dict) else payload)

    events = chrome_events_from_timelines(timelines)
    if args.merge_spans:
        from paddle_tpu.observability import trace as obs_trace
        events.extend(obs_trace.chrome_events())
    with open(args.out, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    summary = summarize(timelines)
    print(f"{len(timelines)} journeys, {len(events)} events -> {args.out}")
    for name, row in summary.items():
        print(f"  {name:<16} {row['ms']:>10.1f} ms  {row['share']:>6.1%}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
