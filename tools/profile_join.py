"""Join an xplane device profile with the step's optimized-HLO metadata so
each device op gets attributed to its SOURCE (model op + file:line), not
just its XLA fusion kind.

Usage: python tools/profile_join.py [resnet|gpt] [--steps N]
"""
from __future__ import annotations

import collections
import glob
import os
import re
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def hlo_meta(txt: str) -> dict:
    """instruction name -> (op_name, source:line) from optimized HLO text."""
    meta = {}
    for m in re.finditer(
            r"%(\S+?) = [^\n]*?metadata=\{op_name=\"([^\"]*)\""
            r"(?:[^\n]*?source_file=\"([^\"]*)\")?"
            r"(?:[^\n]*?source_line=(\d+))?", txt):
        name, op, f, line = m.groups()
        src = f"{os.path.basename(f)}:{line}" if f else ""
        meta[name] = (op, src)
    return meta


def run(which="resnet", steps=5, fmt="NCHW"):
    import jax
    import jax.numpy as jnp
    from profile_model import _build_resnet, _build_gpt, profile

    if which == "resnet":
        step, args = _build_resnet(batch=64, data_format=fmt)
    else:
        step, args = _build_gpt()
    batch = step.shard_batch(*args)
    if step._jitted is None:
        step._jitted = step._build(len(batch))
    core, slots = step._split_tree()
    lr = jnp.float32(0.1)
    txt = step._jitted.lower(core, slots, lr, batch).compile().as_text()
    meta = hlo_meta(txt)

    outdir = profile(step, args, steps=steps)
    paths = glob.glob(os.path.join(outdir, "**", "*.xplane.pb"),
                      recursive=True)
    data = jax.profiler.ProfileData.from_file(paths[-1])
    plane = next(p for p in data.planes
                 if "TPU" in p.name or "/device" in p.name.lower())
    groups = collections.Counter()
    examples = {}
    total = 0.0
    for line in plane.lines:
        if line.name != "XLA Ops":
            continue
        for ev in line.events:
            dur = ev.duration_ns / 1e6
            total += dur
            base = ev.name.split(" = ")[0].lstrip("%")
            op, src = meta.get(base, ("?", "?"))
            # collapse jit scopes/uniquifiers: keep the trailing primitive
            prim = op.split("/")[-1] if op != "?" else "?"
            scope = "bwd" if "transpose(jvp" in op else "fwd"
            key = (prim, scope, src)
            groups[key] += dur
            examples.setdefault(key, base)
    print(f"total device {total / steps:.2f} ms/step")
    print(f"{'ms/step':>8}  {'prim':40} {'pass':3}  source")
    for (prim, scope, src), t in groups.most_common(30):
        print(f"{t / steps:8.3f}  {prim[:40]:40} {scope:3}  {src}  "
              f"e.g. {examples[(prim, scope, src)][:40]}")


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "resnet"
    steps = 5
    if "--steps" in sys.argv:
        steps = int(sys.argv[sys.argv.index("--steps") + 1])
    fmt = "NHWC" if "--nhwc" in sys.argv else "NCHW"
    run(which, steps, fmt)
