"""Isolate which part of the fused conv+BN kernel is slow: pure pallas
matmul vs +prologue vs +stats epilogue, against XLA dot on the same shape."""
from __future__ import annotations

import functools
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from exp_conv_bn import _time, fused_conv1x1_bn, xla_chain


def _k_mm(x_ref, w_ref, o_ref):
    o_ref[...] = jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(o_ref.dtype)


def _k_pro(x_ref, s_ref, b_ref, w_ref, o_ref):
    x = x_ref[...].astype(jnp.float32)
    xn = jnp.maximum(x * s_ref[...].astype(jnp.float32)
                     + b_ref[...].astype(jnp.float32), 0).astype(x_ref.dtype)
    o_ref[...] = jax.lax.dot_general(
        xn, w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(o_ref.dtype)


def _k_stat(x_ref, w_ref, o_ref, st_ref):
    i = pl.program_id(1)
    y = jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    o_ref[...] = y.astype(o_ref.dtype)
    ps = jnp.sum(y, axis=0, keepdims=True)
    pq = jnp.sum(y * y, axis=0, keepdims=True)

    @pl.when(i == 0)
    def _init():
        st_ref[...] = jnp.concatenate([ps, pq], axis=0)

    @pl.when(i > 0)
    def _acc():
        st_ref[...] += jnp.concatenate([ps, pq], axis=0)


def run_mm(x2, w, bm=1024, bn=512, kern=_k_mm, nstat=False):
    m, k = x2.shape
    n = w.shape[1]
    bn = min(bn, n)
    bm = min(bm, m)
    assert m % bm == 0
    grid = (n // bn, m // bm)
    outs = [jax.ShapeDtypeStruct((m, n), x2.dtype)]
    out_specs = [pl.BlockSpec((bm, bn), lambda j, i: (i, j))]
    if nstat:
        outs.append(jax.ShapeDtypeStruct((2, n), jnp.float32))
        out_specs.append(pl.BlockSpec((2, bn), lambda j, i: (0, j)))
    r = pl.pallas_call(
        kern, grid=grid,
        in_specs=[pl.BlockSpec((bm, k), lambda j, i: (i, 0)),
                  pl.BlockSpec((k, bn), lambda j, i: (0, j))],
        out_specs=out_specs if nstat else out_specs[0],
        out_shape=outs if nstat else outs[0],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
            vmem_limit_bytes=100 * 1024 * 1024),
    )(x2, w)
    return r


def run_pro(x2, s, b, w, bm=1024, bn=512):
    m, k = x2.shape
    n = w.shape[1]
    bn = min(bn, n)
    bm = min(bm, m)
    return pl.pallas_call(
        _k_pro, grid=(n // bn, m // bm),
        in_specs=[pl.BlockSpec((bm, k), lambda j, i: (i, 0)),
                  pl.BlockSpec((1, k), lambda j, i: (0, 0)),
                  pl.BlockSpec((1, k), lambda j, i: (0, 0)),
                  pl.BlockSpec((k, bn), lambda j, i: (0, j))],
        out_specs=pl.BlockSpec((bm, bn), lambda j, i: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), x2.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
            vmem_limit_bytes=100 * 1024 * 1024),
    )(x2, s.reshape(1, -1), b.reshape(1, -1), w)


def main():
    shapes = [(50176, 512, 128), (12544, 1024, 256), (200704, 64, 256)]
    rng = np.random.RandomState(0)
    for m, k, n in shapes:
        m = -(-m // 1024) * 1024  # pad-free for this experiment
        x2 = jnp.asarray(rng.standard_normal((m, k)).astype(np.float32),
                         jnp.bfloat16)
        s = jnp.asarray(rng.standard_normal(k).astype(np.float32)) * .1 + 1
        b = jnp.asarray(rng.standard_normal(k).astype(np.float32)) * .1
        w = jnp.asarray(rng.standard_normal((k, n)).astype(np.float32) /
                        np.sqrt(k), jnp.bfloat16)
        t_xla_mm = _time(lambda a, c: jax.lax.dot_general(
            a, c, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(jnp.bfloat16),
            (x2, w), perturb=1)
        t_mm = _time(run_mm, (x2, w), perturb=1)
        t_pro = _time(run_pro, (x2, s, b, w), perturb=1)
        t_stat = _time(functools.partial(run_mm, kern=_k_stat, nstat=True),
                       (x2, w), perturb=1)
        t_full = _time(fused_conv1x1_bn, (x2, s, b, w), perturb=1)
        t_chain = _time(xla_chain, (x2, s, b, w), perturb=1)
        print(f"M={m:7d} K={k:4d} N={n:4d}  xla_mm={t_xla_mm:7.1f} "
              f"pl_mm={t_mm:7.1f} pl_pro={t_pro:7.1f} pl_stat={t_stat:7.1f} "
              f"pl_full={t_full:7.1f} xla_chain={t_chain:7.1f} (us)")


if __name__ == "__main__":
    main()
