"""Device-time micro harness: xplane-based per-call device compute time.

The only trustworthy timing through the remote-dispatch tunnel
(docs/PERF.md): wall clocks see ~2 ms dispatch/fetch noise, scan-chained
bodies risk DCE/hoisting.  Here each call is dispatched normally and the
sync "XLA Ops" line of the device trace is summed.
"""
from __future__ import annotations

import collections
import glob
import os
import tempfile

import jax


def dtime(fn, args, iters=20, warmup=2):
    """Median-free total-device-time/iters in us for jitted fn(*args)."""
    jitted = jax.jit(fn)
    out = None
    for _ in range(warmup):
        out = jitted(*args)
    jax.tree_util.tree_leaves(out)[0].block_until_ready()
    import numpy as np
    np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[0:1])
    outdir = tempfile.mkdtemp(prefix="dtime_")
    with jax.profiler.trace(outdir):
        for _ in range(iters):
            out = jitted(*args)
        np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[0:1])
    return device_total_us(outdir) / iters


def device_total_us(outdir):
    paths = glob.glob(os.path.join(outdir, "**", "*.xplane.pb"),
                      recursive=True)
    assert paths, f"no xplane under {outdir}"
    data = jax.profiler.ProfileData.from_file(paths[-1])
    plane = None
    for p in data.planes:
        if "TPU" in p.name or "/device" in p.name.lower():
            plane = p
            break
    assert plane is not None, [p.name for p in data.planes]
    total = 0.0
    for line in plane.lines:
        if line.name != "XLA Ops":
            continue
        for ev in line.events:
            total += ev.duration_ns / 1e3
    return total


def dtime_ops(fn, args, iters=20, warmup=2, top=15):
    """Like dtime but also returns per-op-group device us/iter."""
    import re
    jitted = jax.jit(fn)
    out = None
    for _ in range(warmup):
        out = jitted(*args)
    import numpy as np
    np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[0:1])
    outdir = tempfile.mkdtemp(prefix="dtime_")
    with jax.profiler.trace(outdir):
        for _ in range(iters):
            out = jitted(*args)
        np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[0:1])
    paths = glob.glob(os.path.join(outdir, "**", "*.xplane.pb"),
                      recursive=True)
    data = jax.profiler.ProfileData.from_file(paths[-1])
    plane = next(p for p in data.planes
                 if "TPU" in p.name or "/device" in p.name.lower())
    groups = collections.Counter()
    total = 0.0
    for line in plane.lines:
        if line.name != "XLA Ops":
            continue
        for ev in line.events:
            base = ev.name.split(" = ")[0].lstrip("%")
            groups[re.sub(r"[.\d]+$", "", base)] += ev.duration_ns / 1e3
            total += ev.duration_ns / 1e3
    per = {k: v / iters for k, v in groups.most_common(top)}
    return total / iters, per
