"""Per-op latency regression gate.

Reference: tools/check_op_benchmark_result.py (parses "Speed" logs from
the op benchmark, fails CI when an op slows down beyond a relative
threshold).

Usage:
    python tools/op_bench.py --output base.json     # on the baseline tree
    python tools/op_bench.py --output head.json     # on the change
    python tools/check_op_benchmark_result.py base.json head.json \
        --threshold 0.15
Exit 0 = no regression beyond threshold; exit 1 lists offenders.
"""
from __future__ import annotations

import argparse
import json
import sys


def load(path: str) -> dict:
    with open(path) as f:
        rows = json.load(f)
    return {f"{r['op']}/{r['config']}": r for r in rows}


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("baseline")
    parser.add_argument("head")
    parser.add_argument("--threshold", type=float, default=0.15,
                        help="max allowed relative slowdown (0.15 = +15%)")
    args = parser.parse_args(argv)

    base, head = load(args.baseline), load(args.head)
    failures = []
    for key, b in sorted(base.items()):
        h = head.get(key)
        if h is None:
            failures.append(f"{key}: missing from head run")
            continue
        if "error" in h and "error" not in b:
            failures.append(f"{key}: now errors: {h['error']}")
            continue
        if "speed_us" not in b or "speed_us" not in h:
            continue
        rel = (h["speed_us"] - b["speed_us"]) / max(b["speed_us"], 1e-9)
        status = "OK" if rel <= args.threshold else "REGRESSED"
        print(f"[{status}] {key}: {b['speed_us']:.1f}us -> "
              f"{h['speed_us']:.1f}us ({rel * 100:+.1f}%)")
        if rel > args.threshold:
            failures.append(
                f"{key}: {rel * 100:+.1f}% (> {args.threshold * 100:.0f}%)")
    if failures:
        print("\nFAILED:", file=sys.stderr)
        for f in failures:
            print(f"  {f}", file=sys.stderr)
        return 1
    print(f"\nall {len(base)} ops within +{args.threshold * 100:.0f}%")
    return 0


if __name__ == "__main__":
    sys.exit(main())
