"""Serving smoke lane: the continuous-batching engine end-to-end on the
CPU backend with telemetry forced ON, asserting that every request
completes AND the observability counters are sane (ISSUE 3 satellite; the
tier-1 gate runs the pytest suite telemetry-off, so this lane is what
keeps the serving telemetry wiring from silently rotting).

    python tools/serving_smoke.py           # quick lane: tiny model,
                                            # 8 concurrent requests
    python tools/serving_smoke.py --soak    # long soak (the `slow`-marked
                                            # variant: 48 mixed requests)

Exit code 0 on success; any failed invariant raises.
"""
from __future__ import annotations

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PADDLE_TPU_TELEMETRY", "1")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def main() -> int:
    soak = "--soak" in sys.argv
    import paddle_tpu as paddle
    from paddle_tpu import observability as obs
    from paddle_tpu.models import build_gpt, gpt_config
    from paddle_tpu.serving import Engine
    from paddle_tpu.serving import engine as eng_mod

    assert obs.enabled(), "telemetry must be ON for this lane"
    obs.registry().reset()

    n_req = 48 if soak else 8
    cfg = gpt_config("gpt-tiny", max_position_embeddings=128,
                     hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    paddle.seed(0)
    model = build_gpt(cfg)
    model.eval()
    engine = Engine(model, max_slots=2 if not soak else 4, max_len=48,
                    max_queue=2 * n_req)
    rs = np.random.RandomState(0)
    try:
        handles = [
            engine.submit(
                rs.randint(0, cfg.vocab_size,
                           rs.randint(3, 13)).astype(np.int64),
                max_new_tokens=int(rs.randint(2, 7)))
            for _ in range(n_req)]
        for h in handles:
            h.result(timeout=600)
        st = engine.stats()
    finally:
        engine.shutdown()

    # -- engine invariants ---------------------------------------------------
    assert st["completed"] == n_req, st
    assert st["active_slots"] == 0 and st["queue_depth"] == 0, st
    assert st["slot_reuses"] > 0, f"no slot reuse across {n_req} requests"
    assert st["decode_compiles"] == 1, \
        f"decode must be ONE compiled program, got {st['decode_compiles']}"

    # -- telemetry counters (the observability wiring itself) ----------------
    reg = obs.registry()
    req_c = reg.get(eng_mod.SERVING_REQUESTS)
    assert req_c is not None, "serving requests counter never registered"
    completed = req_c.value(labels={"outcome": "completed"})
    submitted = req_c.value(labels={"outcome": "submitted"})
    assert completed == n_req and submitted == n_req, req_c.series()
    ttft = reg.get(eng_mod.SERVING_TTFT)
    assert ttft is not None and ttft.total_count() == n_req, \
        "TTFT histogram must have one observation per request"
    tok_c = reg.get(eng_mod.SERVING_TOKENS)
    assert tok_c is not None and tok_c.total() == st["tokens"]
    lat = reg.get(eng_mod.SERVING_TOKEN_LATENCY)
    assert lat is not None and \
        lat.total_count() == st["tokens"] - n_req, \
        "per-token histogram counts every non-first token"
    gauge = reg.get(eng_mod.SERVING_ACTIVE_SLOTS)
    assert gauge is not None and gauge.value() == 0.0
    qd = reg.get(eng_mod.SERVING_QUEUE_DEPTH)
    assert qd is not None and qd.value() == 0.0

    from paddle_tpu.observability import flight
    kinds = {e["name"] for e in flight.events("serving")}
    assert {"admit", "evict"} <= kinds, kinds

    print(json.dumps({"serving_smoke": "ok", "soak": soak,
                      "requests": n_req, "tokens": int(st["tokens"]),
                      "slot_reuses": int(st["slot_reuses"]),
                      "decode_steps": int(st["decode_steps"])}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
