"""Serving chaos lane: kill engines under live mixed-tenant load and
prove the self-healing layer's invariants (ISSUE 9; the serving analogue
of tools/chaos_smoke.py).

Two tiny-model engine replicas run under :class:`EngineSupervisor`
behind the HTTP gateway.  While blocking + streaming traffic from two
tenants is in flight, the lane repeatedly arms a SIGKILL-equivalent
scheduler fault (``serving.scheduler``, the PR 5 seam) until each kill
round has been absorbed by a supervisor restart, then asserts:

* **zero lost zero-token requests** — every blocking request terminates
  with 200 (completed, possibly after a transparent supervisor or
  gateway re-dispatch) or a structured 429 (shed); nothing hangs,
  nothing 5xx-es;
* **bounded interrupted streams** — only STREAMING requests that had
  already delivered tokens may fail, they fail with the typed
  ``stream_interrupted`` SSE error event, and there are at most
  ``kills x max_slots`` of them;
* **no duplicated tokens** — every completed request carries exactly
  ``max_tokens`` tokens (a replayed prefix would exceed it);
* **one decode signature per engine build** — each supervisor build
  compiled at most one decode program (retrace-sentinel-asserted), and
  restarts equal the kills that landed;
* **telemetry** — ``engine_restarts_total`` /
  ``requests_redispatched_total`` exported through /metrics, supervisor
  flight events recorded;
* **graceful drain** — the stack drains clean at the end (True from
  ``GatewayStack.drain``: nothing dropped);
* **kills during scale events** (ISSUE 15) — an :class:`Autoscaler`
  grows the fleet to 3 and shrinks it back to 2 while a
  SIGKILL-equivalent scheduler fault lands mid-``scale.up_build`` (the
  build itself also crashes once and is retried) and
  mid-``scale.down_drain``: zero lost zero-token requests, adapter
  parity across the events, one decode signature per build, zero leaked
  pages/ledger bytes across EVERY build (the scale replicas join the
  same end-of-lane sweep), final fleet size back within [min, max];
* **SLO alerts heal with the fleet** (ISSUE 16) — an aggressive
  availability objective rides the whole kill matrix; any alert raised
  during a rebuild resolves once the fleet is healthy (no stuck-firing
  state across supervisor rebuilds) and every incident bundle written
  mid-kill is complete, parseable JSON (atomic tmp+rename writes);
* **rolling upgrade under chaos** (ISSUE 20) — a fleet of two sharing
  ONE host-DRAM prefix tier is upgraded by
  :class:`RolloutController` under live load with all three rollout
  seams (``rollout.build`` / ``rollout.canary_gate`` /
  ``rollout.drain_old``) armed: every crash absorbed + retried, zero
  lost zero-token requests, the fleet lands all-new (no mixed
  revision), a post-upgrade warm conversation turn is served from the
  host tier token-identically (the tier SPANS the rollout), a second
  rollout to a bad revision is auto-rolled back without touching the
  incumbents, and every rollout build joins the zero-leaked-pages /
  zero-tier-bytes sweep.

    python tools/chaos_serving.py

Exit code 0 on success; any failed invariant raises.
"""
from __future__ import annotations

import http.client
import json
import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PADDLE_TPU_TELEMETRY", "1")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

KILL_ROUNDS = 2
N_BLOCKING = 18
N_STREAMING = 6
MAX_TOKENS = 5
SLOTS = 2


def _blocking(port, payload, tenant, out, lock, timeout=600):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    t0 = time.perf_counter()
    try:
        conn.request("POST", "/v1/completions", json.dumps(payload).encode(),
                     {"Content-Type": "application/json",
                      "X-Tenant": tenant})
        r = conn.getresponse()
        body = r.read()
        token_ids = (json.loads(body)["choices"][0]["token_ids"]
                     if r.status == 200 else [])
        with lock:
            out.append({"kind": "blocking", "status": r.status,
                        "tokens": len(token_ids),
                        "token_ids": token_ids,
                        "prompt": tuple(payload["prompt"]),
                        "model": payload.get("model"),
                        "wall_s": time.perf_counter() - t0})
    except Exception as e:  # noqa: BLE001 — a hang/5xx fails the lane
        with lock:
            out.append({"kind": "blocking", "status": -1,
                        "error": f"{type(e).__name__}: {e}"})
    finally:
        conn.close()


def _streaming(port, payload, tenant, out, lock, timeout=600):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", "/v1/completions",
                     json.dumps(dict(payload, stream=True)).encode(),
                     {"Content-Type": "application/json",
                      "X-Tenant": tenant})
        r = conn.getresponse()
        if r.status != 200:
            r.read()
            with lock:
                out.append({"kind": "streaming", "status": r.status,
                            "tokens": 0, "interrupted": False})
            return
        n_tok, err_code = 0, None
        for line in r:
            if not line.startswith(b"data: "):
                continue
            data = line[6:].strip()
            if data == b"[DONE]":
                break
            event = json.loads(data)
            if "error" in event:
                err_code = event["error"].get("code")
                continue
            n_tok += len(event["choices"][0]["token_ids"])
        with lock:
            out.append({"kind": "streaming", "status": 200,
                        "tokens": n_tok,
                        "interrupted": err_code is not None,
                        "error_code": err_code})
    except Exception as e:  # noqa: BLE001
        with lock:
            out.append({"kind": "streaming", "status": -1,
                        "error": f"{type(e).__name__}: {e}"})
    finally:
        conn.close()


def main() -> int:
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import observability as obs
    from paddle_tpu.models import build_gpt, gpt_config
    from paddle_tpu.observability import flight
    from paddle_tpu.serving import AdapterRegistry, Engine, EngineSupervisor
    from paddle_tpu.serving import make_lora
    from paddle_tpu.serving.engine import (SERVING_ADAPTER_TOKENS,
                                           SERVING_REDISPATCHED)
    from paddle_tpu.serving.gateway import TenantConfig, start_gateway
    from paddle_tpu.serving.supervisor import SERVING_RESTARTS
    from paddle_tpu.testing import faults

    assert obs.enabled(), "telemetry must be ON for this lane"
    obs.registry().reset()

    cfg = gpt_config("gpt-tiny", max_position_embeddings=128,
                     hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    models = []
    for _ in range(2):
        paddle.seed(5)
        m = build_gpt(cfg)
        m.eval()
        models.append(m)

    # decode fast path ON under chaos (ISSUE 10), the PAGED pool under it
    # (ISSUE 11), and MULTI-LORA adapters over both (ISSUE 12): every
    # rebuild must drop the prefix cache AND the page tables AND the
    # adapter banks cleanly (fresh pool, fresh index, fresh allocator,
    # fresh residency with zero pins — no stale rows, pages or bank
    # slots) and keep speculative greedy exact, which the token-count
    # and per-adapter-parity invariants below catch (a stale, replayed
    # or mis-mapped page/bank row would change the emitted tokens).
    # Each replica gets its OWN registry holding IDENTICAL adapters
    # (same seeds), so a cross-replica gateway re-dispatch serves the
    # same variant — the registries persist across that replica's
    # rebuilds while residency is per-build.
    ADAPTERS = ["lora-a", "lora-b", "lora-c"]
    regs = []
    for _ in models:
        reg = AdapterRegistry(cfg, max_resident=3, max_rank=8)
        for j, nm in enumerate(ADAPTERS):
            reg.register(make_lora(cfg, rank=2 + 2 * j, seed=40 + j,
                                   name=nm, std=0.2))
        regs.append(reg)
    engines_built: list = []

    def _factory(mm, reg):
        def build():
            e = Engine(mm, max_slots=SLOTS, max_len=48, max_queue=16,
                       prefix_cache=True, prefix_block=4, speculative_k=3,
                       paged_kv=True, adapters=reg)
            engines_built.append(e)
            return e
        return build

    sups = [EngineSupervisor(
        _factory(m, regs[i]), name=f"engine{i}", poll_interval_s=0.02,
        max_restarts=6, max_redispatch=3)
        for i, m in enumerate(models)]
    tenants = [TenantConfig("vip", priority="interactive", weight=4.0,
                            max_queue=32),
               TenantConfig("bulk", priority="batch", max_queue=8)]
    stack = start_gateway(sups, own_engines=True, tenants=tenants,
                          names=["engine0", "engine1"], max_redispatch=3)
    # SLO engine riding the kill matrix (ISSUE 16): an availability
    # objective aggressive enough that the sheds and interrupted
    # streams the kills cause can burn it.  Whatever fires during a
    # rebuild must RESOLVE once the fleet heals (no stuck-firing state
    # across supervisor rebuilds), and every incident bundle written
    # mid-kill must land as complete, parseable JSON (atomic writes).
    import tempfile
    from paddle_tpu.observability.slo import (INCIDENT_SCHEMA, SloEngine,
                                              SloObjective)
    slo_eng = SloEngine(
        stack, [SloObjective("chaos-availability", "availability", 0.99,
                             fast_window_s=2.0, fast_burn=1.0,
                             slow_window_s=6.0, slow_burn=1.0,
                             fire_ticks=1, resolve_ticks=2,
                             min_events=2)],
        tick_s=0.1,
        incident_dir=tempfile.mkdtemp(prefix="chaos_slo_inc_"))
    rs = np.random.RandomState(0)
    out, lock = [], threading.Lock()
    threads = []
    try:
        port = stack.port
        # warm both replicas (compiles out of the measured window; the
        # router alternates because load ties break toward idleness)
        for i in range(4):
            _blocking(port, {"prompt": [i + 1, 2, 3],
                             "max_tokens": 2}, "vip", [], lock)
        # per-adapter reference outputs BEFORE any kill: a completed
        # request for the same (adapter, prompt) pair during/after the
        # restarts must emit exactly these tokens — a stale or
        # mis-loaded bank row after a rebuild would break the parity
        ref_pairs = []
        for j, nm in enumerate([None] + ADAPTERS):
            prompt = [j + 2, 5, 9, 3]
            payload = {"prompt": prompt, "max_tokens": MAX_TOKENS}
            if nm is not None:
                payload["model"] = nm
            o = []
            _blocking(port, payload, "vip", o, lock)
            assert o and o[0]["status"] == 200, f"reference failed: {o}"
            ref_pairs.append((nm, tuple(prompt), o[0]["token_ids"]))
        reference = {(nm, pr): toks for nm, pr, toks in ref_pairs}

        def spawn(target, payload, tenant):
            th = threading.Thread(target=target,
                                  args=(port, payload, tenant, out, lock))
            th.start()
            threads.append(th)

        total = N_BLOCKING + N_STREAMING
        kill_at = {total // 3, 2 * total // 3}   # mid-load kill points
        kills = 0
        sent = 0
        for i in range(total):
            if i % 3 == 0:
                # a known (adapter, prompt) pair: its completion must
                # match the pre-kill reference bit for bit
                nm, pr, _ = ref_pairs[(i // 3) % len(ref_pairs)]
                payload = {"prompt": list(pr), "max_tokens": MAX_TOKENS}
                if nm is not None:
                    payload["model"] = nm
            else:
                prompt = [int(t) for t in rs.randint(1, cfg.vocab_size, 4)]
                payload = {"prompt": prompt, "max_tokens": MAX_TOKENS}
            tenant = "vip" if i % 3 else "bulk"
            if i % (total // N_STREAMING) == 1 and tenant == "vip":
                spawn(_streaming, payload, tenant)
            else:
                spawn(_blocking, payload, tenant)
            sent += 1
            if sent in kill_at and kills < KILL_ROUNDS:
                before = sum(s.restarts for s in sups)
                faults.arm("serving.scheduler", times=1)
                kills += 1
                deadline = time.time() + 120
                while sum(s.restarts for s in sups) == before:
                    assert time.time() < deadline, \
                        "kill was never absorbed by a supervisor restart"
                    time.sleep(0.02)
            time.sleep(min(rs.exponential(0.03), 0.2))
        for th in threads:
            th.join(timeout=600)
        assert not any(th.is_alive() for th in threads), \
            "a client hung: lost request"
        assert len(out) == total, (len(out), total)

        blocking = [o for o in out if o["kind"] == "blocking"]
        streaming = [o for o in out if o["kind"] == "streaming"]
        # zero lost zero-token requests: blocking work either completed
        # (maybe via re-dispatch) or was shed with a structured 429
        bad = [o for o in blocking if o["status"] not in (200, 429)]
        assert not bad, f"blocking requests lost/5xx: {bad}"
        completed = [o for o in out if o["status"] == 200 and
                     not o.get("interrupted")]
        shed = [o for o in out if o["status"] == 429]
        interrupted = [o for o in streaming if o.get("interrupted")]
        # no duplicated tokens: completed = exactly MAX_TOKENS each
        wrong = [o for o in completed if o["tokens"] != MAX_TOKENS]
        assert not wrong, f"token-count mismatch (duplication?): {wrong}"
        # per-adapter token parity across restarts: every completed
        # known-pair request equals its pre-kill reference
        checked = 0
        for o in blocking:
            key = (o.get("model"), o.get("prompt"))
            if o["status"] == 200 and key in reference:
                assert o["token_ids"] == reference[key], \
                    f"adapter parity broke across a restart: {o} != " \
                    f"{reference[key]}"
                checked += 1
        assert checked > 0, "no known-pair request completed"
        # one decode signature per engine build; every armed kill was
        # absorbed by a restart.  >= not ==: a lane run under external
        # resource pressure can see real (non-injected) engine deaths —
        # the supervisor heals those too, which is the point; the
        # invariants below hold for EVERY death, injected or not
        restarts = sum(s.restarts for s in sups)
        assert restarts >= kills, (restarts, kills)

        # interrupted streams are bounded by the active slots per death
        assert len(interrupted) <= restarts * SLOTS * 2, interrupted
        assert all(o["error_code"] == "stream_interrupted"
                   for o in interrupted), interrupted
        assert len(completed) + len(shed) + len(interrupted) == total
        for s in sups:
            builds = s.builds()
            assert all(b["decode_compiles"] <= 1 for b in builds), \
                (s.name, builds)
            assert builds[-1]["decode_compiles"] == 1, (s.name, builds)
            assert s.failed is None, s.failed
            # fast path live under chaos: the current build's prefix
            # counters only count THIS pool's entries (a rebuild resets
            # them with the index — stale hits would show up here as
            # hits exceeding this build's admissions)
            st = s.stats()
            assert st["prefix_hits"] + st["prefix_misses"] >= \
                st["prefix_inserts"], st
            # paged pool live under chaos: the current build's allocator
            # is internally consistent and conserves pages (a leak shows
            # up as used pages no active request or cache entry holds)
            assert st["kv_pages_free"] + st["kv_pages_used"] == \
                st["kv_num_pages"], st
            # adapter banks live under chaos: residency is per-build,
            # pins bounded by residents bounded by capacity
            assert 0 <= st["adapters_pinned"] <= st["adapters_resident"] \
                <= st["adapter_bank_capacity"], st

        # telemetry through the wire
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        conn.request("GET", "/metrics")
        text = conn.getresponse().read().decode()
        conn.close()
        assert SERVING_RESTARTS in text, "restart counter missing"
        assert SERVING_ADAPTER_TOKENS in text, \
            "per-adapter token counter missing from /metrics"
        restarts_c = obs.registry().get(SERVING_RESTARTS)
        assert restarts_c is not None and restarts_c.total() == restarts
        redis_c = obs.registry().get(SERVING_REDISPATCHED)
        redispatched = 0 if redis_c is None else int(redis_c.total())
        kinds = {e["name"] for e in flight.events("supervisor")}
        assert {"teardown", "restart"} <= kinds, kinds

        # journeys under chaos (ISSUE 13): every finished request
        # timeline is a monotone, gap-free partition of its wall time —
        # INCLUDING the ones that crossed a supervisor rebuild or a
        # gateway redispatch, whose single journey id must keep
        # accumulating phases on the new build/replica (continuity:
        # serving phases appear AFTER the rebuild/redispatch phase)
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        conn.request("GET", "/debug/requests?last=1000")
        tls = json.loads(conn.getresponse().read())["requests"]
        conn.close()
        assert len(tls) >= len(completed), (len(tls), len(completed))
        assert len({t["id"] for t in tls}) == len(tls), "duplicate ids"
        healed = 0
        for tl in tls:
            parts = sum(p["dur_ms"] for p in tl["phases"])
            assert abs(parts - tl["wall_ms"]) < 0.05, \
                (tl["id"], parts, tl["wall_ms"])
            for a, b in zip(tl["phases"], tl["phases"][1:]):
                assert b["t_ms"] >= a["t_ms"] and \
                    abs(a["t_ms"] + a["dur_ms"] - b["t_ms"]) < 0.02, \
                    (tl["id"], "non-monotone or gapped partition")
            names = [p["phase"] for p in tl["phases"]]
            for marker in ("rebuild", "redispatch"):
                if marker in names and tl["outcome"] == "ok":
                    after = names[names.index(marker) + 1:]
                    assert {"engine_queue", "prefill", "decode"} & \
                        set(after), (tl["id"], marker, names)
                    healed += 1
        journey_summary = {"journeys": len(tls), "healed_journeys": healed}

        # -- kills DURING scale events (ISSUE 15): the fleet grows and
        # shrinks itself while SIGKILL-equivalent scheduler faults land
        # mid-`scale.up_build` and mid-`scale.down_drain`.  Invariants:
        # every blocking request still terminates 200/429 (zero lost
        # zero-token requests), every build keeps one decode signature
        # and leaks nothing (asserted over engines_built at the end),
        # and the fleet lands back inside [min, max].
        from paddle_tpu.serving import Autoscaler, ScalePolicy
        paddle.seed(5)
        model3 = build_gpt(cfg)
        model3.eval()
        reg3 = AdapterRegistry(cfg, max_resident=3, max_rank=8)
        for j, nm in enumerate(ADAPTERS):
            reg3.register(make_lora(cfg, rank=2 + 2 * j, seed=40 + j,
                                    name=nm, std=0.2))
        scale_sups: list = []

        def scale_factory():
            sup = EngineSupervisor(
                _factory(model3, reg3),
                name=f"scale{len(scale_sups)}", poll_interval_s=0.02,
                max_restarts=6, max_redispatch=3)
            scale_sups.append(sup)
            return sup

        # thresholds parked at infinity: the lane TRIGGERS each scale
        # event deterministically; the policy must not fire on its own
        auto = Autoscaler(
            stack, scale_factory, min_replicas=2, max_replicas=3,
            policy=ScalePolicy(slo_ttft_s=1e6, up_ticks=10 ** 6,
                               idle_ticks=10 ** 6, cooldown_up_s=3600.0,
                               cooldown_down_s=3600.0),
            poll_interval_s=0.02, drain_deadline_s=30.0,
            build_s_hint=2.0)
        scale_threads: list = []
        scale_out: list = []

        def scale_traffic(n, tag):
            for k in range(n):
                nm, pr, _ = ref_pairs[k % len(ref_pairs)]
                payload = {"prompt": list(pr), "max_tokens": MAX_TOKENS}
                if nm is not None:
                    payload["model"] = nm
                th = threading.Thread(
                    target=_blocking,
                    args=(port, payload, "vip", scale_out, lock))
                th.start()
                scale_threads.append(th)
                time.sleep(0.02)

        try:
            router = stack.gateway.router
            # phase A: scale-up with (1) the build itself crashing once
            # (retried) and (2) an engine kill landing mid-event
            restarts_before = sum(s.restarts for s in sups + scale_sups)
            faults.arm("scale.up_build", times=1)
            auto.trigger("up", reason="chaos")
            scale_traffic(8, "up")
            faults.arm("serving.scheduler", times=1)
            deadline = time.time() + 120
            while len(router.names) < 3:
                assert time.time() < deadline, \
                    "scale-up never completed under chaos"
                time.sleep(0.02)
            assert faults.hits("scale.up_build") >= 2, \
                "crashed build was not retried"
            # phase B: scale-down with an engine kill mid-drain; the
            # supervisor heals whichever engine died and the drain is
            # re-issued — the replica leaves only once EMPTY
            auto.trigger("down", reason="chaos")
            scale_traffic(8, "down")
            faults.arm("serving.scheduler", times=1)
            deadline = time.time() + 180
            while len(router.names) > 2:
                assert time.time() < deadline, \
                    "scale-down never completed under chaos"
                time.sleep(0.02)
            for th in scale_threads:
                th.join(timeout=600)
            assert not any(th.is_alive() for th in scale_threads), \
                "a client hung during a scale event: lost request"
            # zero lost zero-token requests through both scale events
            bad = [o for o in scale_out
                   if o["status"] not in (200, 429)]
            assert not bad, f"requests lost during scale events: {bad}"
            # adapter parity still holds for completions that crossed
            # the scale events (incl. any served by the new replica)
            for o in scale_out:
                key = (o.get("model"), o.get("prompt"))
                if o["status"] == 200 and key in reference:
                    assert o["token_ids"] == reference[key], \
                        f"parity broke across a scale event: {o}"
            # final fleet size back within [min, max] and the drained
            # replica's supervisor fully torn down
            assert 2 <= len(router.names) <= 3, router.names
            assert len(router.names) == 2, router.names
            scale_kinds = {e["name"]
                           for e in flight.events("autoscaler")}
            assert {"scale_up_begin", "scale_up", "scale_up_failed",
                    "scale_down_begin", "scale_down"} <= scale_kinds, \
                scale_kinds
            for s in scale_sups:
                assert s.failed is None, s.failed
                for b in s.builds():
                    assert b["decode_compiles"] <= 1, (s.name, b)
            scale_summary = {
                "scale_requests": len(scale_out),
                "scale_completed": sum(1 for o in scale_out
                                       if o["status"] == 200),
                "scale_replica_builds": len(scale_sups),
                "scale_restarts": sum(s.restarts for s in sups +
                                      scale_sups) - restarts_before,
            }
        finally:
            faults.reset()
            auto.shutdown()

        # -- KV tiering under chaos (ISSUE 18): a SHARED host-DRAM
        # prefix tier (Engine(host_prefix=tier), the supervisor-factory
        # shape) rides a kill.  Turn 1 of a conversation demotes to the
        # host tier when filler traffic evicts it; a SIGKILL-equivalent
        # scheduler fault then rebuilds the engine — fresh pool, fresh
        # device index, EMPTY HBM cache — and the warm turn on the new
        # build is served from the host tier (promote), token-identical
        # to a never-tiered dense reference.  End-of-lane: zero leaked
        # pages on every kv build AND zero leaked host-tier bytes.
        from paddle_tpu.serving import HostPrefixTier
        tier = HostPrefixTier(capacity_mb=32, block=4)
        kv_engines: list = []

        def kv_factory():
            e = Engine(model3, max_slots=SLOTS, max_len=48, max_queue=16,
                       prefix_cache=True, prefix_block=4, paged_kv=True,
                       num_pages=24, host_prefix=tier)
            kv_engines.append(e)
            return e

        kv_sup = EngineSupervisor(kv_factory, name="kvtier",
                                  poll_interval_s=0.02, max_restarts=6,
                                  max_redispatch=3)
        try:
            conv_prompt = [int(t) for t in rs.randint(1, cfg.vocab_size,
                                                      12)]
            t1 = [int(t) for t in kv_sup.submit(
                conv_prompt, max_new_tokens=4,
                conversation="chaos-conv").result(timeout=300)]
            warm = conv_prompt + t1 + \
                [int(t) for t in rs.randint(1, cfg.vocab_size, 4)]
            # independent reference: a dense, never-killed, never-tiered
            # engine decoding the warm prompt from scratch
            ref_eng = Engine(model3, max_slots=1, max_len=48)
            ref_warm = [int(t) for t in ref_eng.submit(
                warm, max_new_tokens=4).result(timeout=300)]
            ref_eng.shutdown()
            # filler conversations force the turn-1 entry out of the
            # 24-page pool — eviction demotes it to the host tier
            for i in range(6):
                filler = [int(t) for t in rs.randint(1, cfg.vocab_size,
                                                     12)]
                kv_sup.submit(filler, max_new_tokens=4,
                              conversation=f"chaos-fill{i}").result(
                    timeout=300)
            assert tier.flush(), "spill worker never drained"
            assert len(tier) > 0 and tier.stats()["demotes"] > 0, \
                "nothing demoted to the host tier before the kill"
            # mid-kill: arm the scheduler fault and poke traffic through
            # it — the supervisor absorbs the death and rebuilds
            kv_restarts_before = kv_sup.restarts
            faults.arm("serving.scheduler", times=1)
            poke = kv_sup.submit([3, 1, 4, 1, 5], max_new_tokens=2)
            deadline = time.time() + 120
            while kv_sup.restarts == kv_restarts_before:
                assert time.time() < deadline, \
                    "kv-tier kill never absorbed by a restart"
                time.sleep(0.02)
            poke.result(timeout=300)     # redispatched onto the rebuild
            # the warm turn lands on a rebuilt engine whose device index
            # is empty — only the host tier can make this a hit
            hw = kv_sup.submit(warm, max_new_tokens=4,
                               conversation="chaos-conv")
            tw = [int(t) for t in hw.result(timeout=300)]
            kv_st = kv_sup.stats()
            assert hw.prefix_hit and kv_st["host_prefix_promotes"] >= 1, \
                f"warm turn was not served from the host tier: {kv_st}"
            assert tw == ref_warm, \
                "host-tier promote changed tokens across a rebuild"
            assert kv_sup.builds()[-1]["decode_compiles"] == 1, \
                kv_sup.builds()
            assert kv_sup.failed is None, kv_sup.failed
            kv_summary = {
                "kv_tier_demotes": tier.stats()["demotes"],
                "kv_tier_promotes": int(kv_st["host_prefix_promotes"]),
                "kv_tier_builds": len(kv_engines),
                "kv_tier_restarts": kv_sup.restarts,
            }
        finally:
            faults.reset()
            kv_sup.shutdown()
        # zero leaked host-tier bytes: shutdown leaves the SHARED tier
        # open by design (that is the rebuild-survival property); its
        # invariants hold, and close releases every byte + the ledger row
        tier.check()
        tier.close()
        assert tier.bytes_used == 0 and len(tier) == 0, tier.stats()

        # -- Pallas decode kernel under chaos (ISSUE 19): the fused
        # paged-attention read (Engine(decode_kernel="pallas"), interpret
        # mode off-TPU) composed with the FULL PR 10/11/12 flag set —
        # prefix cache + speculative verify + int8 KV + device sampling.
        # A scheduler kill rebuilds the engine; tokens stay identical to
        # an XLA-paged-read reference across the rebuild, every build
        # compiles ONE decode signature, and the dead build leaks zero
        # pages.
        pk_flags = dict(max_slots=SLOTS, max_len=48, max_queue=16,
                        prefix_cache=True, prefix_block=4,
                        speculative_k=3, kv_dtype="int8", paged_kv=True,
                        num_pages=24)
        pk_prompts = [[int(t) for t in rs.randint(1, cfg.vocab_size, n)]
                      for n in (9, 13)]
        pk_ref_eng = Engine(model3, decode_kernel="xla", **pk_flags)
        pk_ref = [[int(t) for t in pk_ref_eng.submit(
            p, max_new_tokens=4).result(timeout=300)]
            for p in pk_prompts]
        pk_ref_eng.shutdown()
        pk_engines: list = []

        def pk_factory():
            e = Engine(model3, decode_kernel="pallas", **pk_flags)
            pk_engines.append(e)
            return e

        pk_sup = EngineSupervisor(pk_factory, name="pallas",
                                  poll_interval_s=0.02, max_restarts=6,
                                  max_redispatch=3)
        try:
            t0 = [int(t) for t in pk_sup.submit(
                pk_prompts[0], max_new_tokens=4).result(timeout=300)]
            assert t0 == pk_ref[0], \
                "fused kernel diverged from the XLA paged read"
            faults.arm("serving.scheduler", times=1)
            pk_poke = pk_sup.submit([2, 7, 1, 8], max_new_tokens=2)
            deadline = time.time() + 120
            while pk_sup.restarts == 0:
                assert time.time() < deadline, \
                    "pallas-leg kill never absorbed by a restart"
                time.sleep(0.02)
            pk_poke.result(timeout=300)
            # dead build: host bookkeeping fully unwound
            pk_engines[0]._page_alloc.check()
            assert pk_engines[0]._page_alloc.n_used == 0, \
                f"dead pallas build leaked pages: " \
                f"{pk_engines[0]._page_alloc!r}"
            t1p = [int(t) for t in pk_sup.submit(
                pk_prompts[1], max_new_tokens=4).result(timeout=300)]
            assert t1p == pk_ref[1], \
                "fused kernel diverged after the rebuild"
            assert pk_sup.failed is None, pk_sup.failed
            for b in pk_sup.builds():
                assert b["decode_compiles"] <= 1, pk_sup.builds()
            pk_summary = {
                "pallas_builds": len(pk_engines),
                "pallas_restarts": pk_sup.restarts,
                "pallas_decode_compiles": [b["decode_compiles"]
                                           for b in pk_sup.builds()],
            }
        finally:
            faults.reset()
            pk_sup.shutdown()

        # SLO under chaos (ISSUE 16): the kill matrix is over and the
        # fleet is healthy — any alert the rebuilds raised must clear
        # as the window's errors age out (a stuck-firing alert here
        # would mean evaluator state survived a heal it shouldn't)
        deadline = time.time() + 60
        while slo_eng.firing():
            assert time.time() < deadline, \
                f"alert stuck firing after the fleet healed: " \
                f"{slo_eng.firing()}"
            time.sleep(0.1)
        incidents = slo_eng.store.list()
        from tools.incident_report import render
        for m in incidents:
            b = slo_eng.store.get(m["id"])
            assert b is not None and b["schema"] == INCIDENT_SCHEMA, m
            for key in ("incident", "window", "flight_events"):
                assert key in b, (m["id"], key)
            assert b["incident"]["objective"] == "chaos-availability", b
            # traffic capture under chaos (ISSUE 17): every bundle cut
            # mid-kill carries the capture tail — the arrivals that
            # caused the burn, admitted AND shed, privacy-safe (no
            # prompt ids even if a gateway ran full-mode) and each
            # journey id resolving over the wire
            tail = b.get("capture_tail")
            assert tail and isinstance(tail["entries"], list), (m, tail)
            assert tail["entries"], f"empty capture tail in {m['id']}"
            assert all("prompt" not in e for e in tail["entries"]), \
                "prompt ids leaked into an incident bundle"
            for e in tail["entries"][-3:]:
                if not e["journey_id"]:
                    continue
                conn = http.client.HTTPConnection("127.0.0.1", port,
                                                  timeout=60)
                conn.request("GET", f"/debug/requests/{e['journey_id']}")
                r = conn.getresponse()
                r.read()
                conn.close()
                assert r.status == 200, \
                    f"capture_tail journey {e['journey_id']} unresolvable"
            assert "-- capture tail" in render(b), "renderer dropped tail"
        cap_stats = stack.gateway.capture.stats()
        assert cap_stats["entries"] <= cap_stats["max_entries"], cap_stats
        slo_summary = {
            "slo_alert_transitions": len(flight.events("alert")),
            "slo_incidents": len(incidents),
            "captured_arrivals": cap_stats["recorded"],
            "capture_dropped": cap_stats["dropped"],
        }

        # -- rolling upgrade under chaos (ISSUE 20): a fleet of two
        # supervised replicas sharing ONE host-DRAM prefix tier is
        # upgraded to a new revision under live HTTP load with ALL
        # THREE rollout seams armed (`rollout.build`,
        # `rollout.canary_gate`, `rollout.drain_old`): every injected
        # crash is absorbed and retried, zero requests are lost, the
        # fleet lands all-new (no mixed revision), and a warm
        # conversation turn AFTER the upgrade — whose device caches are
        # all fresh builds — is served from the shared host tier,
        # token-identical to a dense reference (the tier spans the
        # rollout).  A second rollout to an injected BAD revision (a
        # zero-signature gate no real build can pass) is auto-rolled
        # back: the canary is drained out, the incumbents are never
        # touched.  End of leg: zero leaked pages on every rollout
        # build and zero leaked host-tier bytes.
        from paddle_tpu.serving import (CanaryGate, HostPrefixTier as _HPT,
                                        RolloutController,
                                        RolloutRolledBack)
        ru_tier = _HPT(capacity_mb=32, block=4)
        ru_engines: list = []
        ru_sups: list = []

        def ru_factory(revision):
            def build():
                # one model instance per replica: a rollout build traces
                # its jit programs while the incumbents are serving —
                # concurrent tracing over one shared module is
                # unsupported (same rule as the autoscale factory)
                paddle.seed(5)
                mr = build_gpt(cfg)
                mr.eval()
                e = Engine(mr, max_slots=SLOTS, max_len=48,
                           max_queue=16, prefix_cache=True, prefix_block=4,
                           paged_kv=True, num_pages=24,
                           host_prefix=ru_tier)
                ru_engines.append(e)
                return e
            sup = EngineSupervisor(build, name=f"ru{len(ru_sups)}",
                                   poll_interval_s=0.02, max_restarts=6,
                                   max_redispatch=3)
            ru_sups.append(sup)
            return sup

        ru_stack = start_gateway(
            [ru_factory("r0"), ru_factory("r0")], own_engines=True,
            tenants=[TenantConfig("vip", priority="interactive",
                                  weight=4.0, max_queue=32)],
            names=["ru0", "ru1"], max_redispatch=3)
        ru_rs = np.random.RandomState(7)
        ru_out: list = []
        ru_threads: list = []
        try:
            ru_port = ru_stack.port
            ru_router = ru_stack.gateway.router
            # turn 1 of a conversation on the OLD revision; fillers
            # evict it from the page pools, demoting it into the SHARED
            # host tier — which must outlive the whole upgrade
            conv = [int(t) for t in ru_rs.randint(1, cfg.vocab_size, 12)]
            o1 = []
            _blocking(ru_port, {"prompt": conv, "max_tokens": 4,
                                "conversation": "ru-conv"}, "vip", o1,
                      lock)
            assert o1 and o1[0]["status"] == 200, o1
            warm = conv + o1[0]["token_ids"] + \
                [int(t) for t in ru_rs.randint(1, cfg.vocab_size, 4)]
            paddle.seed(5)
            ref_m = build_gpt(cfg)
            ref_m.eval()
            ref_eng = Engine(ref_m, max_slots=1, max_len=48)
            ref_warm = [int(t) for t in ref_eng.submit(
                warm, max_new_tokens=4).result(timeout=300)]
            ref_eng.shutdown()
            for i in range(10):
                filler = [int(t) for t in ru_rs.randint(
                    1, cfg.vocab_size, 12)]
                fo: list = []
                _blocking(ru_port, {"prompt": filler, "max_tokens": 4,
                                    "conversation": f"ru-fill{i}"},
                          "vip", fo, lock)
            assert ru_tier.flush(), "rollout-leg spill never drained"
            assert ru_tier.stats()["demotes"] > 0, \
                "nothing demoted before the rollout"

            def ru_feed(ctl, n_max=120):
                i = 0
                while i < n_max:
                    try:
                        ctl.wait(0.05)
                        return
                    except TimeoutError:
                        pass
                    prompt = [int(t) for t in ru_rs.randint(
                        1, cfg.vocab_size, 4)]
                    th = threading.Thread(
                        target=_blocking,
                        args=(ru_port, {"prompt": prompt,
                                        "max_tokens": MAX_TOKENS},
                              "vip", ru_out, lock))
                    th.start()
                    ru_threads.append(th)
                    i += 1

            # phase A: the upgrade, all three seams armed — each crash
            # absorbed + retried, the fleet lands all-new
            for seam in ("rollout.build", "rollout.canary_gate",
                         "rollout.drain_old"):
                faults.arm(seam, times=1)
            ctl = RolloutController(
                ru_stack, ru_factory,
                gate=CanaryGate(min_requests=2, timeout_s=60.0,
                                ttft_p99_ratio=1e3,
                                ttft_p99_floor_s=1e3),
                drain_deadline_s=30.0, build_s_hint=2.0,
                name_prefix="ru")
            ctl.start_rollout("r1")
            ru_feed(ctl)
            ru_res = ctl.wait(timeout=600)
            assert ru_res is not None and ru_res.ok, ru_res
            for seam in ("rollout.build", "rollout.canary_gate",
                         "rollout.drain_old"):
                assert faults.hits(seam) >= 2, \
                    f"{seam} crash was not retried: {faults.hits(seam)}"
            assert set(ru_router.revisions().values()) == {"r1"}, \
                ru_router.revisions()
            assert len(ru_router.names) == 2, ru_router.names
            # the warm conversation turn lands on a NEW-revision build
            # whose device index is empty — only the host tier, which
            # spanned the rollout, can make this token-identical
            hw: list = []
            _blocking(ru_port, {"prompt": warm, "max_tokens": 4,
                                "conversation": "ru-conv"}, "vip", hw,
                      lock)
            assert hw and hw[0]["status"] == 200, hw
            assert hw[0]["token_ids"] == ref_warm, \
                "host-tier promote changed tokens across the rollout"
            ru_promotes = sum(
                int(s.stats().get("host_prefix_promotes", 0))
                for s in ru_sups[2:])
            assert ru_promotes >= 1, \
                "warm turn was not served from the shared host tier"
            ctl.shutdown()
            # phase B: the canary gate bites on an injected bad
            # revision (a zero-signature gate no real build passes) —
            # automatic rollback, incumbents never drained
            incumbents = set(ru_router.names)
            faults.reset()
            ctl2 = RolloutController(
                ru_stack, ru_factory,
                gate=CanaryGate(min_requests=1, timeout_s=120.0,
                                max_decode_signatures=0),
                drain_deadline_s=30.0, build_s_hint=2.0,
                name_prefix="ru")
            ctl2.start_rollout("r2")
            ru_feed(ctl2)
            ru_res2 = ctl2.wait(timeout=600)
            assert isinstance(ru_res2, RolloutRolledBack), ru_res2
            assert ru_res2.gate == "decode_signatures", \
                (ru_res2.gate, ru_res2.detail)
            assert set(ru_router.names) == incumbents, \
                "rollback touched an incumbent"
            assert set(ru_router.revisions().values()) == {"r1"}, \
                ru_router.revisions()
            ctl2.shutdown()
            for th in ru_threads:
                th.join(timeout=600)
            assert not any(th.is_alive() for th in ru_threads), \
                "a client hung across the rollout: lost request"
            # zero lost zero-token requests across upgrade AND rollback
            ru_bad = [o for o in ru_out
                      if o["status"] not in (200, 429)]
            assert not ru_bad, f"requests lost across the rollout: " \
                f"{ru_bad}"
            # the 120+ requests flooded the bounded global flight ring,
            # so the full lifecycle is asserted from each controller's
            # own (unbounded) event log; the ring keeps the rollback
            # tail
            a_events = {e["event"] for e in ctl.stats()["events"]}
            assert {"begin", "routed_in", "canary_passed",
                    "retired"} <= a_events, a_events
            b_events = {e["event"] for e in ctl2.stats()["events"]}
            assert "rollback" in b_events, b_events
            ru_kinds = {e["name"] for e in flight.events("rollout")}
            assert {"rollback_begin", "rolled_back"} <= ru_kinds, ru_kinds
            ru_summary = {
                "rollout_builds": len(ru_engines),
                "rollout_upgraded": ru_res.upgraded,
                "rollout_requests": len(ru_out),
                "rollout_completed": sum(1 for o in ru_out
                                         if o["status"] == 200),
                "rollout_tier_promotes": ru_promotes,
                "rollback_gate": ru_res2.gate,
            }
        finally:
            faults.reset()
            ru_drained = ru_stack.drain(deadline_s=60.0)
        assert ru_drained, "rollout-leg drain dropped work"
        # zero leaked pages on EVERY rollout build — the retired old
        # revision, the upgraded fleet, and the rolled-back canary
        for e in ru_engines:
            e.shutdown()
            e._page_alloc.check()
            assert e._page_alloc.n_used == 0, \
                f"leaked pages in a rollout build: {e._page_alloc!r}"
        # and zero leaked host-tier bytes once the shared tier closes
        ru_tier.check()
        ru_tier.close()
        assert ru_tier.bytes_used == 0 and len(ru_tier) == 0, \
            ru_tier.stats()


        summary = {
            "chaos_serving": "ok", "requests": total, "kills": kills,
            "completed": len(completed), "shed": len(shed),
            "interrupted_streams": len(interrupted),
            "supervisor_restarts": restarts,
            "redispatched": redispatched,
            "builds_per_engine": [len(s.builds()) for s in sups],
            **journey_summary,
            **scale_summary,
            **kv_summary,
            **pk_summary,
            **ru_summary,
            **slo_summary,
        }
    finally:
        faults.reset()
        slo_eng.shutdown()
        drained = stack.drain(deadline_s=60.0)
    assert drained, "final drain dropped work"
    # zero leaked pages: every build of every supervisor — the killed
    # ones (unwound by the death path) and the final drained ones —
    # ends with an internally-consistent allocator and no page still
    # referenced (shutdown/death deref every request and cache entry)
    for e in engines_built:
        e.shutdown()
        e._page_alloc.check()
        assert e._page_alloc.n_used == 0, \
            f"leaked pages: {e._page_alloc!r}"
        # zero leaked adapter pins, every build (death + drain paths
        # both unpin; a leak would keep refs > 0 here)
        e._adapters.check()
    # the kv-tier builds too: every build — the killed one and the
    # drained one — ends with zero pages referenced (ISSUE 18)
    for e in kv_engines:
        e.shutdown()
        e._page_alloc.check()
        assert e._page_alloc.n_used == 0, \
            f"leaked pages in a kv-tier build: {e._page_alloc!r}"
    # and the pallas-kernel builds (ISSUE 19): the fused read borrows
    # pages through the same allocator — kernel on/off must not change
    # the zero-leak invariant
    for e in pk_engines:
        e.shutdown()
        e._page_alloc.check()
        assert e._page_alloc.n_used == 0, \
            f"leaked pages in a pallas build: {e._page_alloc!r}"
    # fresh adapter banks per rebuild: every build got its OWN residency
    # (stale bank reuse across pools is impossible by construction)
    assert len({id(e._adapters) for e in engines_built}) == \
        len(engines_built), "a rebuild reused a residency tracker"
    # HBM-ledger conservation (ISSUE 14; the byte analogue of the
    # zero-leaked-pages assert): every engine build registered fresh
    # owner rows (weights + kv_pool + adapter_bank + prefix_cache per
    # build), every teardown — killed or drained — released them, so
    # after the kill matrix the ledger holds ZERO serving bytes
    from paddle_tpu.observability import perfscope
    led = perfscope.ledger()
    snap = led.snapshot()
    assert snap["total"] == 0 and not snap["rows"], \
        f"leaked ledger bytes after the kill matrix: {snap}"
    # every build that SERVED registered its 4 owner rows; builds killed
    # or drained before their first admission never built pools (lazy)
    # and legitimately register fewer — the floor is the two seed builds
    assert led.registered_total >= 8, \
        (led.registered_total, len(engines_built))
    assert led.released_total == led.registered_total, snap
    summary["ledger_rows_cycled"] = led.registered_total
    summary["engine_builds_checked"] = len(engines_built)
    summary["drained"] = True
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
