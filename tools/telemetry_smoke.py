"""Telemetry-on smoke lane: run a small tier-1 subset with every
observability layer forced ON so the instrumented paths can't silently rot
(ISSUE 2 satellite; the tier-1 gate itself runs telemetry-off).

    python tools/telemetry_smoke.py            # default subset
    python tools/telemetry_smoke.py tests/test_io.py   # explicit subset

Forces PADDLE_TPU_TELEMETRY=1 (metrics registry + op-dispatch hook +
retrace sentinel + step metrics live) on top of the always-on span/flight
layer, and a 60 s step watchdog so the watchdog arm/disarm path in the
SPMD step executes on every train-step test.  Exit code is pytest's.
"""
from __future__ import annotations

import os
import subprocess
import sys

# the subset exercises every instrumented subsystem: op dispatch + spans +
# chrome merge (observability), dataloader waits (io), to_static compiles
# (jit), checkpoint phases, the SPMD step + collectives (distributed)
DEFAULT_SUBSET = [
    "tests/test_observability.py",
    "tests/test_io.py",
    "tests/test_jit_static.py",
    "tests/test_checkpoint.py",
    "tests/test_distributed.py",
    "tests/test_serving.py",
]


def main() -> int:
    targets = sys.argv[1:] or DEFAULT_SUBSET
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PADDLE_TPU_TELEMETRY": "1",
        "PADDLE_TPU_STEP_TIMEOUT_S": env.get(
            "PADDLE_TPU_STEP_TIMEOUT_S", "60"),
    })
    cmd = [sys.executable, "-m", "pytest", "-q", "-m", "not slow",
           "-p", "no:cacheprovider", *targets]
    print("telemetry smoke lane:", " ".join(cmd), file=sys.stderr)
    return subprocess.call(cmd, env=env,
                           cwd=os.path.dirname(os.path.dirname(
                               os.path.abspath(__file__))))


if __name__ == "__main__":
    sys.exit(main())
