"""Telemetry-on smoke lane: run a small tier-1 subset with every
observability layer forced ON so the instrumented paths can't silently rot
(ISSUE 2 satellite; the tier-1 gate itself runs telemetry-off).

    python tools/telemetry_smoke.py            # default subset + prefetch lane
    python tools/telemetry_smoke.py tests/test_io.py   # explicit subset only

Forces PADDLE_TPU_TELEMETRY=1 (metrics registry + op-dispatch hook +
retrace sentinel + step metrics live) on top of the always-on span/flight
layer, and a 60 s step watchdog so the watchdog arm/disarm path in the
SPMD step executes on every train-step test.  With the default subset it
additionally runs the prefetch-on training lane (ISSUE 4 satellite): a
tiny hapi fit through DevicePrefetcher that must complete AND export the
input-pipeline metrics (host_input_wait counter, buffer-occupancy gauge),
the tpu-lint ratchet lane (ISSUE 7) and the gateway lane (ISSUE 8:
mixed-tenant HTTP traffic through tools/gateway_smoke.py).
Exit code is pytest's, or 1 if any extra lane fails.
"""
from __future__ import annotations

import os
import subprocess
import sys

# the subset exercises every instrumented subsystem: op dispatch + spans +
# chrome merge (observability), dataloader waits + prefetch (io), to_static
# compiles (jit), checkpoint phases, the SPMD step + collectives
# (distributed)
DEFAULT_SUBSET = [
    "tests/test_observability.py",
    "tests/test_io.py",
    "tests/test_prefetch.py",
    "tests/test_jit_static.py",
    "tests/test_checkpoint.py",
    "tests/test_distributed.py",
    "tests/test_serving.py",
    "tests/test_decode_fastpath.py",
    "tests/test_paged_kv.py",
    "tests/test_gateway.py",
    "tests/test_self_healing.py",
    "tests/test_robustness.py",
    "tests/test_multi_lora.py",
    "tests/test_journey.py",
    "tests/test_perfscope.py",
    "tests/test_autoscale.py",
    "tests/test_slo.py",
    "tests/test_capture.py",
    "tests/test_kv_tier.py",
    "tests/test_rollout.py",
]

# decode fast-path lane (ISSUE 10): prefix cache + speculation + int8 KV
# + device sampling composed on one engine with telemetry live — the new
# counters/gauges must export, flight must record the fast-path events,
# and decode must stay at ONE compiled signature.
FASTPATH_LANE = r"""
import numpy as np
import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.models import build_gpt, gpt_config
from paddle_tpu.observability import flight
from paddle_tpu.serving import Engine
from paddle_tpu.serving.engine import (
    SERVING_KV_POOL_BYTES, SERVING_PREFIX_HITS, SERVING_PREFIX_MISSES,
    SERVING_SPEC_ACCEPTED, SERVING_SPEC_DRAFTED)

assert obs.enabled(), "PADDLE_TPU_TELEMETRY=1 must bootstrap telemetry"
cfg = gpt_config("gpt-tiny", max_position_embeddings=128,
                 hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
paddle.seed(0)
model = build_gpt(cfg)
model.eval()
rs = np.random.RandomState(0)
shared = rs.randint(0, cfg.vocab_size, 12).astype(np.int64)
prompts = [np.concatenate([shared, rs.randint(0, cfg.vocab_size, 3)
                           .astype(np.int64)]) for _ in range(5)]
eng = Engine(model, max_slots=2, max_len=64, prefix_cache=True,
             prefix_block=4, speculative_k=3, kv_dtype="int8",
             prefill_batch=1)
outs = [eng.submit(p, max_new_tokens=6).result(timeout=300)
        for p in prompts]
st = eng.stats()
eng.shutdown()
assert all(o.shape == (6,) for o in outs)
assert st["decode_compiles"] == 1, st
assert st["prefix_hits"] > 0 and st["spec_accepted"] > 0, st
assert st["kv_pool_bytes"] > 0, st
d = obs.dump()
for name in (SERVING_PREFIX_HITS, SERVING_PREFIX_MISSES,
             SERVING_SPEC_DRAFTED, SERVING_SPEC_ACCEPTED):
    assert name in d["counters"], (name, sorted(d["counters"]))
assert SERVING_KV_POOL_BYTES in d["gauges"]
text = obs.to_prometheus_text()
assert SERVING_PREFIX_HITS in text and SERVING_KV_POOL_BYTES in text
names = {e["name"] for e in flight.events("serving")}
assert {"prefix_admit", "prefix_insert", "spec_verify"} <= names, names
print("fast-path lane ok:", {
    "prefix_hits": st["prefix_hits"], "spec_accepted": st["spec_accepted"],
    "kv_pool_bytes": st["kv_pool_bytes"],
    "decode_compiles": st["decode_compiles"]})
"""

# multi-adapter lane (ISSUE 12): two tenants on two LoRA adapters
# through the HTTP gateway with telemetry live — the per-adapter
# gauges/counters must export, cold loads hit the flight recorder, and
# decode stays at ONE compiled signature with the adapter path on.
MULTI_LORA_LANE = r"""
import http.client, json
import numpy as np
import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.models import build_gpt, gpt_config
from paddle_tpu.observability import flight
from paddle_tpu.serving import AdapterRegistry, Engine, make_lora
from paddle_tpu.serving.engine import (
    SERVING_ADAPTER_LOADS, SERVING_ADAPTER_TOKENS, SERVING_ADAPTER_TTFT,
    SERVING_ADAPTERS_RESIDENT)
from paddle_tpu.serving.gateway import TenantConfig, start_gateway

assert obs.enabled(), "PADDLE_TPU_TELEMETRY=1 must bootstrap telemetry"
cfg = gpt_config("gpt-tiny", max_position_embeddings=128,
                 hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
paddle.seed(0)
model = build_gpt(cfg)
model.eval()
reg = AdapterRegistry(model, max_resident=2, max_rank=8)
reg.register(make_lora(cfg, rank=4, seed=1, name="tenant-a-model",
                       std=0.4))
reg.register(make_lora(cfg, rank=4, seed=2, name="tenant-b-model",
                       std=0.4))
eng = Engine(model, max_slots=2, max_len=48, adapters=reg)
stack = start_gateway(
    [eng], tenants=[TenantConfig("ta"), TenantConfig("tb")],
    model_name="base")
try:
    outs = {}
    for tenant, mdl in (("ta", "tenant-a-model"), ("tb", "tenant-b-model"),
                        ("ta", None)):
        conn = http.client.HTTPConnection("127.0.0.1", stack.port,
                                          timeout=300)
        payload = {"prompt": [3, 5, 7, 9], "max_tokens": 4}
        if mdl is not None:
            payload["model"] = mdl
        conn.request("POST", "/v1/completions",
                     json.dumps(payload).encode(),
                     {"Content-Type": "application/json",
                      "X-Tenant": tenant})
        r = conn.getresponse()
        body = json.loads(r.read())
        conn.close()
        assert r.status == 200, (r.status, body)
        outs[(tenant, mdl)] = body["choices"][0]["token_ids"]
    assert outs[("ta", "tenant-a-model")] != outs[("tb", "tenant-b-model")]
    st = eng.stats()
    assert st["decode_compiles"] == 1, st
    assert st["adapter_loads"] == 2 and st["adapters_resident"] == 2, st
finally:
    stack.close()
    eng.shutdown()
d = obs.dump()
for name in (SERVING_ADAPTER_LOADS, SERVING_ADAPTER_TOKENS):
    assert name in d["counters"], (name, sorted(d["counters"]))
assert SERVING_ADAPTERS_RESIDENT in d["gauges"]
assert SERVING_ADAPTER_TTFT in d["histograms"]
text = obs.to_prometheus_text()
assert SERVING_ADAPTER_TOKENS in text and SERVING_ADAPTERS_RESIDENT in text
names = {e["name"] for e in flight.events("serving")}
assert "adapter_load" in names, names
print("multi-lora lane ok:", {
    "adapter_loads": st["adapter_loads"],
    "resident": st["adapters_resident"],
    "decode_compiles": st["decode_compiles"]})
"""

# journey lane (ISSUE 13): mixed-tenant HTTP traffic with journeys live —
# every request's phase partition must sum to its client-observed wall
# time (the attribution invariant, end to end over a real socket), the
# journey id round-trips via X-Request-Id, /debug/requests serves the
# window, window_stats() TTFT percentiles agree with the per-request
# timelines they aggregate, the chrome-trace export parses, and decode
# stays at ONE compiled signature with journeys on.
JOURNEY_LANE = r"""
import http.client, json, time
import numpy as np
import paddle_tpu as paddle
from paddle_tpu.models import build_gpt, gpt_config
from paddle_tpu.serving import Engine
from paddle_tpu.serving.gateway import TenantConfig, start_gateway
from tools.journey_report import chrome_events_from_timelines, summarize

cfg = gpt_config("gpt-tiny", max_position_embeddings=128,
                 hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
paddle.seed(0)
model = build_gpt(cfg)
model.eval()
eng = Engine(model, max_slots=2, max_len=64)
stack = start_gateway(
    [eng], tenants=[TenantConfig("ta", priority="interactive"),
                    TenantConfig("tb", priority="batch")])
walls = {}
try:
    rs = np.random.RandomState(3)
    for i in range(6):
        tenant = "ta" if i % 2 == 0 else "tb"
        rid = f"smoke-{i}"
        prompt = [int(t) for t in rs.randint(0, cfg.vocab_size, 3 + i)]
        conn = http.client.HTTPConnection("127.0.0.1", stack.port,
                                          timeout=300)
        t0 = time.perf_counter()
        conn.request("POST", "/v1/completions",
                     json.dumps({"prompt": prompt, "max_tokens": 4,
                                 "stream": i % 3 == 0}).encode(),
                     {"Content-Type": "application/json",
                      "X-Tenant": tenant, "X-Request-Id": rid})
        r = conn.getresponse()
        raw = r.read()
        walls[rid] = (time.perf_counter() - t0) * 1e3
        conn.close()
        assert r.status == 200, (r.status, raw)
        assert dict(r.getheaders()).get("X-Request-Id") == rid
        if i % 3 == 0:
            assert b'"request_id": "%s"' % rid.encode() in raw or \
                rid in raw.decode(), "SSE finish event must echo the id"
    time.sleep(0.2)
    conn = http.client.HTTPConnection("127.0.0.1", stack.port, timeout=60)
    conn.request("GET", "/debug/requests?last=16")
    payload = json.loads(conn.getresponse().read())
    conn.close()
    tls = payload["requests"]
    assert len(tls) == 6, [t["id"] for t in tls]
    ttfts = []
    for tl in tls:
        parts = sum(p["dur_ms"] for p in tl["phases"])
        assert abs(parts - tl["wall_ms"]) < 0.02, (tl["id"], parts,
                                                   tl["wall_ms"])
        wall_client = walls[tl["id"]]
        assert abs(tl["wall_ms"] - wall_client) <= \
            0.05 * wall_client + 5.0, (tl["id"], tl["wall_ms"], wall_client)
        starts = [p["t_ms"] for p in tl["phases"]]
        assert starts == sorted(starts), tl["id"]
        for a, b in zip(tl["phases"], tl["phases"][1:]):
            assert abs(a["t_ms"] + a["dur_ms"] - b["t_ms"]) < 0.01, \
                (tl["id"], "gap")
        assert tl["outcome"] == "ok" and tl["ttft_ms"] is not None
        ttfts.append(tl["ttft_ms"] / 1e3)
    # window feed agrees with the per-request timelines it aggregates
    w = stack.gateway.window_stats()
    assert w["requests"] == 6 and w["ttft_s"]["n"] == 6, w
    ttfts.sort()
    assert abs(w["ttft_s"]["p50"] -
               (ttfts[2] + ttfts[3]) / 2) < 1e-3, (w["ttft_s"], ttfts)
    assert w["ttft_s"]["p99"] <= ttfts[-1] + 1e-6
    # one id fetch + chrome export parses
    conn = http.client.HTTPConnection("127.0.0.1", stack.port, timeout=60)
    conn.request("GET", "/debug/requests/smoke-0")
    one = json.loads(conn.getresponse().read())
    conn.close()
    assert one["id"] == "smoke-0"
    events = chrome_events_from_timelines(tls)
    blob = json.dumps({"traceEvents": events})
    parsed = json.loads(blob)
    assert len(parsed["traceEvents"]) == sum(len(t["phases"]) for t in tls)
    assert all(e["ph"] == "X" and e["cat"] == "journey"
               for e in parsed["traceEvents"])
    st = eng.stats()
    assert st["decode_compiles"] == 1, st
    print("journey lane ok:", {
        "requests": w["requests"],
        "ttft_p50_ms": round(w["ttft_s"]["p50"] * 1e3, 1),
        "phase_share": summarize(tls) and list(summarize(tls))[:3],
        "decode_compiles": st["decode_compiles"]})
finally:
    stack.close()
    eng.shutdown()
"""

# perfscope lane (ISSUE 14): serving traffic with device-time sampling ON
# (every dispatch timed) — the per-program roofline gauges must export,
# the reported decode MFU/BW fractions must match the cost_analysis
# expectation, the HBM ledger must reconcile with the pre-existing
# kv_pool_bytes / weight_bytes exports and drain to zero at shutdown,
# the chrome device lane must parse, and decode stays at ONE signature.
PERFSCOPE_LANE = r"""
import http.client, json
import numpy as np
import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.models import build_gpt, gpt_config
from paddle_tpu.observability import perfscope
from paddle_tpu.serving import Engine
from paddle_tpu.serving.gateway import TenantConfig, start_gateway
from tools.perf_report import format_memory, format_perf

assert obs.enabled(), "PADDLE_TPU_TELEMETRY=1 must bootstrap telemetry"
perfscope.set_sample_every(1)
cfg = gpt_config("gpt-tiny", max_position_embeddings=128,
                 hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
paddle.seed(0)
model = build_gpt(cfg)
model.eval()
eng = Engine(model, max_slots=2, max_len=64, prefix_cache=True,
             prefix_block=4)
stack = start_gateway([eng], tenants=[TenantConfig("ta")])
try:
    rs = np.random.RandomState(7)
    for i in range(4):
        conn = http.client.HTTPConnection("127.0.0.1", stack.port,
                                          timeout=300)
        prompt = [int(t) for t in rs.randint(1, cfg.vocab_size, 5 + i)]
        conn.request("POST", "/v1/completions",
                     json.dumps({"prompt": prompt,
                                 "max_tokens": 4}).encode(),
                     {"Content-Type": "application/json", "X-Tenant": "ta"})
        r = conn.getresponse()
        body = r.read()
        conn.close()
        assert r.status == 200, (r.status, body)
    st = eng.stats()
    assert st["decode_compiles"] == 1, st

    conn = http.client.HTTPConnection("127.0.0.1", stack.port, timeout=60)
    conn.request("GET", "/debug/perf")
    perf = json.loads(conn.getresponse().read())
    conn.close()
    dec = next(p for p in perf["programs"]
               if p["program"] == "serving.decode")
    assert dec["sampled"] > 0 and dec["signatures"] == 1, dec
    mean_dt = dec["device_s"] / dec["sampled"]
    expect = dec["flops"] / (mean_dt * perf["peak_flops"])
    assert abs(dec["mfu"] - expect) <= 0.02 * expect + 1e-9, (dec, expect)

    conn = http.client.HTTPConnection("127.0.0.1", stack.port, timeout=60)
    conn.request("GET", "/debug/memory")
    mem = json.loads(conn.getresponse().read())
    conn.close()
    assert mem["owners"]["kv_pool"] == eng.pool_bytes() == \
        st["kv_pool_bytes"], (mem["owners"], st["kv_pool_bytes"])
    assert mem["owners"]["weights"] == eng.weight_bytes() == \
        st["weight_bytes"], (mem["owners"], st["weight_bytes"])
    assert mem["total_tracked"] == sum(mem["owners"].values()), mem

    conn = http.client.HTTPConnection("127.0.0.1", stack.port, timeout=60)
    conn.request("GET", "/metrics")
    text = conn.getresponse().read().decode()
    conn.close()
    for name in ("paddle_tpu_device_program_seconds",
                 "paddle_tpu_device_program_mfu",
                 "paddle_tpu_device_program_hbm_bw_frac",
                 "paddle_tpu_hbm_bytes"):
        assert name in text, name

    events = perfscope.chrome_events()
    parsed = json.loads(json.dumps({"traceEvents": events}))
    assert parsed["traceEvents"] and all(
        e["ph"] == "X" and e["cat"] == "device"
        for e in parsed["traceEvents"])
    for line in format_perf(perf) + format_memory(mem):
        print(line)
finally:
    stack.close()
    eng.shutdown()
led = perfscope.ledger().owner_bytes()
assert all(v == 0 for v in led.values()), f"leaked ledger bytes: {led}"
print("perfscope lane ok:", {
    "decode_sampled": dec["sampled"], "decode_mfu": dec["mfu"],
    "owners": list(mem["owners"]), "decode_compiles": st["decode_compiles"]})
"""

# autoscale lane (ISSUE 15): the closed loop twice over — (a) sim mode:
# the seeded flash-crowd trace through FleetSim with the live ScalePolicy
# (SLO attainment >= best static fleet at fewer replica-seconds, zero
# flaps); (b) real HTTP: a flash burst against a one-replica stack makes
# the autoscaler build and route a second replica, idle drains it back
# out (drain-before-remove), fleet metrics and /debug/fleet export, and
# decode stays at ONE compiled signature per engine build.
AUTOSCALE_LANE = r"""
import http.client, json, threading, time
import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.models import build_gpt, gpt_config
from paddle_tpu.observability import flight
from paddle_tpu.serving import Autoscaler, Engine, FleetSim, ScalePolicy
from paddle_tpu.serving.autoscaler import (FLEET_ALIVE, FLEET_DESIRED,
                                           FLEET_DRAINING,
                                           FLEET_SCALE_EVENTS)
from paddle_tpu.serving.gateway import TenantConfig, start_gateway
from tools.load_gen import make_trace

assert obs.enabled(), "PADDLE_TPU_TELEMETRY=1 must bootstrap telemetry"

# -- sim-mode closed loop (virtual time, no devices) --------------------
trace = make_trace(60.0, 4.0, seed=0, flash_mult=8.0, flash_duration_s=10.0,
                   prompt_mean=12.0, out_mean=10.0, deadline_s=3.0)
pol = ScalePolicy(slo_ttft_s=1.0, up_ticks=2, idle_ticks=8,
                  cooldown_up_s=2.0, cooldown_down_s=6.0)
auto_sim = FleetSim(pol, min_replicas=1, max_replicas=5,
                    slots_per_replica=4, prefill_s=0.05, token_s=0.01,
                    build_s=1.5).run(trace)
statics = [FleetSim(None, min_replicas=n, max_replicas=n, start_replicas=n,
                    slots_per_replica=4, prefill_s=0.05,
                    token_s=0.01).run(trace) for n in range(1, 6)]
best = max(s["slo_attainment"] for s in statics)
cheapest = min(s["replica_seconds"] for s in statics
               if s["slo_attainment"] >= best)
assert auto_sim["slo_attainment"] >= best - 1e-9, (auto_sim, best)
assert auto_sim["replica_seconds"] < cheapest, (auto_sim, cheapest)
assert auto_sim["flaps"] == 0, auto_sim["events"]

# -- real HTTP flash burst ----------------------------------------------
cfg = gpt_config("gpt-tiny", max_position_embeddings=128,
                 hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
built = []


def factory():
    # one model instance per replica: a scale-up build traces its jit
    # programs while the loaded replica may be compiling a new prefill
    # bucket — concurrent tracing over one shared module is unsupported
    paddle.seed(0)
    model = build_gpt(cfg)
    model.eval()
    e = Engine(model, max_slots=2, max_len=48, max_queue=32)
    built.append(e)
    return e


stack = start_gateway([factory()], own_engines=True,
                      tenants=[TenantConfig("t", max_queue=64)],
                      window_s=2.0)
auto = Autoscaler(
    stack, factory, min_replicas=1, max_replicas=2,
    policy=ScalePolicy(slo_ttft_s=30.0, queue_wait_p99_s=0.05, up_ticks=1,
                       idle_ticks=3, cooldown_up_s=0.3,
                       cooldown_down_s=0.8, idle_util=0.99),
    poll_interval_s=0.05, drain_deadline_s=10.0, build_s_hint=2.0)
statuses = []
lock = threading.Lock()


def one(i):
    conn = http.client.HTTPConnection("127.0.0.1", stack.port, timeout=300)
    conn.request("POST", "/v1/completions",
                 json.dumps({"prompt": [1 + i % 7, 2, 3],
                             "max_tokens": 4}).encode(),
                 {"Content-Type": "application/json", "X-Tenant": "t"})
    r = conn.getresponse()
    r.read()
    conn.close()
    with lock:
        statuses.append(r.status)


def wait(pred, timeout=120.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return False


try:
    one(0)                                     # warm the first replica
    threads = [threading.Thread(target=one, args=(i,)) for i in range(16)]
    for th in threads:
        th.start()
    assert wait(lambda: len(stack.gateway.router.names) == 2), \
        "flash burst never triggered a scale-up"
    for th in threads:
        th.join(timeout=300)
    assert statuses and all(s == 200 for s in statuses), statuses
    assert wait(lambda: len(stack.gateway.router.names) == 1), \
        "idle never drained the flash replica back out"
    def fleet_state():
        c = http.client.HTTPConnection("127.0.0.1", stack.port, timeout=60)
        c.request("GET", "/debug/fleet")
        f = json.loads(c.getresponse().read())
        c.close()
        return f
    # the router shrinks when the drain completes; desired settles on
    # the autoscaler's next tick
    assert wait(lambda: (lambda f: f["alive"] == 1
                         and f["autoscaler"]["desired"] == 1)(fleet_state()))
    fleet = fleet_state()
    # >= 1: straggler load can re-breach after the first drain and fire
    # a second up/down cycle before idle settles
    assert fleet["autoscaler"]["builds"] >= 1
    conn = http.client.HTTPConnection("127.0.0.1", stack.port, timeout=60)
    conn.request("GET", "/metrics")
    text = conn.getresponse().read().decode()
    conn.close()
    for name in (FLEET_DESIRED, FLEET_ALIVE, FLEET_DRAINING,
                 FLEET_SCALE_EVENTS):
        assert name in text, name
    ev = {e["name"] for e in flight.events("autoscaler")}
    assert {"scale_up", "scale_down"} <= ev, ev
    assert len(built) >= 2
    assert all(e.compile_stats()["decode_compiles"] <= 1 for e in built), \
        [e.compile_stats() for e in built]
finally:
    auto.shutdown()
    stack.close()
    for e in built:
        e.shutdown()
print("autoscale lane ok:", {
    "sim_attainment": auto_sim["slo_attainment"],
    "sim_replica_seconds": auto_sim["replica_seconds"],
    "sim_vs_best_static": cheapest,
    "http_requests": len(statuses),
    "builds": len(built)})
"""

# SLO lane (ISSUE 16): burn-rate alerting end to end.  Sim mode first — a
# flash crowd over an undersized fleet must fire the fast-burn rule and
# resolve after the autoscaler absorbs it, while a steady diurnal trace
# fires nothing (zero false positives).  Then a real HTTP gateway with an
# impossible ttft objective: the alert fires, the incident bundle parses
# with all three telemetry planes correlated, the renderer formats it,
# the slo gauges export, and decode keeps ONE compiled signature.
SLO_LANE = r"""
import http.client, json, time
import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.models import build_gpt, gpt_config
from paddle_tpu.observability.slo import (INCIDENT_SCHEMA, SLO_ALERTS,
                                          SLO_ATTAINMENT,
                                          SLO_BUDGET_REMAINING,
                                          SLO_BURN_RATE, SloObjective)
from paddle_tpu.serving import FleetSim, ScalePolicy
from paddle_tpu.serving.gateway import TenantConfig, start_gateway
from tools.incident_report import render
from tools.load_gen import make_trace

assert obs.enabled(), "PADDLE_TPU_TELEMETRY=1 must bootstrap telemetry"

# -- sim mode: flash crowd fires fast-burn, resolves after absorb -------
ev_obj = SloObjective("sim-ttft", "ttft_p99", 0.9, threshold_s=1.55,
                      fast_window_s=3.0, fast_burn=6.0, slow_window_s=15.0,
                      slow_burn=2.0, fire_ticks=2, resolve_ticks=6,
                      min_events=4)
pol = ScalePolicy(slo_ttft_s=1.55, headroom_frac=0.4, up_ticks=1,
                  idle_ticks=8, cooldown_up_s=4.0, cooldown_down_s=3.0)
flash = make_trace(60.0, 20.0, seed=0, flash_mult=2.5, flash_at=0.25,
                   flash_duration_s=10.0, prompt_mean=12.0, out_mean=10.0,
                   out_max=48)


def sim(trace, start_replicas):
    from paddle_tpu.observability.slo import SloEvaluator
    return FleetSim(pol, min_replicas=1, max_replicas=6,
                    start_replicas=start_replicas, slots_per_replica=4,
                    prefill_s=0.05, token_s=0.01, build_s=2.0,
                    policy_poll_s=0.25, window_s=5.0,
                    slo_evaluator=SloEvaluator([ev_obj])).run(trace)


hot = sim(flash, 1)
slo = hot["slo"]
assert slo["fired"] >= 1, slo
assert slo["resolved"] == slo["fired"], slo
firings = [t for t in slo["transitions"] if t["to"] == "firing"]
assert all(t["rule"] == "fast" for t in firings), firings
ups = [e for e in hot["events"] if e["direction"] == "up"]
resolves = [t for t in slo["transitions"] if t["to"] == "resolved"]
assert ups and resolves and resolves[0]["t"] > ups[0]["t"], \
    (ups[:1], resolves[:1])

steady = sim(make_trace(60.0, 8.0, seed=1, flash_mult=1.0), 2)
assert steady["slo"]["fired"] == 0, steady["slo"]

# -- real HTTP gateway: alert -> incident bundle -> renderer ------------
cfg = gpt_config("gpt-tiny", max_position_embeddings=128,
                 hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
paddle.seed(0)
model = build_gpt(cfg)
model.eval()
from paddle_tpu.serving import Engine
eng = Engine(model, max_slots=2, max_len=48, max_queue=32)
obj = SloObjective("ttft-tight", "ttft_p99", 0.9, threshold_s=1e-4,
                   fast_window_s=5.0, fast_burn=5.0, slow_window_s=30.0,
                   slow_burn=2.0, fire_ticks=2, resolve_ticks=2,
                   min_events=3)
stack = start_gateway([eng], tenants=[TenantConfig("acme", max_queue=64)],
                      window_s=30.0, slo_objectives=[obj], slo_tick_s=0.1)


def get(path):
    conn = http.client.HTTPConnection("127.0.0.1", stack.port, timeout=60)
    conn.request("GET", path)
    r = conn.getresponse()
    body = r.read()
    conn.close()
    return r.status, body


try:
    for i in range(6):
        conn = http.client.HTTPConnection("127.0.0.1", stack.port,
                                          timeout=300)
        conn.request("POST", "/v1/completions",
                     json.dumps({"prompt": [1 + i, 2, 3],
                                 "max_tokens": 4}).encode(),
                     {"Content-Type": "application/json",
                      "X-Tenant": "acme"})
        conn.getresponse().read()
        conn.close()
    deadline = time.time() + 60.0
    state = None
    while time.time() < deadline:
        state = json.loads(get("/debug/slo")[1])
        if (any(t["to"] == "firing" for t in state["transitions"])
                and state["incidents"]):
            break
        time.sleep(0.1)
    assert state and state["incidents"], state
    inc_id = state["incidents"][-1]["id"]
    status, body = get("/debug/incidents/" + inc_id)
    assert status == 200
    bundle = json.loads(body)
    assert bundle["schema"] == INCIDENT_SCHEMA, bundle["schema"]
    assert bundle["incident"]["objective"] == "ttft-tight"
    assert bundle["window"]["global"]["requests"] >= 3, bundle["window"]
    assert "acme" in bundle["window"]["by_tenant"]["keys"]
    assert bundle["slowest_journeys"], "no journey plane in bundle"
    assert bundle["fleet"]["alive"] == 1, bundle["fleet"]
    sheet = render(bundle)
    assert "ttft-tight" in sheet and "tenant:acme" in sheet, sheet
    text = get("/metrics")[1].decode()
    for name in (SLO_ATTAINMENT, SLO_BUDGET_REMAINING, SLO_BURN_RATE,
                 SLO_ALERTS):
        assert name in text, name
    assert eng.compile_stats()["decode_compiles"] == 1, eng.compile_stats()
finally:
    stack.close()
    eng.shutdown()
print("slo lane ok:", {
    "sim_fired": slo["fired"], "sim_resolved": slo["resolved"],
    "steady_fired": steady["slo"]["fired"],
    "incident": inc_id})
"""

# traffic capture lane (ISSUE 17): a seeded mixed-tenant HTTP run through
# a full-mode recorder — /debug/capture serves it, a replay through
# replay_capture.to_trace + load_gen.replay_http is token-identical
# (greedy) and seed-exact (sampled), fit_trace recovers a trace FleetSim
# accepts, and decode stays ONE compiled program with capture on.
CAPTURE_LANE = r"""
import http.client, json
import numpy as np
import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.models import build_gpt, gpt_config
from paddle_tpu.observability.capture import fit_params, fit_trace
from paddle_tpu.serving import Engine, FleetSim, ScalePolicy
from paddle_tpu.serving.gateway import TenantConfig, start_gateway
from tools.load_gen import replay_http
from tools.replay_capture import to_trace

assert obs.enabled(), "PADDLE_TPU_TELEMETRY=1 must bootstrap telemetry"

cfg = gpt_config("gpt-tiny", max_position_embeddings=128,
                 hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
paddle.seed(0)
model = build_gpt(cfg)
model.eval()
eng = Engine(model, max_slots=2, max_len=48, max_queue=32)
stack = start_gateway([eng],
                      tenants=[TenantConfig("acme",
                                            priority="interactive"),
                               TenantConfig("bulk", priority="batch")],
                      capture_mode="full", capture_entries=512)
rs = np.random.RandomState(7)
try:
    url = f"http://127.0.0.1:{stack.port}"
    sent = {}
    for i in range(10):
        payload = {"prompt": [int(x) for x in rs.randint(1, 60, 3 + i % 4)],
                   "max_tokens": 3}
        if i % 2:
            payload.update(temperature=0.8, top_k=5, seed=200 + i)
        conn = http.client.HTTPConnection("127.0.0.1", stack.port,
                                          timeout=300)
        conn.request("POST", "/v1/completions", json.dumps(payload).encode(),
                     {"Content-Type": "application/json",
                      "X-Tenant": "acme" if i % 3 else "bulk"})
        r = conn.getresponse()
        hdrs = dict(r.getheaders())
        body = json.loads(r.read())
        conn.close()
        assert r.status == 200, body
        sent[hdrs["X-Request-Id"]] = body["choices"][0]["token_ids"]

    conn = http.client.HTTPConnection("127.0.0.1", stack.port, timeout=60)
    conn.request("GET", "/debug/capture?last=100")
    dump = json.loads(conn.getresponse().read())
    conn.close()
    window = dump["window"]
    assert dump["mode"] == "full" and len(window) == 10, dump["filtered"]
    assert {e["tenant"] for e in window} == {"acme", "bulk"}

    trace = to_trace(window, admitted_only=True)
    summary = replay_http(url, trace, collect_tokens=True, speed=20.0)
    assert summary["completed"] == 10 and summary["errors"] == 0, summary
    exact = 0
    for entry, res in zip(trace, summary["results"]):
        assert res["token_ids"] == sent[entry["journey_id"]], entry
        exact += 1

    p = fit_params(window)
    fitted = fit_trace(window, seed=1, params=p)
    res = FleetSim(ScalePolicy(up_ticks=1), min_replicas=1, max_replicas=4,
                   start_replicas=1, slots_per_replica=4, prefill_s=0.05,
                   token_s=0.01, build_s=2.0, policy_poll_s=0.25,
                   window_s=5.0).run(fitted)
    assert res["arrivals"] == len(fitted) > 0, res
    assert eng.compile_stats()["decode_compiles"] == 1, eng.compile_stats()
finally:
    stack.close()
    eng.shutdown()
print("capture lane ok:", {
    "captured": len(window), "replayed_exact": exact,
    "fitted_arrivals": len(fitted),
    "sim_peak_replicas": res["peak_replicas"]})
"""

# conversation lane (ISSUE 18): a two-turn /v1/chat/completions chat
# through a SUPERVISED replica with a forced eviction between the turns.
# Turn 1 demotes to the host-DRAM tier when filler traffic evicts it, the
# warm turn (history + reply + new user message) is served via host-tier
# promote — one decode signature — and the whole path exports: demote /
# promote counters through /metrics, the hbm ledger host_prefix owner
# row, the prefix_promote journey phase, and the capture conversation
# filter.
CONVERSATION_LANE = r"""
import http.client, json
import numpy as np
import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.models import build_gpt, gpt_config
from paddle_tpu.serving import Engine, EngineSupervisor, HostPrefixTier
from paddle_tpu.serving.engine import (SERVING_HOST_PREFIX_HITS,
                                       SERVING_HOST_PREFIX_PROMOTES)
from paddle_tpu.serving.gateway import TenantConfig, start_gateway
from paddle_tpu.serving.kv_tier import (SERVING_HOST_PREFIX_DEMOTES,
                                        SERVING_HOST_PREFIX_ENTRIES)

assert obs.enabled(), "PADDLE_TPU_TELEMETRY=1 must bootstrap telemetry"
cfg = gpt_config("gpt-tiny", max_position_embeddings=128,
                 hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
paddle.seed(0)
model = build_gpt(cfg)
model.eval()
tier = HostPrefixTier(capacity_mb=32, block=4)


def factory():
    return Engine(model, max_slots=2, max_len=48, max_queue=32,
                  prefix_cache=True, prefix_block=4, paged_kv=True,
                  num_pages=24, host_prefix=tier)


sup = EngineSupervisor(factory, name="conv0", poll_interval_s=0.02)
stack = start_gateway([sup], own_engines=True, names=["conv0"],
                      tenants=[TenantConfig("acme")], capture_mode="full")


def post(path, payload):
    conn = http.client.HTTPConnection("127.0.0.1", stack.port, timeout=300)
    conn.request("POST", path, json.dumps(payload).encode(),
                 {"Content-Type": "application/json", "X-Tenant": "acme"})
    r = conn.getresponse()
    body = r.read()
    conn.close()
    assert r.status == 200, (r.status, body)
    return body


def get(path):
    conn = http.client.HTTPConnection("127.0.0.1", stack.port, timeout=60)
    conn.request("GET", path)
    body = conn.getresponse().read()
    conn.close()
    return body


rs = np.random.RandomState(5)
u1 = [int(x) for x in rs.randint(1, cfg.vocab_size, 12)]
try:
    # turn 1: blocking chat
    b1 = json.loads(post("/v1/chat/completions",
                         {"messages": [{"role": "user", "content": u1}],
                          "max_tokens": 4, "conversation": "chat-1"}))
    assert b1["object"] == "chat.completion", b1
    assert b1["conversation"] == "chat-1", b1
    r1 = b1["choices"][0]["message"]["token_ids"]
    assert len(r1) == 4, b1
    # forced eviction between the turns: filler conversations overrun
    # the 24-page pool, so turn 1's entry demotes to the host tier
    for i in range(6):
        post("/v1/completions",
             {"prompt": [int(x) for x in rs.randint(1, cfg.vocab_size, 12)],
              "max_tokens": 4, "conversation": f"fill{i}"})
    assert tier.flush(), "spill worker never drained"
    assert len(tier) > 0, "no entry demoted to the host tier"
    # warm turn: the full history + the new user message, STREAMED
    u2 = [int(x) for x in rs.randint(1, cfg.vocab_size, 4)]
    msgs = [{"role": "user", "content": u1},
            {"role": "assistant", "content": r1},
            {"role": "user", "content": u2}]
    conn = http.client.HTTPConnection("127.0.0.1", stack.port, timeout=300)
    conn.request("POST", "/v1/chat/completions",
                 json.dumps({"messages": msgs, "max_tokens": 4,
                             "conversation": "chat-1",
                             "stream": True}).encode(),
                 {"Content-Type": "application/json", "X-Tenant": "acme"})
    r = conn.getresponse()
    assert r.status == 200, r.status
    warm_toks = []
    for line in r:
        if not line.startswith(b"data: "):
            continue
        data = line[6:].strip()
        if data == b"[DONE]":
            break
        ev = json.loads(data)
        assert ev["object"] == "chat.completion.chunk", ev
        warm_toks += ev["choices"][0]["delta"].get("token_ids", [])
    conn.close()
    assert len(warm_toks) == 4, warm_toks
    st = sup.stats()
    assert st["host_prefix_hits"] >= 1 and \
        st["host_prefix_promotes"] >= 1, st
    assert st["decode_compiles"] == 1, st
    # telemetry through the wire: tier counters + the ledger owner row
    text = get("/metrics").decode()
    for name in (SERVING_HOST_PREFIX_DEMOTES, SERVING_HOST_PREFIX_ENTRIES,
                 SERVING_HOST_PREFIX_HITS, SERVING_HOST_PREFIX_PROMOTES):
        assert name in text, name
    assert 'paddle_tpu_hbm_bytes{owner="host_prefix"}' in text
    # the warm turn's journey carries the prefix_promote phase
    tls = json.loads(get("/debug/requests?last=50"))["requests"]
    assert any(p["phase"] == "prefix_promote"
               for tl in tls for p in tl["phases"]), \
        [p["phase"] for tl in tls for p in tl["phases"]]
    # capture attribution: the conversation filter isolates the chat
    dump = json.loads(get("/debug/capture?conversation=chat-1"))
    assert len(dump["window"]) == 2, dump
    assert all(e["conversation"] == "chat-1" for e in dump["window"])
finally:
    stack.close()
tier.check()
tier.close()
assert tier.bytes_used == 0, tier.stats()
print("conversation lane ok:", {
    "host_prefix_hits": st["host_prefix_hits"],
    "host_prefix_promotes": st["host_prefix_promotes"],
    "demotes": tier.stats()["demotes"],
    "decode_compiles": st["decode_compiles"]})
"""

# rollout lane (ISSUE 20): a real-HTTP fleet of two upgraded in place by
# RolloutController while traffic is in flight — canary gate passes on
# live outcomes, every replica lands at the new revision (no mixed
# steady state), ZERO lost zero-token requests, the revision label
# exports through /metrics and /debug/fleet, old builds are torn down,
# and every build keeps ONE compiled decode signature.
ROLLOUT_LANE = r"""
import http.client, json, threading
import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.models import build_gpt, gpt_config
from paddle_tpu.observability import flight
from paddle_tpu.serving import CanaryGate, Engine, RolloutController
from paddle_tpu.serving.autoscaler import FLEET_ALIVE
from paddle_tpu.serving.gateway import TenantConfig, start_gateway
from paddle_tpu.serving.rollout import FLEET_ROLLOUTS

assert obs.enabled(), "PADDLE_TPU_TELEMETRY=1 must bootstrap telemetry"
cfg = gpt_config("gpt-tiny", max_position_embeddings=128,
                 hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
built = []


def factory_for_revision(revision):
    # one model instance per replica: rollout builds trace their jit
    # programs while the incumbents are still serving
    paddle.seed(0)
    model = build_gpt(cfg)
    model.eval()
    e = Engine(model, max_slots=2, max_len=48, max_queue=32)
    built.append((revision, e))
    return e


stack = start_gateway(
    [factory_for_revision("r0"), factory_for_revision("r0")],
    own_engines=True, tenants=[TenantConfig("t", max_queue=64)])
results = []
lock = threading.Lock()


def one(i):
    conn = http.client.HTTPConnection("127.0.0.1", stack.port, timeout=300)
    conn.request("POST", "/v1/completions",
                 json.dumps({"prompt": [1 + i % 7, 2, 3],
                             "max_tokens": 4}).encode(),
                 {"Content-Type": "application/json", "X-Tenant": "t"})
    r = conn.getresponse()
    body = r.read()
    n_tok = (len(json.loads(body)["choices"][0]["token_ids"])
             if r.status == 200 else 0)
    conn.close()
    with lock:
        results.append((r.status, n_tok))


def get(path):
    conn = http.client.HTTPConnection("127.0.0.1", stack.port, timeout=60)
    conn.request("GET", path)
    body = conn.getresponse().read()
    conn.close()
    return body


ctl = RolloutController(
    stack, factory_for_revision,
    gate=CanaryGate(min_requests=2, timeout_s=60.0, ttft_p99_ratio=50.0,
                    ttft_p99_floor_s=30.0),
    drain_deadline_s=30.0, build_s_hint=2.0)
try:
    one(0)                                # warm an incumbent
    old_builds = [e for _, e in built]
    ctl.start_rollout("r1")
    threads, i = [], 0
    while i < 60:                         # live load across the upgrade
        try:
            ctl.wait(0.05)
            break
        except TimeoutError:
            pass
        th = threading.Thread(target=one, args=(i,))
        th.start()
        threads.append(th)
        i += 1
    res = ctl.wait(timeout=600)
    for th in threads:
        th.join(timeout=300)
    assert res.ok and res.upgraded == 2, res
    # zero lost zero-token requests: everything in flight across the
    # upgrade completed with its full token budget
    with lock:
        snap = list(results)
    assert snap and all(s == 200 and n == 4 for s, n in snap), snap
    revs = stack.gateway.router.revisions()
    assert len(revs) == 2 and set(revs.values()) == {"r1"}, revs
    # the retired incumbents were torn down, one decode signature per
    # build — the upgrade never retraced anyone
    assert all(e._stop for e in old_builds)
    assert all(e.compile_stats()["decode_compiles"] <= 1
               for _, e in built), [e.compile_stats() for _, e in built]
    text = get("/metrics").decode()
    assert FLEET_ROLLOUTS in text and FLEET_ALIVE in text, text[:400]
    assert 'revision="r1"' in text, "revision label missing from /metrics"
    fleet = json.loads(get("/debug/fleet"))
    assert fleet["rollout"]["revision"] == "r1", fleet["rollout"]
    assert all(r["revision"] == "r1"
               for r in fleet["replicas"].values()), fleet["replicas"]
    names = {e["name"] for e in flight.events("rollout")}
    assert {"begin", "routed_in", "canary_passed", "retired",
            "done"} <= names, names
finally:
    ctl.shutdown()
    stack.close()
    for _, e in built:
        e.shutdown()
print("rollout lane ok:", {
    "requests": len(snap), "upgraded": res.upgraded,
    "builds": len(built),
    "revisions": sorted(set(revs.values()))})
"""

# prefetch-on training lane: fit a tiny model THROUGH DevicePrefetcher with
# telemetry live and assert the input-pipeline series were exported.  Runs
# in its own interpreter so the env-var bootstrap path is what's exercised.
PREFETCH_LANE = r"""
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import observability as obs
from paddle_tpu.hapi import Model
from paddle_tpu.io.dataset import Dataset
from paddle_tpu.observability import steps as steps_mod

assert obs.enabled(), "PADDLE_TPU_TELEMETRY=1 must bootstrap telemetry"


class DS(Dataset):
    def __getitem__(self, i):
        rs = np.random.RandomState(i)
        return rs.randn(4).astype("float32"), np.int64(i % 3)

    def __len__(self):
        return 16


paddle.seed(0)
net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 3))
model = Model(net)
model.prepare(optimizer=paddle.optimizer.Adam(
    parameters=model.parameters(), learning_rate=1e-3),
    loss=nn.CrossEntropyLoss())
model.fit(DS(), epochs=1, batch_size=4, verbose=0, shuffle=False,
          prefetch=True)

d = obs.dump()
assert steps_mod.HOST_INPUT_WAIT in d["counters"], \
    f"host input wait counter missing: {sorted(d['counters'])}"
assert steps_mod.PREFETCH_DEPTH in d["gauges"], \
    f"prefetch buffer-occupancy gauge missing: {sorted(d['gauges'])}"
assert steps_mod.PREFETCH_BATCHES in d["counters"], \
    f"prefetch batches counter missing: {sorted(d['counters'])}"
text = obs.to_prometheus_text()
assert steps_mod.HOST_INPUT_WAIT in text and steps_mod.PREFETCH_DEPTH in text
print("prefetch lane ok:", {k: d["counters"][k]
                            for k in (steps_mod.HOST_INPUT_WAIT,
                                      steps_mod.PREFETCH_BATCHES)})
"""


def main() -> int:
    explicit = bool(sys.argv[1:])
    targets = sys.argv[1:] or DEFAULT_SUBSET
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PADDLE_TPU_TELEMETRY": "1",
        "PADDLE_TPU_STEP_TIMEOUT_S": env.get(
            "PADDLE_TPU_STEP_TIMEOUT_S", "60"),
    })
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cmd = [sys.executable, "-m", "pytest", "-q", "-m", "not slow",
           "-p", "no:cacheprovider", *targets]
    print("telemetry smoke lane:", " ".join(cmd), file=sys.stderr)
    rc = subprocess.call(cmd, env=env, cwd=root)
    if not explicit:
        print("telemetry smoke: prefetch-on training lane", file=sys.stderr)
        lane_rc = subprocess.call([sys.executable, "-c", PREFETCH_LANE],
                                  env=env, cwd=root)
        if lane_rc != 0:
            print("prefetch lane FAILED", file=sys.stderr)
        rc = rc or lane_rc
        # decode fast-path lane (ISSUE 10): prefix cache + speculation +
        # int8 KV + device sampling with telemetry live — counters,
        # flight events, one decode signature
        print("telemetry smoke: decode fast-path lane", file=sys.stderr)
        fp_rc = subprocess.call([sys.executable, "-c", FASTPATH_LANE],
                                env=env, cwd=root)
        if fp_rc != 0:
            print("fast-path lane FAILED", file=sys.stderr)
        rc = rc or fp_rc
        # multi-adapter lane (ISSUE 12): two tenants on two LoRA
        # adapters through the gateway — per-adapter telemetry exports,
        # one decode signature with the adapter path live
        print("telemetry smoke: multi-lora lane", file=sys.stderr)
        ml_rc = subprocess.call([sys.executable, "-c", MULTI_LORA_LANE],
                                env=env, cwd=root)
        if ml_rc != 0:
            print("multi-lora lane FAILED", file=sys.stderr)
        rc = rc or ml_rc
        # journey lane (ISSUE 13): phase partition == client wall time
        # over a real socket, /debug/requests, window feed agreement,
        # chrome export, one decode signature with journeys on
        print("telemetry smoke: journey lane", file=sys.stderr)
        jn_rc = subprocess.call([sys.executable, "-c", JOURNEY_LANE],
                                env=env, cwd=root)
        if jn_rc != 0:
            print("journey lane FAILED", file=sys.stderr)
        rc = rc or jn_rc
        # perfscope lane (ISSUE 14): device-time sampling + HBM ledger —
        # roofline gauges export, decode MFU matches the cost_analysis
        # expectation, ledger reconciles with kv_pool/weight bytes and
        # drains to zero, one decode signature with sampling on
        print("telemetry smoke: perfscope lane", file=sys.stderr)
        ps_rc = subprocess.call([sys.executable, "-c", PERFSCOPE_LANE],
                                env=env, cwd=root)
        if ps_rc != 0:
            print("perfscope lane FAILED", file=sys.stderr)
        rc = rc or ps_rc
        # autoscale lane (ISSUE 15): sim-mode closed loop gates + a real
        # HTTP flash burst scaling a fleet up and draining it back down
        print("telemetry smoke: autoscale lane", file=sys.stderr)
        as_rc = subprocess.call([sys.executable, "-c", AUTOSCALE_LANE],
                                env=env, cwd=root)
        if as_rc != 0:
            print("autoscale lane FAILED", file=sys.stderr)
        rc = rc or as_rc
        # slo lane (ISSUE 16): sim-mode burn-rate gates (flash fires fast
        # rule + resolves post-absorb, steady diurnal fires nothing) plus
        # a real HTTP alert -> incident bundle -> renderer round trip
        print("telemetry smoke: slo lane", file=sys.stderr)
        slo_rc = subprocess.call([sys.executable, "-c", SLO_LANE],
                                 env=env, cwd=root)
        if slo_rc != 0:
            print("slo lane FAILED", file=sys.stderr)
        rc = rc or slo_rc
        # capture lane (ISSUE 17): HTTP run -> full-mode capture ->
        # deterministic replay (greedy token-identical, sampled
        # seed-exact) -> fit_trace -> FleetSim accepts the fitted trace
        print("telemetry smoke: capture lane", file=sys.stderr)
        cap_rc = subprocess.call([sys.executable, "-c", CAPTURE_LANE],
                                 env=env, cwd=root)
        if cap_rc != 0:
            print("capture lane FAILED", file=sys.stderr)
        rc = rc or cap_rc
        # conversation lane (ISSUE 18): two-turn HTTP chat through a
        # supervised replica with a forced eviction between the turns —
        # warm turn via host-tier promote, one decode signature, tier
        # metrics + journey phase + capture filter exported
        print("telemetry smoke: conversation lane", file=sys.stderr)
        cv_rc = subprocess.call([sys.executable, "-c", CONVERSATION_LANE],
                                env=env, cwd=root)
        if cv_rc != 0:
            print("conversation lane FAILED", file=sys.stderr)
        rc = rc or cv_rc
        # rollout lane (ISSUE 20): a real-HTTP fleet of two upgraded in
        # place under live load — canary gate on live outcomes, zero
        # lost requests, revision-labelled metrics, one decode
        # signature per build
        print("telemetry smoke: rollout lane", file=sys.stderr)
        ro_rc = subprocess.call([sys.executable, "-c", ROLLOUT_LANE],
                                env=env, cwd=root)
        if ro_rc != 0:
            print("rollout lane FAILED", file=sys.stderr)
        rc = rc or ro_rc
        # tpu-lint ratchet gate (ISSUE 7): runs even when the pytest
        # subset has unrelated failures, in its own interpreter (the
        # analyzer is jax-free, so it cannot be broken by runtime drift)
        print("telemetry smoke: tpu-lint ratchet lane", file=sys.stderr)
        lint_rc = subprocess.call(
            [sys.executable, os.path.join("tools", "lint_smoke.py")],
            env=env, cwd=root)
        if lint_rc != 0:
            print("tpu-lint lane FAILED", file=sys.stderr)
        rc = rc or lint_rc
        # gateway lane (ISSUE 8 satellite): mixed-tenant HTTP traffic
        # with telemetry on — fair-share isolation, shed 429s, /metrics
        # export, clean shutdown
        print("telemetry smoke: gateway lane", file=sys.stderr)
        gw_rc = subprocess.call(
            [sys.executable, os.path.join("tools", "gateway_smoke.py")],
            env=env, cwd=root)
        if gw_rc != 0:
            print("gateway lane FAILED", file=sys.stderr)
        rc = rc or gw_rc
        # serving chaos lane (ISSUE 9): engine kills under mixed-tenant
        # load — supervisor restarts, bounded interrupted streams, one
        # decode signature per rebuild, clean drain
        print("telemetry smoke: serving chaos lane", file=sys.stderr)
        chaos_rc = subprocess.call(
            [sys.executable, os.path.join("tools", "chaos_serving.py")],
            env=env, cwd=root)
        if chaos_rc != 0:
            print("serving chaos lane FAILED", file=sys.stderr)
        rc = rc or chaos_rc
    return rc


if __name__ == "__main__":
    sys.exit(main())
