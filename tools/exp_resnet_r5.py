"""Round-5 ResNet frontier A/B: grad barrier x pointwise-as-dot.
xplane device time per step, batch 64 (the profile configuration)."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import importlib.util

spec = importlib.util.spec_from_file_location(
    "pm", os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "profile_model.py"))
pm = importlib.util.module_from_spec(spec)
spec.loader.exec_module(pm)


def run(tag, barrier, as_dot):
    os.environ["PT_GRAD_BARRIER"] = barrier
    from paddle_tpu.nn.functional.conv import pointwise_as_dot
    pointwise_as_dot(as_dot)
    step, args = pm._build_resnet()
    outdir = pm.profile(step, args, steps=5)
    import collections, glob, jax
    paths = glob.glob(os.path.join(outdir, "**", "*.xplane.pb"), recursive=True)
    data = jax.profiler.ProfileData.from_file(paths[-1])
    plane = next(p for p in data.planes if "TPU" in p.name)
    total = 0.0
    for line in plane.lines:
        if line.name == "XLA Ops":
            total += sum(e.duration_ns for e in line.events) / 1e6
    print(f"{tag}: {total / 5:.3f} ms/step", flush=True)
    return total / 5


if __name__ == "__main__":
    which = sys.argv[1:] or ["base", "pre", "post", "dot", "dot_pre"]
    cfgs = {
        "base": ("", False), "pre": ("pre_cast", False),
        "post": ("post_cast", False), "dot": ("", True),
        "dot_pre": ("pre_cast", True),
    }
    for w in which:
        b, d = cfgs[w]
        run(w, b, d)
