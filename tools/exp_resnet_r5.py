"""Round-5 ResNet frontier A/B: grad barrier x pointwise-as-dot.
xplane device time per step, batch 64 (the profile configuration)."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import importlib.util

spec = importlib.util.spec_from_file_location(
    "pm", os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "profile_model.py"))
pm = importlib.util.module_from_spec(spec)
spec.loader.exec_module(pm)


def run(tag, barrier, as_dot):
    os.environ["PT_GRAD_BARRIER"] = barrier
    from paddle_tpu.nn.functional.conv import pointwise_as_dot
    pointwise_as_dot(as_dot)
    step, args = pm._build_resnet()
    outdir = pm.profile(step, args, steps=5)
    import collections, glob, jax
    paths = glob.glob(os.path.join(outdir, "**", "*.xplane.pb"), recursive=True)
    data = jax.profiler.ProfileData.from_file(paths[-1])
    plane = next(p for p in data.planes if "TPU" in p.name)
    total = 0.0
    for line in plane.lines:
        if line.name == "XLA Ops":
            total += sum(e.duration_ns for e in line.events) / 1e6
    print(f"{tag}: {total / 5:.3f} ms/step", flush=True)
    return total / 5


def run_bf16_state(tag="bf16_state"):
    """GPT-1.3B recipe applied to vision: params/slots in bf16 (no f32
    masters), measuring what the f32 optimizer state costs per step."""
    os.environ["PT_GRAD_BARRIER"] = ""
    from paddle_tpu.nn.functional.conv import pointwise_as_dot
    pointwise_as_dot(False)
    import jax.numpy as jnp
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    import paddle_tpu.nn as nn
    from paddle_tpu.vision.models import resnet50

    paddle.seed(0)
    model = resnet50(num_classes=1000)
    model.to(dtype="bfloat16")
    crit = nn.CrossEntropyLoss()
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=model.parameters(),
                                    weight_decay=1e-4)
    step = dist.make_train_step(model, opt, loss_fn=crit)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.standard_normal((64, 3, 224, 224)).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 1000, (64,)).astype(np.int64))
    outdir = pm.profile(step, (x, y), steps=5)
    import collections, glob, jax
    paths = glob.glob(os.path.join(outdir, "**", "*.xplane.pb"), recursive=True)
    data = jax.profiler.ProfileData.from_file(paths[-1])
    plane = next(p for p in data.planes if "TPU" in p.name)
    total = sum(sum(e.duration_ns for e in line.events)
                for line in plane.lines if line.name == "XLA Ops") / 1e6
    print(f"{tag}: {total / 5:.3f} ms/step", flush=True)


if __name__ == "__main__":
    which = sys.argv[1:] or ["base", "pre", "post", "dot", "dot_pre"]
    cfgs = {
        "base": ("", False), "pre": ("pre_cast", False),
        "post": ("post_cast", False), "dot": ("", True),
        "dot_pre": ("pre_cast", True),
    }
    for w in which:
        if w == "bf16_state":
            run_bf16_state()
            continue
        b, d = cfgs[w]
        run(w, b, d)
