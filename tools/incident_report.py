"""Render an SLO incident bundle as a human-readable postmortem sheet.

An incident bundle (``paddle_tpu.incident.v1``, written by the SLO
engine on every transition to firing and served by
``GET /debug/incidents/<id>``) correlates all three telemetry planes at
the moment an objective started burning: the keyed window snapshots
(host wall time), the perfscope roofline + HBM ownership ledger (device
time + bytes), and the slowest journey timelines + flight tail (what
each request was doing).  This tool turns one bundle into the text
summary you'd paste into a postmortem:

    python tools/incident_report.py --url http://127.0.0.1:8000
    python tools/incident_report.py --url http://127.0.0.1:8000 --id inc-...
    python tools/incident_report.py --json /tmp/paddle_tpu_incidents/inc-....json

With ``--url`` and no ``--id`` it lists the incident ring; with an id
(or a saved JSON file) it prints the full sheet.  stdlib-only; no jax,
no paddle_tpu import — usable against a live gateway from anywhere.
"""
from __future__ import annotations

import argparse
import json
import sys
import urllib.request

__all__ = ["render", "fetch"]


def fetch(url: str, inc_id: str | None = None) -> dict:
    path = "/debug/incidents" + (f"/{inc_id}" if inc_id else "")
    with urllib.request.urlopen(url.rstrip("/") + path, timeout=30) as r:
        return json.loads(r.read().decode("utf-8"))


def _fmt_pcts(p: dict | None) -> str:
    if not p:
        return "-"
    p50, p99 = p.get("p50"), p.get("p99")
    return (f"p50={p50 * 1e3:.1f}ms p99={p99 * 1e3:.1f}ms n={p.get('n')}"
            if p50 is not None and p99 is not None else "-")


def _window_lines(tag: str, snap: dict, out: list):
    out.append(f"  [{tag}] requests={snap.get('requests')} "
               f"shed={snap.get('shed')} "
               f"shed_rate={snap.get('shed_rate')}")
    out.append(f"      ttft {_fmt_pcts(snap.get('ttft_s'))} | "
               f"queue_wait {_fmt_pcts(snap.get('queue_wait_s'))} | "
               f"token {_fmt_pcts(snap.get('token_s'))}")
    reasons = snap.get("shed_reasons") or {}
    if reasons:
        out.append("      shed_reasons: " + ", ".join(
            f"{k}={v}" for k, v in sorted(reasons.items())))


def render(bundle: dict) -> str:
    out: list[str] = []
    inc = bundle.get("incident", {})
    out.append("=" * 72)
    out.append(f"INCIDENT {inc.get('id', '?')}")
    out.append(f"  objective={inc.get('objective')} key={inc.get('key')} "
               f"rule={inc.get('rule')} at {inc.get('time')}")
    out.append(f"  burn fast={inc.get('burn_fast')} "
               f"slow={inc.get('burn_slow')} "
               f"attainment={inc.get('attainment')}")
    out.append("=" * 72)

    window = bundle.get("window") or {}
    if window:
        out.append("\n-- windowed telemetry (host plane) --")
        if window.get("global"):
            _window_lines("global", window["global"], out)
        for by in ("by_tenant", "by_class"):
            keys = (window.get(by) or {}).get("keys") or {}
            for name, snap in sorted(keys.items()):
                _window_lines(f"{by[3:]}:{name or '(default)'}", snap, out)

    perf = bundle.get("perf") or {}
    programs = perf.get("programs") or []
    if programs:
        out.append("\n-- device roofline (device-time plane) --")
        for p in programs[:8]:
            out.append(f"  {p.get('name', '?')}: "
                       f"dispatches={p.get('dispatches')} "
                       f"device_s={p.get('device_s')} "
                       f"mfu={p.get('mfu')} "
                       f"hbm_bw_frac={p.get('hbm_bw_frac')}")

    mem = bundle.get("memory") or {}
    owners = mem.get("owners") or {}
    if owners:
        out.append("\n-- HBM ownership (bytes plane) --")
        for name, b in sorted(owners.items(),
                              key=lambda kv: -(kv[1] or 0))[:8]:
            out.append(f"  {name}: {b}")

    fleet = bundle.get("fleet") or {}
    if fleet:
        out.append("\n-- fleet --")
        out.append(f"  alive={fleet.get('alive')} "
                   f"draining={fleet.get('draining')} "
                   f"total_slots={fleet.get('total_slots')}")
        for name, rep in sorted((fleet.get("replicas") or {}).items()):
            out.append(f"  {name}: alive={rep.get('alive')} "
                       f"slots={rep.get('slots_in_use')}/"
                       f"{rep.get('max_slots')} "
                       f"queue={rep.get('queue_depth')}")

    slowest = bundle.get("slowest_journeys") or []
    if slowest:
        out.append("\n-- slowest journeys in-window --")
        for tl in slowest:
            phases = ", ".join(
                f"{ph.get('phase')}={ph.get('dur_ms', 0):.1f}ms"
                for ph in (tl.get("phases") or [])[:6])
            out.append(f"  {tl.get('id')}: wall="
                       f"{tl.get('wall_ms') or 0:.1f}ms "
                       f"outcome={tl.get('outcome')} [{phases}]")

    flights = bundle.get("flight_events") or []
    if flights:
        out.append(f"\n-- flight tail ({len(flights)} events) --")
        for evt in flights[-12:]:
            out.append(f"  {evt.get('kind')}/{evt.get('event')}: "
                       + ", ".join(f"{k}={v}" for k, v in evt.items()
                                   if k not in ("kind", "event", "t")))

    cap = bundle.get("capture_tail") or {}
    entries = cap.get("entries") or []
    if entries:
        span = cap.get("window_s") or 0.0
        out.append(f"\n-- capture tail ({len(entries)} arrivals over "
                   f"{span:.1f}s, mode={cap.get('mode')}) --")
        counts = cap.get("counts") or {}
        t0 = entries[0].get("t", 0.0)
        width = max(span, 1e-9)
        blocks = "▁▂▃▄▅▆▇█"
        for tenant in sorted(counts):
            bins = [0] * 24
            for e in entries:
                if e.get("tenant") != tenant:
                    continue
                i = int((e.get("t", t0) - t0) / width * 24)
                bins[min(23, max(0, i))] += 1
            peak = max(bins) or 1
            spark = "".join(
                " " if not b else blocks[min(7, b * 8 // (peak + 1))]
                for b in bins)
            c = counts[tenant]
            out.append(f"  {tenant or '(default)'}: |{spark}| "
                       f"admitted={c.get('admitted', 0)} "
                       f"shed={c.get('shed', 0)}")
        sheds = [e for e in entries if e.get("outcome") != "admitted"]
        for e in sheds[-4:]:
            out.append(f"    shed {e.get('journey_id') or '?'}: "
                       f"tenant={e.get('tenant')} "
                       f"outcome={e.get('outcome')} "
                       f"prompt_len={e.get('prompt_len')} "
                       f"max_tokens={e.get('max_tokens')}")
    out.append("")
    return "\n".join(out)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url", help="gateway base URL (http://host:port)")
    ap.add_argument("--id", help="incident id to render (with --url)")
    ap.add_argument("--json", help="render a saved bundle JSON file")
    args = ap.parse_args()
    if args.json:
        with open(args.json) as f:
            print(render(json.load(f)))
        return 0
    if not args.url:
        ap.error("need --url or --json")
    if not args.id:
        ring = fetch(args.url).get("incidents", [])
        if not ring:
            print("no incidents recorded")
            return 0
        for m in ring:
            print(f"{m['id']}  objective={m.get('objective')} "
                  f"key={m.get('key')} rule={m.get('rule')} "
                  f"time={m.get('time')}")
        return 0
    print(render(fetch(args.url, args.id)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
