"""Pipeline-schedule memory probe (round-5 verdict ask #6).

Measures XLA `memory_analysis().temp_size_in_bytes` of the compiled
GPipeTrainStep across schedules at growing micro-batch counts M — the
grad-accumulation regime (FleetX 6.7B uses M >> S) where true 1F1B's
<=S-deep activation stash (reference pipeline_parallel.py:108,491) could
beat the one-program circular schedule's remat residency (V*M x 1 input
act, docs/PERF.md "Interleaved 1F1B accounting").

Run from the repo root:
    python tools/pp_mem_probe.py [--ms 16,32,64]

Prints a markdown table (pasted into docs/PERF.md) with, per M:
  gpipe G=1 / +remat / 1f1b C=S / C=S+remat temp bytes, plus the analytic
  true-1F1B stash bound S*(1+k) acts for comparison.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.distributed as dist  # noqa: E402
import paddle_tpu.nn as nn  # noqa: E402
from paddle_tpu.distributed.pipeline import (  # noqa: E402
    GPipeTrainStep, Stash1F1BTrainStep)

H, T, N_BLOCKS, K = 64, 16, 8, 4          # FFN expansion k=4 transformer-ish
S = 4                                     # pipe stages


class Block(nn.Layer):
    def __init__(self, h=H):
        super().__init__()
        self.fc1 = nn.Linear(h, K * h)
        self.fc2 = nn.Linear(K * h, h)
        self.norm = nn.LayerNorm(h)

    def forward(self, x):
        return x + self.fc2(nn.functional.gelu(self.fc1(self.norm(x))))


def build(mesh, m, schedule, chunk=None, remat=False):
    paddle.seed(0)
    pre = nn.Sequential(nn.Linear(8, H))
    blocks = [Block() for _ in range(N_BLOCKS)]
    post = nn.Sequential(nn.LayerNorm(H), nn.Linear(H, 4))
    opt = paddle.optimizer.SGD(
        parameters=(pre.parameters() +
                    [p for bl in blocks for p in bl.parameters()] +
                    post.parameters()), learning_rate=1e-2)
    if schedule == "stash":
        return Stash1F1BTrainStep(pre, blocks, post, nn.MSELoss(), opt,
                                  mesh=mesh, num_micro=m)
    return GPipeTrainStep(pre, blocks, post, nn.MSELoss(), opt, mesh=mesh,
                          num_micro=m, schedule=schedule, chunk_micro=chunk,
                          remat=remat)


def temp_bytes(step, x, y):
    b = x.shape[0]
    fn = step._build(*step._pick_schedule(b))
    lowered = fn.lower(step.params, step.slots, step.step_count,
                       jnp.float32(1e-2), jax.random.key(0),
                       (jnp.asarray(x), jnp.asarray(y)))
    return lowered.compile().memory_analysis().temp_size_in_bytes


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ms", default="16,32,64")
    ap.add_argument("--micro", type=int, default=2,
                    help="per-micro batch rows")
    args = ap.parse_args()

    mesh = dist.build_mesh([1, S], ["dp", "pipe"])
    dist.set_global_mesh(mesh)
    rng = np.random.default_rng(0)

    act_bytes = args.micro * T * H * 4          # one input activation
    print(f"# S={S}, {N_BLOCKS} blocks h={H} k={K}, micro rows="
          f"{args.micro}, seq={T}; act={act_bytes/1024:.1f} KB")
    print("| M | gpipe G=1 | +remat | 1f1b C=S | C=S +remat | "
          "1F1B stash | stash bound |")
    print("|---|---|---|---|---|---|---|")
    for m in [int(v) for v in args.ms.split(",")]:
        b = args.micro * m
        x = rng.standard_normal((b, T, 8)).astype("float32")
        y = rng.standard_normal((b, T, 4)).astype("float32")
        row = []
        for sched, chunk, remat in (("gpipe", None, False),
                                    ("gpipe", None, True),
                                    ("1f1b", S, False),
                                    ("1f1b", S, True),
                                    ("stash", None, False)):
            mb = temp_bytes(build(mesh, m, sched, chunk, remat), x, y)
            row.append(f"{mb/2**20:.2f} MB")
        bound = (2 * S - 1) * (1 + K) * act_bytes
        print(f"| {m} | " + " | ".join(row) +
              f" | {bound/2**20:.2f} MB ({2*S-1}x{1+K} acts) |")

    # numerics guard: remat/chunk variants must train identically
    m = 16
    b = args.micro * m
    x = rng.standard_normal((b, T, 8)).astype("float32")
    y = rng.standard_normal((b, T, 4)).astype("float32")
    ref = None
    for sched, chunk, remat in (("gpipe", None, False),
                                ("gpipe", None, True),
                                ("1f1b", S, True)):
        st = build(mesh, m, sched, chunk, remat)
        losses = [float(st(x, y)) for _ in range(3)]
        if ref is None:
            ref = losses
        else:
            np.testing.assert_allclose(losses, ref, rtol=2e-4, atol=1e-5)
    print("# numerics: gpipe == gpipe+remat == 1f1b+remat (3 steps)")


if __name__ == "__main__":
    main()
