"""Experiment: fused (BN-apply -> relu -> 1x1 conv -> BN-stats) as ONE
Pallas kernel vs the XLA chain the model currently runs.

ResNet's HBM traffic per 1x1 conv today (docs/PERF.md): conv reads xn,
writes y; BN stats read y; BN apply reads y, writes z.  The fused form
reads x_raw once, writes y once, and carries the prologue (prev BN
apply + relu) and epilogue (per-channel sum/sumsq of y) in registers.

Usage: python tools/exp_conv_bn.py
"""
from __future__ import annotations

import functools
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(x_ref, s_ref, b_ref, w_ref, o_ref, st_ref, *, m_total, bm):
    i = pl.program_id(1)
    x = x_ref[...].astype(jnp.float32)
    xn = jnp.maximum(x * s_ref[...].astype(jnp.float32)
                     + b_ref[...].astype(jnp.float32), 0).astype(x_ref.dtype)
    y = jax.lax.dot_general(xn, w_ref[...], (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    o_ref[...] = y.astype(o_ref.dtype)
    rows = i * bm + jax.lax.broadcasted_iota(jnp.int32, (y.shape[0], 1), 0)
    ym = jnp.where(rows < m_total, y, 0.0)
    ps = jnp.sum(ym, axis=0, keepdims=True)
    pq = jnp.sum(ym * ym, axis=0, keepdims=True)
    stat = jnp.concatenate([ps, pq], axis=0)

    @pl.when(i == 0)
    def _init():
        st_ref[...] = stat

    @pl.when(i > 0)
    def _acc():
        st_ref[...] += stat


def fused_conv1x1_bn(x2, s, b, w, bm=1024, bn=512):
    """x2: [M, K] raw prev-conv output (bf16); s,b: [K] f32 BN scale/shift;
    w: [K, N].  Returns y [M, N] bf16, stats [2, N] f32 (sum, sumsq)."""
    m, k = x2.shape
    n = w.shape[1]
    bn = min(bn, n)
    bm = min(bm, m)
    mp = -(-m // bm) * bm
    if mp != m:
        x2 = jnp.pad(x2, ((0, mp - m), (0, 0)))
    ni = mp // bm
    nj = n // bn
    y, st = pl.pallas_call(
        functools.partial(_kernel, m_total=m, bm=bm),
        grid=(nj, ni),
        in_specs=[
            pl.BlockSpec((bm, k), lambda j, i: (i, 0)),
            pl.BlockSpec((1, k), lambda j, i: (0, 0)),
            pl.BlockSpec((1, k), lambda j, i: (0, 0)),
            pl.BlockSpec((k, bn), lambda j, i: (0, j)),
        ],
        out_specs=[
            pl.BlockSpec((bm, bn), lambda j, i: (i, j)),
            pl.BlockSpec((2, bn), lambda j, i: (0, j)),
        ],
        out_shape=[jax.ShapeDtypeStruct((mp, n), x2.dtype),
                   jax.ShapeDtypeStruct((2, n), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
            vmem_limit_bytes=100 * 1024 * 1024),
    )(x2, s.reshape(1, -1), b.reshape(1, -1), w)
    return y[:m], st


def xla_chain(x2, s, b, w):
    xn = jnp.maximum(x2.astype(jnp.float32) * s + b, 0).astype(x2.dtype)
    y = jax.lax.dot_general(xn, w, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32
                            ).astype(x2.dtype)
    yf = y.astype(jnp.float32)
    mean = jnp.mean(yf, axis=0)
    var = jnp.maximum(jnp.mean(yf * yf, axis=0) - mean * mean, 0)
    return y, mean, var


def _time(fn, args, iters=400, perturb=1):
    """Scan-chained timing for sub-dispatch-cost ops: the carry perturbs one
    SMALL argument by carry*1e-45 (a denormal — numerically invisible, but
    not constant-foldable), so XLA cannot hoist the body out of the loop.
    The ~2 ms tunnel fetch is measured separately and subtracted."""
    def body(c, _):
        a = list(args)
        a[perturb] = a[perturb] + (c * 1e-45).astype(a[perturb].dtype)
        out = fn(*a)
        leaf = jax.tree_util.tree_leaves(out)[0]
        return c + leaf.reshape(-1)[0].astype(jnp.float32), None

    chained = jax.jit(functools.partial(
        lambda ln: jax.lax.scan(body, jnp.float32(0), None, length=ln),
        iters))
    float(chained()[0])
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter()
        float(chained()[0])
        best = min(best, time.perf_counter() - t0)
    # ~2 ms fixed dispatch+fetch cost spread over `iters` (+5 us/iter bias
    # at iters=400 — identical for both sides of every comparison here)
    return best / iters * 1e6


def main():
    shapes = [
        # (M, K, N) — ResNet-50 batch-64 1x1 convs, NHWC-flattened
        (64 * 56 * 56, 64, 256),
        (64 * 56 * 56, 256, 64),
        (64 * 28 * 28, 512, 128),
        (64 * 28 * 28, 128, 512),
        (64 * 14 * 14, 1024, 256),
        (64 * 14 * 14, 256, 1024),
        (64 * 7 * 7, 2048, 512),
        (64 * 7 * 7, 512, 2048),
    ]
    rng = np.random.RandomState(0)
    for m, k, n in shapes:
        x2 = jnp.asarray(rng.standard_normal((m, k)).astype(np.float32),
                         jnp.bfloat16)
        s = jnp.asarray(rng.standard_normal(k).astype(np.float32)) * 0.1 + 1
        b = jnp.asarray(rng.standard_normal(k).astype(np.float32)) * 0.1
        w = jnp.asarray(rng.standard_normal((k, n)).astype(np.float32) /
                        np.sqrt(k), jnp.bfloat16)
        # correctness
        yf, st = jax.jit(fused_conv1x1_bn)(x2, s, b, w)
        yx, mean, var = jax.jit(xla_chain)(x2, s, b, w)
        mf = st[0] / m
        vf = jnp.maximum(st[1] / m - mf * mf, 0)
        err_y = float(jnp.max(jnp.abs(yf.astype(jnp.float32)
                                      - yx.astype(jnp.float32))))
        err_m = float(jnp.max(jnp.abs(mf - mean)))
        err_v = float(jnp.max(jnp.abs(vf - var)))
        t_pal = _time(fused_conv1x1_bn, (x2, s, b, w))
        t_xla = _time(xla_chain, (x2, s, b, w))
        gb = (m * k + m * n) * 2 / 1e9  # one read + one write, bf16
        print(f"M={m:7d} K={k:4d} N={n:4d}  pallas={t_pal:8.1f}us "
              f"xla={t_xla:8.1f}us  speedup={t_xla / t_pal:5.2f}x  "
              f"bw={gb / (t_pal / 1e6):6.0f}GB/s  err y/m/v="
              f"{err_y:.3g}/{err_m:.3g}/{err_v:.3g}")


if __name__ == "__main__":
    main()
