"""Deterministic replay of captured gateway traffic.

Pull a window (or one request) out of a traffic capture — a live
gateway's ``/debug/capture`` ring, a saved dump, or a JSONL spill file —
and re-drive it against a gateway via ``load_gen.replay_http``,
preserving inter-arrival times (compressible with ``--speed``),
tenants, priorities, adapters and sampling seeds.  A full-mode capture
carries exact prompt token ids, so a greedy request reproduces
token-identical output and a sampled one is seed-exact (the engine's
PRNG keys on (seed, position), not batch shape); a shape-mode capture
replays with synthetic prompts of the captured lengths.

    # replay the target gateway's own recent traffic, 4x compressed
    python tools/replay_capture.py --url http://127.0.0.1:PORT --speed 4

    # re-drive one captured request (by X-Request-Id / journey id)
    python tools/replay_capture.py --url http://127.0.0.1:PORT \
        --file capture.json --request-id 7f3a...

    # replay a window captured on prod against a staging gateway
    python tools/replay_capture.py --url http://staging:8000 \
        --from http://prod:8000 --tenant acme --admitted-only
"""
from __future__ import annotations

import argparse
import http.client
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools.load_gen import replay_http  # noqa: E402

__all__ = ["fetch_capture", "load_file", "to_trace"]


def fetch_capture(url: str, last: int = 10 ** 9,
                  tenant: str | None = None) -> list:
    """GET ``/debug/capture`` from a live gateway -> entry list."""
    from urllib.parse import urlparse
    u = urlparse(url)
    q = f"/debug/capture?last={last}"
    if tenant:
        q += f"&tenant={tenant}"
    conn = http.client.HTTPConnection(u.hostname, u.port, timeout=30)
    try:
        conn.request("GET", q)
        r = conn.getresponse()
        body = json.loads(r.read())
        if r.status != 200:
            raise RuntimeError(f"GET {q} -> {r.status}: {body}")
    finally:
        conn.close()
    return body["window"]


def load_file(path: str) -> list:
    """Read a capture from disk: a ``/debug/capture`` dump, a bare entry
    list, or a rotating JSONL spill file."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    try:
        data = json.loads(text)
    except json.JSONDecodeError:          # JSONL spill: one entry/line
        return [json.loads(line) for line in text.splitlines()
                if line.strip()]
    if isinstance(data, dict):
        return [data] if "t" in data else data.get("window", [])
    return data


def to_trace(entries, *, request_id: str | None = None,
             tenant: str | None = None, last: int | None = None,
             admitted_only: bool = False) -> list:
    """Filter + order a capture into a replayable trace: sort by
    arrival, rebase ``t`` so the first entry fires immediately."""
    out = list(entries)
    if request_id is not None:
        out = [e for e in out if e.get("journey_id") == request_id]
        if not out:
            raise SystemExit(f"no captured entry with journey id "
                             f"{request_id!r} ({len(entries)} entries)")
    if tenant is not None:
        out = [e for e in out if e.get("tenant") == tenant]
    if admitted_only:
        out = [e for e in out if e.get("outcome") == "admitted"]
    out.sort(key=lambda e: e["t"])
    if last is not None:
        out = out[-max(0, int(last)):]
    if not out:
        raise SystemExit("capture window is empty after filtering")
    t0 = out[0]["t"]
    return [dict(e, t=round(e["t"] - t0, 4)) for e in out]


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url", required=True,
                    help="target gateway to replay AGAINST")
    src = ap.add_mutually_exclusive_group()
    src.add_argument("--from", dest="src_url", default=None,
                     help="source gateway to pull the capture FROM "
                     "(default: the target's own ring)")
    src.add_argument("--file", default=None,
                     help="saved capture: /debug/capture dump, bare "
                     "entry list, or JSONL spill")
    ap.add_argument("--request-id", default=None,
                    help="replay ONE captured request by journey id")
    ap.add_argument("--tenant", default=None,
                    help="replay only this tenant's entries")
    ap.add_argument("--last", type=int, default=None,
                    help="replay only the newest N entries (post-filter)")
    ap.add_argument("--admitted-only", action="store_true",
                    help="skip entries the source gateway shed")
    ap.add_argument("--speed", type=float, default=1.0,
                    help="time-compression factor (4.0 = 4x faster)")
    ap.add_argument("--seed", type=int, default=0,
                    help="synthetic-prompt stream for shape-mode entries")
    ap.add_argument("--vocab", type=int, default=1000)
    args = ap.parse_args()
    if args.file:
        entries = load_file(args.file)
    else:
        entries = fetch_capture(args.src_url or args.url,
                                tenant=args.tenant)
    trace = to_trace(entries, request_id=args.request_id,
                     tenant=args.tenant, last=args.last,
                     admitted_only=args.admitted_only)
    exact = sum(1 for e in trace if e.get("prompt"))
    print(f"# replaying {len(trace)} captured arrivals over "
          f"{trace[-1]['t']:.1f}s at {args.speed}x "
          f"({exact} with exact prompt ids)", file=sys.stderr)
    summary = replay_http(args.url, trace, vocab=args.vocab,
                          seed=args.seed, speed=args.speed)
    print(json.dumps(summary))
    return 0 if summary["errors"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
