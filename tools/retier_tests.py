"""Regenerate tests/slow_tests.txt from measured per-test durations.

Usage:
    python tools/retier_tests.py              # run suite per-file, retier
    python tools/retier_tests.py --from-logs DIR   # reuse existing logs

Runs every tests/test_*.py file separately with `--durations` so one bad
file cannot sink the measurement, collects call times, and writes every
base nodeid whose call time is >= CUTOFF_S (2s) to tests/slow_tests.txt.
The conftest collection hook turns that list into @pytest.mark.slow, so
`pytest -m "not slow"` is the smoke gate (round-3 verdict Weak #6).
"""
from __future__ import annotations

import glob
import os
import re
import subprocess
import sys
import tempfile

CUTOFF_S = 2.0
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_suite(outdir: str) -> list[str]:
    timed_out = []
    for f in sorted(glob.glob(os.path.join(REPO, "tests", "test_*.py"))):
        base = os.path.basename(f)[:-3]
        log = os.path.join(outdir, base + ".log")
        with open(log, "w") as fh:
            try:
                subprocess.run(
                    [sys.executable, "-m", "pytest", f, "-q", "-p",
                     "no:cacheprovider", "--durations=0",
                     "--durations-min=1.0"],
                    cwd=REPO, stdout=fh, stderr=subprocess.STDOUT,
                    timeout=1800, check=False)
            except subprocess.TimeoutExpired:
                # pytest prints --durations only at session end, so a
                # killed file contributes NO timings: remember it and keep
                # its previous slow entries instead of silently re-tiering
                # its (clearly slow) tests into the smoke gate
                timed_out.append("tests/" + base + ".py")
                print(base, "TIMED OUT (>1800s); keeping previous tier",
                      file=sys.stderr)
        print(base, "done", file=sys.stderr)
    return timed_out


def collect(outdir: str):
    entries = []
    for log in glob.glob(os.path.join(outdir, "*.log")):
        for line in open(log):
            m = re.match(r"\s*([\d.]+)s\s+call\s+(\S+::\S+)", line)
            if m:
                entries.append((float(m.group(1)), m.group(2)))
    return entries


def main():
    timed_out: list[str] = []
    if "--from-logs" in sys.argv:
        outdir = sys.argv[sys.argv.index("--from-logs") + 1]
    else:
        outdir = tempfile.mkdtemp(prefix="retier_")
        timed_out = run_suite(outdir)
    entries = collect(outdir)
    bases = {n.split("[")[0] for t, n in entries if t >= CUTOFF_S}
    listing_prev = os.path.join(REPO, "tests", "slow_tests.txt")
    if timed_out and os.path.exists(listing_prev):
        for line in open(listing_prev):
            line = line.strip()
            if line and not line.startswith("#") and \
                    any(line.startswith(f + "::") for f in timed_out):
                bases.add(line)
    bases = sorted(bases)
    listing = os.path.join(REPO, "tests", "slow_tests.txt")
    with open(listing, "w") as f:
        f.write("# Tests marked @slow by measured duration (>=2s call time "
                "on the\n# 8-device CPU mesh; tools/retier_tests.py "
                "regenerates).  The smoke\n# tier is `pytest -m 'not "
                "slow'`.\n")
        for b in bases:
            f.write(b + "\n")
    print(f"{len(bases)} slow tests -> {listing}")


if __name__ == "__main__":
    main()
