"""Round-5: fused LM-head + chunked CE A/B on the chip (gpt2-small shapes)."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np


def run(tag, fused, name="gpt2-small-en", batch=16, seq=1024, steps=10):
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.models import (GPTPretrainingCriterion, build_gpt,
                                   gpt_config, gpt_train_flops_per_token)

    cfg = gpt_config(name, max_position_embeddings=seq,
                     hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                     fuse_head_loss=fused)
    paddle.seed(0)
    model = build_gpt(cfg)
    crit = GPTPretrainingCriterion()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 weight_decay=0.01)
    step = dist.make_train_step(model, opt, loss_fn=crit,
                                compute_dtype="bfloat16")
    ids = np.random.RandomState(0).randint(0, cfg.vocab_size,
                                           (batch, seq + 1)).astype(np.int64)
    x, y = ids[:, :-1], ids[:, 1:]
    loss = step(x, y)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(x, y)
    lv = float(loss)
    dt = time.perf_counter() - t0
    tps = batch * seq * steps / dt
    mfu = tps * gpt_train_flops_per_token(cfg, seq) / 197e12
    print(f"{tag}: {tps:,.0f} tok/s mfu={mfu:.3f} loss={lv:.4f}",
          flush=True)


if __name__ == "__main__":
    for a in sys.argv[1:]:
        run(a, fused=a.startswith("fused"))
