"""Benchmark: BASELINE.md configs on one TPU chip.

Prints ONE JSON line with the flagship GPT metric at the top level (the
schema the driver has parsed since round 1) plus a "legs" object carrying
EVERY leg's result — GPT-2-small, PP-YOLOE, GPT-3-1.3B (north-star scale:
on-device bf16 state + scan_layers + remat), ResNet-50, BERT-base
(batch 64 + bf16 state), and a GPT KV-cache decode serving leg — so
BENCH_r{N}.json records non-flagship regressions too.  Every leg reports
a `noise_pct` band from repeat windows (round-4 verdict Weak #6), and a
persistent XLA compile cache keeps repeat runs inside the time budget.

`python bench.py --flagship-only` restores the old single-leg behavior.
"""
from __future__ import annotations

import json
import os
import sys
import time
import traceback

import numpy as np

# CPU smoke runs get 2 simulated host devices so the cross-dp elastic
# resume gate can build a real dp=2 mesh (must land before the backend
# initializes; hardware runs don't set JAX_PLATFORMS=cpu and are
# untouched)
if os.environ.get("JAX_PLATFORMS", "").lower().startswith("cpu") and \
        "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") +
        " --xla_force_host_platform_device_count=2").strip()

# persistent compile cache: repeated bench runs (and the driver's final
# run on this host) skip the 40-150s per-leg XLA compiles
try:
    import jax

    jax.config.update("jax_compilation_cache_dir",
                      os.path.expanduser("~/.cache/jax_bench_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
except Exception:
    pass

# bf16 peak FLOPs/s per chip by TPU generation (public spec sheets)
_PEAK = {"v5 lite": 197e12, "v5e": 197e12, "v4": 275e12, "v5p": 459e12,
         "v6": 918e12}


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, val in _PEAK.items():
        if key in kind:
            return val
    return 197e12  # assume v5e-class


def _reset_parallel_state():
    import paddle_tpu.distributed as dist
    dist.set_global_mesh(None)




def _timed_rate(step_once, units_per_step, steps, reps=3):
    """Headline rate from ONE long window of `steps` steps (the same
    methodology BENCH_r01..r04 used, so values stay cross-round
    comparable), plus a noise band (max-min)/median measured over `reps`
    short windows of steps//reps steps each.  Through the remote-dispatch
    tunnel every host sync costs a round-trip, so short synced windows
    under-measure 3-20%: the band is computed from equal-sized windows
    (the sync bias cancels in the spread) and only the long window sets
    the reported value."""
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step_once()
    float(loss)
    value = units_per_step * steps / (time.perf_counter() - t0)
    sub = max(1, steps // reps)
    rates = []
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(sub):
            loss = step_once()
        float(loss)
        rates.append(units_per_step * sub / (time.perf_counter() - t0))
    med = float(np.median(rates))
    noise = (max(rates) - min(rates)) / med if med else 0.0
    return value, round(100 * noise, 2), loss


def _loss_series(losses):
    """One host sync for a list of step losses (scalars or [K] stacks)."""
    out = []
    for l in losses:
        a = np.asarray(l.numpy() if hasattr(l, "numpy") else l)
        out.extend(np.ravel(a).astype(np.float64).tolist())
    return out


def _input_overlap_block(step, batches, stacked=False, parity_make=None):
    """Input-overlap probe (ISSUE 4): drive a train step over host-side
    numpy batches twice — synchronous inline transfers vs DevicePrefetcher
    — and report each path's host-wait fraction (time the loop spent
    obtaining a device-ready batch / loop wall time).  On an accelerator
    the prefetched path must wait less (the transfer overlaps compute);
    on CPU timings are noise, so the fallback assertion is bit-identical
    loss parity between the two paths on fresh models (`parity_make`)."""
    import jax

    from paddle_tpu.io.prefetch import DevicePrefetcher

    on_tpu = jax.devices()[0].platform != "cpu"
    call = (lambda s, xs: s.run_steps(*xs)) if stacked \
        else (lambda s, xs: s(*xs))

    def run(s, prefetch):
        # warmup outside the timed window (compile / allocator settle);
        # both paths run it, so parity series stay aligned
        warm = tuple(jax.device_put(np.asarray(a)) for a in batches[0])
        _loss_series([call(s, warm)])
        wait, losses, stalls = 0.0, [], 0
        t_loop = time.perf_counter()
        if prefetch:
            pf = DevicePrefetcher(batches, depth=2, mesh=s.mesh,
                                  stacked=stacked, name="bench")
            for xs in pf:
                losses.append(call(s, xs))
            wait = pf.stats()["wait_seconds"]
            stalls = pf.stats()["stalls"]
        else:
            for b in batches:
                t0 = time.perf_counter()
                xs = tuple(jax.device_put(np.asarray(a)) for a in b)
                wait += time.perf_counter() - t0
                losses.append(call(s, xs))
        series = _loss_series(losses)  # the sync point closing the window
        wall = time.perf_counter() - t_loop
        return (wait / wall if wall > 0 else 0.0), series, stalls

    sync_frac, _, _ = run(step, prefetch=False)
    pf_frac, _, stalls = run(step, prefetch=True)
    block = {"steps": len(batches),
             "host_wait_frac_sync": round(sync_frac, 4),
             "host_wait_frac_prefetch": round(pf_frac, 4),
             "prefetch_stalls": int(stalls)}
    if on_tpu and pf_frac >= sync_frac:
        raise RuntimeError(
            f"input overlap regressed: prefetch host-wait frac {pf_frac:.4f}"
            f" >= sync {sync_frac:.4f}")
    if parity_make is not None and not on_tpu:
        _, s_sync, _ = run(parity_make(), prefetch=False)
        _, s_pf, _ = run(parity_make(), prefetch=True)
        if s_sync != s_pf:
            raise RuntimeError(
                f"prefetch loss parity broke: {s_sync} vs {s_pf}")
        block["loss_parity"] = True
    return block


def _checkpoint_block(step, batch, on_tpu, make_step=None):
    """Checkpoint-overhead probe (ISSUE 5): host snapshot, async sharded
    write (CRC + COMMITTED marker), validated restore — the costs the
    preemption-safe training path adds per checkpoint — plus the CPU
    resume-parity gate: load_state_dict must reproduce the next steps'
    losses bit-identically without adding a jit signature.

    Elastic additions (ISSUE 6): `restore_reshard_ms` times the
    load-with-relayout path (read + CRC on stored bytes + per-leaf
    placement onto a target mesh), and — CPU with >=2 devices and a
    `make_step(mesh=...)` factory — a cross-dp resume-parity gate: the
    same checkpoint restored onto a dp=2 mesh must reproduce the next
    steps' losses to tolerance with ZERO new jit signatures."""
    import tempfile

    import numpy as _np

    from paddle_tpu.framework.checkpoint import AsyncCheckpointSaver

    block = {}
    with tempfile.TemporaryDirectory() as d:
        saver = AsyncCheckpointSaver(d, keep_last=2)
        t0 = time.perf_counter()
        state = step.state_dict()
        block["snapshot_ms"] = round(1e3 * (time.perf_counter() - t0), 2)
        t0 = time.perf_counter()
        saver.save(state, step=int(step.optimizer._step_count))
        saver.wait()
        block["async_write_ms"] = round(1e3 * (time.perf_counter() - t0), 2)
        t0 = time.perf_counter()
        _, restored = saver.restore_latest_valid()
        block["restore_ms"] = round(1e3 * (time.perf_counter() - t0), 2)
        # elastic restore timing: relayout every leaf onto a mesh (the
        # step's own, or a 1-device mesh when the step runs mesh-free)
        import jax as _jax

        import paddle_tpu.distributed as _dist
        resh_mesh = step.mesh if step.mesh is not None else \
            _dist.build_mesh([1], ["dp"], devices=_jax.devices()[:1])
        t0 = time.perf_counter()
        saver.restore(target_mesh=resh_mesh,
                      target_specs=step.elastic_specs())
        block["restore_reshard_ms"] = round(
            1e3 * (time.perf_counter() - t0), 2)
        parity = None
        tail_b = None
        if not on_tpu:
            sigs_before = len(step._jitted._signatures)
            tail_a = _loss_series([step(*batch) for _ in range(2)])
            step.load_state_dict(restored)
            tail_b = _loss_series([step(*batch) for _ in range(2)])
            parity = (tail_a == tail_b and
                      len(step._jitted._signatures) == sigs_before)
            if not parity:
                raise RuntimeError(
                    f"checkpoint resume parity broke: {tail_a} vs {tail_b} "
                    f"(signatures {sigs_before} -> "
                    f"{len(step._jitted._signatures)})")
        block["resume_parity"] = parity
        # cross-dp elastic resume gate: restore the SAME checkpoint onto
        # a dp=2 mesh and require the loss tail to match (cross-dp
        # reduction order differs by ~1 ulp on CPU, hence tolerance — the
        # relayout itself is byte-lossless, asserted in tests)
        cross = None
        cpu_devs = len([dev for dev in _jax.devices()
                        if dev.platform == "cpu"])
        if not on_tpu and make_step is not None and cpu_devs >= 2:
            mesh2 = _dist.build_mesh([2], ["dp"])
            step2 = make_step(mesh=mesh2)
            _loss_series([step2(*batch)])  # compile BEFORE the restore
            sigs = len(step2._jitted._signatures)
            step2.load_state_dict(restored)
            tail_c = _loss_series([step2(*batch) for _ in range(2)])
            cross = (len(step2._jitted._signatures) == sigs and
                     bool(_np.allclose(tail_c, tail_b,
                                       rtol=1e-4, atol=1e-6)))
            if not cross:
                raise RuntimeError(
                    f"cross-dp elastic resume parity broke: {tail_b} vs "
                    f"{tail_c} (signatures {sigs} -> "
                    f"{len(step2._jitted._signatures)})")
        block["cross_dp_resume_parity"] = cross
    return block


def bench_gpt_small():
    """Flagship: GPT-2-small pretraining step (125M; comparable to the
    round-1..3 flagship numbers)."""
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.models import (GPTPretrainingCriterion, build_gpt,
                                   gpt_config, gpt_train_flops_per_token)

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"

    if on_tpu:
        name, batch, seq, steps = "gpt2-small-en", 16, 1024, 20
    else:  # CI/CPU smoke: tiny shapes, same code path
        name, batch, seq, steps = "gpt-tiny", 2, 128, 3

    cfg = gpt_config(name, max_position_embeddings=max(seq, 1024),
                     hidden_dropout_prob=0.0, attention_dropout_prob=0.0)

    def make_step(mesh=None):
        paddle.seed(0)
        m = build_gpt(cfg)
        o = paddle.optimizer.AdamW(learning_rate=1e-4,
                                   parameters=m.parameters(),
                                   weight_decay=0.01)
        return dist.make_train_step(
            m, o, loss_fn=GPTPretrainingCriterion(), mesh=mesh,
            compute_dtype="bfloat16" if on_tpu else None)

    step = make_step()
    ids = np.random.RandomState(0).randint(
        0, cfg.vocab_size, size=(batch, seq + 1)).astype(np.int64)
    x, y = ids[:, :-1], ids[:, 1:]

    loss = step(x, y)  # compile + warmup
    float(loss)
    tokens_per_sec, noise, loss = _timed_rate(
        lambda: step(x, y), batch * seq, steps)
    flops_tok = gpt_train_flops_per_token(cfg, seq)
    mfu = tokens_per_sec * flops_tok / _peak_flops(dev) if on_tpu else 0.0
    print(f"# device={dev.device_kind} loss={float(loss):.4f} "
          f"mfu={mfu:.3f} steps={steps} noise={noise}%", file=sys.stderr)
    overlap = _input_overlap_block(
        step, [(x, y)] * (8 if on_tpu else 3),
        parity_make=None if on_tpu else make_step)
    ckpt = _checkpoint_block(step, (x, y), on_tpu,
                             make_step=None if on_tpu else make_step)
    return {
        "metric": f"gpt_{name}_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s/chip",
        "noise_pct": noise,
        "input_overlap": overlap,
        "checkpoint": ckpt,
        "vs_baseline": round(mfu / 0.35, 4) if on_tpu else 0.0,
    }


def bench_gpt_1p3b():
    """North-star-scale leg (round-3 verdict #1): GPT-3 1.3B — the
    BASELINE.md gate model (>=0.35 MFU, FleetX recipe) — on ONE chip.
    Measured recipe (round 4): bf16 params + slots on device, scan_layers +
    per-layer remat, eager weight copies freed after the train state is
    built (the state owns the live weights; sync_to_model is never called
    here).  Host-offloaded slots were measured 8.8x slower (0.057 MFU, the
    PCIe staging dominates) and batch 16 regresses to 0.450 — batch 8 +
    remat gives 0.506 MFU, 1.45x the 0.35 gate.  MFU is per-step, so
    single-chip throughput is the honest scale measurement the 125M proxy
    could not provide."""
    import gc

    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.models import (GPTPretrainingCriterion, build_gpt,
                                   gpt_config, gpt_train_flops_per_token)

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    if on_tpu:
        name, batch, seq, steps = "gpt3-1.3B-en", 8, 1024, 5
    else:
        name, batch, seq, steps = "gpt-tiny", 2, 128, 2

    cfg = gpt_config(name, max_position_embeddings=max(seq, 1024),
                     hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                     scan_layers=True, use_recompute=True)

    def make_step():
        paddle.seed(0)
        if on_tpu:
            paddle.set_default_dtype("bfloat16")
        try:
            m = build_gpt(cfg)
        finally:
            paddle.set_default_dtype("float32")
        o = paddle.optimizer.AdamW(learning_rate=1e-4,
                                   parameters=m.parameters(),
                                   weight_decay=0.01)
        return m, dist.make_train_step(
            m, o, loss_fn=GPTPretrainingCriterion(),
            compute_dtype="bfloat16" if on_tpu else None)

    model, step = make_step()
    if on_tpu:
        # free the eager weight copies: 2.6 GiB of headroom the 1.3B
        # single-chip budget needs (params 2.6 + slots 5.2 + grads 2.6)
        for p in model.parameters():
            p._replace_(jnp.zeros((), p._value.dtype), None)
        gc.collect()
    ids = np.random.RandomState(0).randint(
        0, cfg.vocab_size, size=(batch, seq + 1)).astype(np.int64)
    x, y = ids[:, :-1], ids[:, 1:]
    loss = step(x, y)
    float(loss)
    tps, noise, loss = _timed_rate(lambda: step(x, y), batch * seq, steps)
    flops_tok = gpt_train_flops_per_token(cfg, seq)
    mfu = tps * flops_tok / _peak_flops(dev) if on_tpu else 0.0
    print(f"# gpt-1.3B device={dev.device_kind} loss={float(loss):.4f} "
          f"mfu={mfu:.3f} noise={noise}%", file=sys.stderr)
    # overlap probe reuses the live step (no second 1.3B model on TPU);
    # parity on the CPU fallback only, where the model is gpt-tiny
    overlap = _input_overlap_block(
        step, [(x, y)] * (4 if on_tpu else 3),
        parity_make=None if on_tpu else (lambda: make_step()[1]))
    return {
        "noise_pct": noise,
        "metric": f"gpt_{name}_tokens_per_sec_per_chip",
        "value": round(tps, 1),
        "unit": "tokens/s/chip",
        "input_overlap": overlap,
        "vs_baseline": round(mfu / 0.35, 4) if on_tpu else 0.0,
    }


def bench_resnet50():
    """ResNet-50 ImageNet-shape training step, images/s/chip (BASELINE.md
    row 1; reference model zoo resnet50)."""
    import jax
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    import paddle_tpu.nn as nn
    from paddle_tpu.vision.models import resnet50

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    # batch 128 amortizes the fixed per-op costs best on one v5e chip
    # (measured: 64 -> 0.130 MFU, 128 -> 0.146, 256 -> 0.143)
    batch, steps = (128, 10) if on_tpu else (2, 2)
    size = 224 if on_tpu else 32

    def make_step():
        paddle.seed(0)
        # stem_s2d: space-to-depth stem, +1.4% end-to-end measured (2541 ->
        # 2577 img/s; exact-equivalent math, docs/PERF.md round-4 A/B)
        m = resnet50(num_classes=1000, stem_s2d=on_tpu)
        o = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                      parameters=m.parameters(),
                                      weight_decay=1e-4)
        return dist.make_train_step(
            m, o, loss_fn=nn.CrossEntropyLoss(),
            compute_dtype="bfloat16" if on_tpu else None)

    step = make_step()
    rng = np.random.RandomState(0)
    # device-resident batch: a real input pipeline overlaps H2D with
    # compute; through the remote tunnel an un-overlapped 38 MB image batch
    # would otherwise dominate the measurement (docs/PERF.md).  The K-step
    # stack is materialized ON DEVICE (broadcast of one batch) and stepped
    # through run_steps — one dispatch for all K steps, the same
    # amortization the reference gets from its C++ trainer run loop
    # (trainer.cc); at ~26 ms device steps the per-dispatch tunnel cost
    # would otherwise add ~8 ms/step.
    import jax.numpy as jnp
    x1 = jnp.asarray(
        rng.standard_normal((batch, 3, size, size)).astype(np.float32))
    y1 = jnp.asarray(rng.randint(0, 1000, (batch,)).astype(np.int64))
    rep = jax.jit(lambda a, k: jnp.broadcast_to(a[None], (k,) + a.shape) + 0,
                  static_argnums=1)
    x, y = rep(x1, steps), rep(y1, steps)
    jax.block_until_ready(x)
    loss = step.run_steps(x, y)  # compile + warmup
    np.asarray(loss.numpy() if hasattr(loss, "numpy") else loss)
    # value: 3 back-to-back run_steps stacks, ONE sync (= BENCH_r04
    # methodology, cross-round comparable)
    t0 = time.perf_counter()
    for _ in range(3):
        loss = step.run_steps(x, y)
    losses = np.asarray(loss.numpy() if hasattr(loss, "numpy") else loss)
    ips = batch * steps * 3 / (time.perf_counter() - t0)
    # noise band: equal-sized singly-synced stacks (sync bias cancels)
    rates = []
    for _ in range(3):
        t0 = time.perf_counter()
        loss = step.run_steps(x, y)
        losses = np.asarray(loss.numpy() if hasattr(loss, "numpy")
                            else loss)
        rates.append(batch * steps / (time.perf_counter() - t0))
    loss = float(losses[-1])
    noise = round(100 * (max(rates) - min(rates)) / float(np.median(rates)),
                  2)
    # ~3.8 GFLOP/image fwd at 224², x3 for fwd+bwd
    mfu = ips * 3 * 3.8e9 / _peak_flops(dev) if on_tpu else 0.0
    print(f"# resnet50 device={dev.device_kind} loss={float(loss):.4f} "
          f"mfu={mfu:.3f} batch={batch} noise={noise}%", file=sys.stderr)
    # overlap probe: host-side [K,B,...] stacks (38 MB/batch images are
    # exactly the payload the prefetcher exists for) through the SAME
    # compiled run_steps signature — sync inline puts vs prefetched
    x_np = rng.standard_normal((batch, 3, size, size)).astype(np.float32)
    y_np = rng.randint(0, 1000, (batch,)).astype(np.int64)
    stack = (np.broadcast_to(x_np[None], (steps,) + x_np.shape),
             np.broadcast_to(y_np[None], (steps,) + y_np.shape))
    overlap = _input_overlap_block(
        step, [stack] * (3 if on_tpu else 2), stacked=True,
        parity_make=None if on_tpu else make_step)
    return {
        "metric": "resnet50_images_per_sec_per_chip",
        "value": round(ips, 1),
        "noise_pct": noise,
        "unit": "images/s/chip",
        "input_overlap": overlap,
        "vs_baseline": round(mfu / 0.35, 4) if on_tpu else 0.0,
    }


def bench_ppyoloe():
    """PP-YOLOE-s-class detector train step at 640x640 (BASELINE.md row 6;
    conv-heavy detection workload on top of the same conv/BN path as
    ResNet).  No reference number exists in-tree, so vs_baseline reports
    MFU/0.35 like the other rows (FLOPs ~17.4 GFLOP/image fwd at 6402 for
    the s scale)."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.vision.models import PPYOLOE, PPYOLOELoss

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    batch, size, steps = (8, 640, 10) if on_tpu else (2, 64, 2)

    paddle.seed(0)
    model = PPYOLOE(num_classes=80)
    loss_fn = PPYOLOELoss(model)
    opt = paddle.optimizer.Momentum(learning_rate=0.01, momentum=0.9,
                                    parameters=model.parameters(),
                                    weight_decay=5e-4)
    step = dist.make_train_step(
        model, opt, loss_fn=loss_fn, num_labels=2,
        compute_dtype="bfloat16" if on_tpu else None)
    rng = np.random.RandomState(0)
    x = jnp.asarray(
        rng.standard_normal((batch, 3, size, size)).astype(np.float32))
    gtb = jnp.asarray(np.stack([np.array([[4, 4, 300, 300], [64, 32, 400,
                                          500]], "float32")] * batch))
    gtl = jnp.asarray(np.stack([np.array([1, 3], "int64")] * batch))
    loss = step(x, gtb, gtl)
    float(loss)
    ips, noise, loss = _timed_rate(lambda: step(x, gtb, gtl), batch, steps)
    mfu = ips * 3 * 17.4e9 / _peak_flops(dev) if on_tpu else 0.0
    print(f"# ppyoloe device={dev.device_kind} loss={float(loss):.4f} "
          f"mfu={mfu:.3f} noise={noise}%", file=sys.stderr)
    return {
        "metric": "ppyoloe_s_images_per_sec_per_chip",
        "value": round(ips, 1),
        "noise_pct": noise,
        "unit": "images/s/chip",
        "vs_baseline": round(mfu / 0.35, 4) if on_tpu else 0.0,
    }


def bench_bert():
    """BERT-base MLM-shape step, tokens/s/chip (BASELINE.md row 2; the DP
    scaling leg runs on the CPU-sim mesh in tests/test_bert.py)."""
    import jax
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.models import (BertPretrainingCriterion, bert_config,
                                   build_bert)

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    # batch 64 measured +17% tokens/s over the round-4 batch 16 (0.355 ->
    # 0.415 MFU: BERT at 16x512 has half GPT's tokens/step, so the
    # param-proportional costs — AdamW f32 state traffic, vocab-head
    # wgrad — weighed double; docs/PERF.md round-5 BERT section)
    batch, seq, steps = (64, 512, 9) if on_tpu else (2, 64, 2)
    name = "bert-base-uncased" if on_tpu else "bert-tiny"

    paddle.seed(0)
    cfg = bert_config(name, hidden_dropout_prob=0.0,
                      attention_dropout_prob=0.0)
    model = build_bert(cfg)
    if on_tpu:
        # bf16 params + AdamW state — the same measured recipe the 1.3B
        # leg ships (docs/PERF.md): +5% over f32 masters at batch 64
        # (0.415 -> 0.435 MFU), loss parity to 3e-4 at step 10
        model.to(dtype="bfloat16")
    crit = BertPretrainingCriterion()

    def loss_fn(out, labels, nsp_labels):
        mlm, nsp = out
        return crit(mlm, nsp, labels, nsp_labels)

    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    step = dist.make_train_step(
        model, opt, loss_fn=loss_fn, num_labels=2)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int64)
    labels = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int64)
    nsp = rng.randint(0, 2, (batch,)).astype(np.int64)
    loss = step(ids, labels, nsp)
    float(loss)
    tps, noise, loss = _timed_rate(
        lambda: step(ids, labels, nsp), batch * seq, steps)
    # 6 * params flops/token (110M)
    mfu = tps * 6 * 110e6 / _peak_flops(dev) if on_tpu else 0.0
    print(f"# bert device={dev.device_kind} loss={float(loss):.4f} "
          f"mfu={mfu:.3f} noise={noise}%", file=sys.stderr)
    return {
        "metric": "bert_base_tokens_per_sec_per_chip",
        "value": round(tps, 1),
        "noise_pct": noise,
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.35, 4) if on_tpu else 0.0,
    }


def bench_gpt_decode():
    """Serving leg (round-5 verdict ask #5): GPT-2-small KV-cache decode
    through HybridParallelInferenceHelper — prefill once, then
    autoregressive per-token steps with donated cache buffers (the
    AnalysisPredictor zero-copy analog, analysis_predictor.cc:1618).
    Reports decode tokens/s and ms/token; vs_baseline is decode HBM
    utilization: roofline ms/token (params read once per token at spec
    bandwidth) over measured ms/token."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.distributed.fleet.utils import (
        HybridParallelInferenceHelper)
    from paddle_tpu.models import build_gpt, gpt_config

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    if on_tpu:
        name, batch, prompt, new = "gpt2-small-en", 8, 512, 64
    else:
        name, batch, prompt, new = "gpt-tiny", 2, 16, 4

    cfg = gpt_config(name, max_position_embeddings=max(prompt + new, 128),
                     hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    paddle.seed(0)
    if on_tpu:
        paddle.set_default_dtype("bfloat16")
    try:
        model = build_gpt(cfg)
    finally:
        paddle.set_default_dtype("float32")
    model.eval()
    helper = HybridParallelInferenceHelper(model,
                                           max_length=prompt + new)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, prompt)).astype(np.int64)
    # decode-only differential: generations at n=new and n=1 share the
    # (compute-bound) prefill cost, so their time difference isolates the
    # per-token decode loop.  Warm each shape twice (compile + allocator
    # settle), then 3 timed reps each.
    def timed(n, reps=3):
        helper.generate(ids, max_new_tokens=n)
        helper.generate(ids, max_new_tokens=n)
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            out = helper.generate(ids, max_new_tokens=n)
            ts.append(time.perf_counter() - t0)
        assert out.shape == (batch, prompt + n)
        return ts

    t_full = timed(new)
    t_one = timed(1)
    dts = [a - b for a, b in zip(sorted(t_full), sorted(t_one))]
    dt = float(np.median(dts))
    # prefill noise can swamp the decode delta on fast/tiny runs and push
    # the median to <= 0; clamp so the reported JSON can't carry a
    # divide-by-zero or negative tokens/s
    eps = 1e-9
    if dt < eps:
        print(f"# gpt-decode: decode delta {dt:.3e}s <= 0 (prefill noise "
              f"dominates); clamping to {eps}", file=sys.stderr)
        dt = eps
    noise = round(100 * (max(dts) - min(dts)) / dt, 2)
    tps = batch * (new - 1) / dt
    ms_tok = dt / (new - 1) * 1000
    prefill_ms = float(np.median(t_one)) * 1000
    # decode roofline: every param read once per token (bf16) at HBM BW
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    roofline_ms = n_params * 2 / 819e9 * 1000
    util = roofline_ms / ms_tok if on_tpu else 0.0
    print(f"# gpt-decode device={dev.device_kind} batch={batch} "
          f"prompt={prompt} new={new} {tps:,.0f} tok/s "
          f"{ms_tok:.2f} ms/token (prefill+1 {prefill_ms:.0f} ms) "
          f"noise={noise}%", file=sys.stderr)
    return {
        "metric": "gpt_decode_tokens_per_sec_per_chip",
        "value": round(tps, 1),
        "unit": "tokens/s/chip",
        "ms_per_token": round(ms_tok, 3),
        "prefill_ms": round(prefill_ms, 1),
        "batch": batch,
        "noise_pct": noise,
        "vs_baseline": round(util, 4),
    }


def bench_serving():
    """Continuous-batching serving leg (ISSUE 3): synthetic Poisson
    arrivals through serving.Engine — many concurrent requests share one
    compiled prefill and ONE compiled decode step over a fixed slot pool.
    Reports request throughput, token throughput, p50/p99 time-to-first-
    token and per-token latency; asserts the continuous-batching
    invariants (all requests complete, slots recycled, decode never
    retraces after warmup).  Then sweeps offered QPS through the HTTP
    gateway (ISSUE 8) for the closed-loop latency-under-load curve —
    client-measured TTFT percentiles, tokens/s and shed rate per level
    (`gateway` block)."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.models import build_gpt, gpt_config
    from paddle_tpu.serving import Engine

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    if on_tpu:
        name, slots, max_len, n_req, new = "gpt2-small-en", 8, 640, 24, 32
        p_lo, p_hi, rate = 32, 128, 50.0
    else:  # CI/CPU: tiny shapes, same code path (>=16 concurrent requests)
        name, slots, max_len, n_req, new = "gpt-tiny", 4, 64, 16, 8
        p_lo, p_hi, rate = 4, 12, 50.0

    cfg = gpt_config(name, max_position_embeddings=max(max_len, 128),
                     hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    paddle.seed(0)
    model = build_gpt(cfg)
    model.eval()
    # device perfscope ON for the leg: every Nth decode dispatch is
    # timed, so the leg reports MFU/BW-per-program for free (the next
    # hardware round's roofline comes straight from this block)
    from paddle_tpu.observability import perfscope
    prev_sample = perfscope.sample_every()
    perfscope.set_sample_every(
        int(os.environ.get("PADDLE_TPU_PERFSCOPE_SAMPLE", "4") or 0))
    perfscope.reset_programs()
    engine = Engine(model, max_slots=slots, max_len=max_len,
                    max_queue=2 * n_req)
    try:
        rs = np.random.RandomState(0)
        prompts = [rs.randint(0, cfg.vocab_size,
                              rs.randint(p_lo, p_hi + 1)).astype(np.int64)
                   for _ in range(n_req)]
        # warmup: compile the decode step and both pow2 prompt buckets
        for plen in {len(min(prompts, key=len)), len(max(prompts, key=len))}:
            engine.submit(prompts[0][:plen] if plen <= len(prompts[0])
                          else rs.randint(0, cfg.vocab_size,
                                          plen).astype(np.int64),
                          max_new_tokens=2).result(timeout=600)
        warm_decode = engine.compile_stats()["decode_compiles"]

        t0 = time.perf_counter()
        handles = []
        for p in prompts:  # Poisson arrivals: exp-distributed gaps
            handles.append(engine.submit(p, max_new_tokens=new))
            time.sleep(min(rs.exponential(1.0 / rate), 0.25))
        for h in handles:
            h.result(timeout=600)
        wall = time.perf_counter() - t0
        st = engine.stats()
        decode_compiles = engine.compile_stats()["decode_compiles"]
        perf_rep = perfscope.perf_report()
    finally:
        engine.shutdown()
        perfscope.set_sample_every(prev_sample)

    if st["completed"] < n_req:
        raise RuntimeError(f"serving leg: only {st['completed']}/{n_req} "
                           f"requests completed: {st}")
    if st["slot_reuses"] <= 0:
        raise RuntimeError(f"serving leg: no slot reuse across {n_req} "
                           f"requests over {slots} slots: {st}")
    if decode_compiles != warm_decode:
        raise RuntimeError(
            f"serving leg: decode retraced after warmup "
            f"({warm_decode} -> {decode_compiles} signatures)")
    # perfscope roofline gate: the decode program must have sampled at
    # ONE compiled signature, and its reported MFU/BW fraction must match
    # the cost_analysis-derived expectation (flops / (mean sampled dt x
    # peak)) — validating the whole attribution chain on every CPU run
    dec = next((p for p in perf_rep["programs"]
                if p["program"] == "serving.decode"), None)
    if dec is None or not dec["sampled"]:
        raise RuntimeError(
            f"serving leg: perfscope sampled no decode dispatches: "
            f"{perf_rep['programs']}")
    if dec["signatures"] != 1:
        raise RuntimeError(
            f"serving leg: decode registered {dec['signatures']} "
            f"signatures with perfscope sampling on (must stay at 1)")
    mean_dt = dec["device_s"] / dec["sampled"]
    for got, flop_or_bytes, peak in (
            (dec["mfu"], dec["flops"], perf_rep["peak_flops"]),
            (dec["hbm_bw_frac"], dec["bytes"], perf_rep["peak_hbm_bw"])):
        if not (flop_or_bytes and peak):
            continue
        expect = flop_or_bytes / (mean_dt * peak)
        if got is None or abs(got - expect) > 0.02 * expect + 1e-9:
            raise RuntimeError(
                f"serving leg: perfscope roofline mismatch: got {got}, "
                f"cost_analysis expectation {expect:.6g}")
    perfscope_block = {
        "sample_every": perf_rep["sample_every"],
        "peak_flops": perf_rep["peak_flops"],
        "peak_hbm_bw": perf_rep["peak_hbm_bw"],
        "programs": {p["program"]: {
            k: p[k] for k in ("dispatches", "sampled", "device_s",
                              "est_total_s", "share", "mfu",
                              "hbm_bw_frac")}
            for p in perf_rep["programs"]},
    }
    total_tokens = sum(len(h.generated) for h in handles)
    ttfts = np.array([h.ttft_s for h in handles])
    toks = np.array([t for h in handles for t in h.token_latencies_s])
    # seed the gateway sweep's shed model with the measured engine
    # latencies so the first load level already sheds meaningfully
    measured = {"prefill_s": float(np.percentile(ttfts, 50)),
                "token_s": float(np.percentile(toks, 50))}
    fast_path_block = _bench_fast_path(model, cfg, on_tpu)
    paged_block = _bench_paged_kv(model, cfg, on_tpu)
    decode_kernel_block = _bench_decode_kernel(model, cfg, on_tpu)
    kv_tier_block = _bench_kv_tier(model, cfg, on_tpu)
    multi_lora_block = _bench_multi_lora(model, cfg, on_tpu)
    gateway_block = _bench_gateway_curve(cfg, on_tpu, measured)
    autoscale_block = _bench_autoscale_curve(measured)
    slo_block = _bench_slo_alerting(measured)
    capture_block = _bench_capture_fit(measured)
    tok_p50 = float(np.percentile(toks, 50))
    noise = round(100 * (float(np.percentile(toks, 90)) -
                         float(np.percentile(toks, 10))) / tok_p50, 2) \
        if tok_p50 else 0.0
    tps = total_tokens / wall
    print(f"# serving device={dev.device_kind} slots={slots} "
          f"requests={n_req} {tps:,.0f} tok/s "
          f"ttft p50={np.percentile(ttfts, 50) * 1e3:.1f}ms "
          f"p99={np.percentile(ttfts, 99) * 1e3:.1f}ms "
          f"token p50={tok_p50 * 1e3:.2f}ms "
          f"reuses={st['slot_reuses']}", file=sys.stderr)
    return {
        "metric": "serving_tokens_per_sec_per_chip",
        "value": round(tps, 1),
        "unit": "tokens/s/chip",
        "noise_pct": noise,
        "vs_baseline": 0.0,
        "requests": n_req,
        "requests_per_sec": round(n_req / wall, 2),
        "max_slots": slots,
        "slot_reuses": int(st["slot_reuses"]),
        "decode_steps": int(st["decode_steps"]),
        "decode_compiles": int(decode_compiles),
        "ttft_ms": {"p50": round(float(np.percentile(ttfts, 50)) * 1e3, 2),
                    "p99": round(float(np.percentile(ttfts, 99)) * 1e3, 2)},
        "token_ms": {"p50": round(tok_p50 * 1e3, 3),
                     "p99": round(float(np.percentile(toks, 99)) * 1e3, 3)},
        "fast_path": fast_path_block,
        "paged_kv": paged_block,
        "decode_kernel": decode_kernel_block,
        "kv_tier": kv_tier_block,
        "multi_lora": multi_lora_block,
        "gateway": gateway_block,
        "autoscale": autoscale_block,
        "slo": slo_block,
        "capture": capture_block,
        "perfscope": perfscope_block,
    }


def _bench_fast_path(model, cfg, on_tpu):
    """Decode fast-path blocks (ISSUE 10): prefix caching, speculative
    decoding and int8 KV, each measured on the serving engine with its
    flag on and parity-gated against the plain engine (CPU-runnable,
    like the input_overlap blocks).  Reports prefix hit rate + TTFT
    delta, draft acceptance rate + effective tokens per verify dispatch,
    and pool bytes + token-level quality delta for int8."""
    from paddle_tpu.serving import Engine

    if on_tpu:
        slots, max_len, new = 8, 640, 32
        shared_len, tail_len, n_req, block = 384, 16, 16, 16
    else:
        slots, max_len, new = 4, 64, 8
        shared_len, tail_len, n_req, block = 24, 4, 8, 4

    rs = np.random.RandomState(11)
    shared = rs.randint(0, cfg.vocab_size, shared_len).astype(np.int64)

    def make_prompts():
        return [np.concatenate(
            [shared,
             rs.randint(0, cfg.vocab_size, tail_len).astype(np.int64)])
            for _ in range(n_req)]

    prompts_w, prompts_m = make_prompts(), make_prompts()

    def run(engine, prompts):
        handles = [engine.submit(p, max_new_tokens=new) for p in prompts]
        outs = [h.result(timeout=600) for h in handles]
        return handles, outs

    def admit_to_first(handles):
        return [h.ttft_s - (h.t_admit - h.t_submit) for h in handles]

    # -- baseline: plain engine.  Wave 1 warms the compiles; wave 2 is
    # the measured cold-prefill reference (admit->first-token, so queue
    # wait behind earlier waves doesn't pollute the comparison) --------
    plain = Engine(model, max_slots=slots, max_len=max_len,
                   max_queue=2 * n_req)
    _, base_w = run(plain, prompts_w)
    h_plain, base_m = run(plain, prompts_m)
    plain_st = plain.stats()
    plain_bytes = plain.pool_bytes()
    plain.shutdown()
    cold_adm = admit_to_first(h_plain)

    # -- prefix cache: wave 1 seeds the index (and compiles the tail
    # program via its own later admissions); wave 2 hits a warm cache
    # with warm programs — the measured TTFT win -------------------------
    eng = Engine(model, max_slots=slots, max_len=max_len,
                 max_queue=2 * n_req, prefix_cache=True,
                 prefix_block=block)
    _, outs_w = run(eng, prompts_w)
    st1 = eng.stats()
    h_hit, outs_m = run(eng, prompts_m)
    st = eng.stats()
    eng.shutdown()
    for b, o in zip(base_w + base_m, outs_w + outs_m):
        np.testing.assert_array_equal(b, o)   # hits change nothing
    hits_m = st["prefix_hits"] - st1["prefix_hits"]
    misses_m = st["prefix_misses"] - st1["prefix_misses"]
    if hits_m <= 0:
        raise RuntimeError(f"fast path: no prefix hits on a shared-prefix "
                           f"workload: {st}")
    if st["decode_compiles"] != 1:
        raise RuntimeError(f"fast path: prefix cache retraced decode: {st}")
    hit_adm = admit_to_first([h for h in h_hit if h.prefix_hit])
    prefix_block_out = {
        "requests": n_req,
        "hit_rate": round(hits_m / max(hits_m + misses_m, 1), 3),
        "shared_prefix_tokens": shared_len,
        "admit_to_first_ms_hit_p50": round(
            float(np.percentile(hit_adm, 50)) * 1e3, 2),
        "admit_to_first_ms_cold_p50": round(
            float(np.percentile(cold_adm, 50)) * 1e3, 2),
        "ttft_delta_ms": round(
            (float(np.percentile(cold_adm, 50)) -
             float(np.percentile(hit_adm, 50))) * 1e3, 2),
        "tail_prefill_compiles": st["tail_prefill_compiles"],
        "decode_compiles": st["decode_compiles"],
        "parity": "exact",
    }

    # -- speculative: accepted drafts > 1 token per pool read ------------
    eng = Engine(model, max_slots=slots, max_len=max_len,
                 max_queue=2 * n_req, speculative_k=4)
    _, outs = run(eng, prompts_w)
    st = eng.stats()
    eng.shutdown()
    for b, o in zip(base_w, outs):      # greedy token-identical gate
        np.testing.assert_array_equal(b, o)
    # decode tokens only: the first token of each request comes from its
    # prefill, not from a verify dispatch
    tokens_per_verify = (st["tokens"] - n_req) / max(st["decode_steps"], 1)
    if tokens_per_verify <= 1.0:
        raise RuntimeError(
            f"fast path: speculative decode gained nothing "
            f"({tokens_per_verify:.2f} tokens/verify): {st}")
    if st["decode_compiles"] != 1:
        raise RuntimeError(f"fast path: speculation retraced decode: {st}")
    spec_block = {
        "k": 4,
        "drafted": int(st["spec_drafted"]),
        "accepted": int(st["spec_accepted"]),
        "acceptance_rate": round(
            st["spec_accepted"] / max(st["spec_drafted"], 1), 3),
        "tokens_per_verify": round(tokens_per_verify, 3),
        "verify_steps": int(st["decode_steps"]),
        "plain_decode_steps": int(plain_st["decode_steps"]),
        "decode_compiles": st["decode_compiles"],
        "parity": "exact",
    }

    # -- int8 KV: 2x slots in the same pool bytes ------------------------
    eng = Engine(model, max_slots=2 * slots, max_len=max_len,
                 max_queue=2 * n_req, kv_dtype="int8")
    _, outs = run(eng, prompts_w)
    st = eng.stats()
    int8_bytes = eng.pool_bytes()
    eng.shutdown()
    if int8_bytes > plain_bytes:
        raise RuntimeError(
            f"fast path: int8 pool at 2x slots ({int8_bytes}B) exceeds "
            f"the float pool at 1x ({plain_bytes}B)")
    if st["decode_compiles"] != 1:
        raise RuntimeError(f"fast path: int8 KV retraced decode: {st}")
    match = float(np.mean([np.mean(
        np.pad(b, (0, max(0, len(o) - len(b))))[:min(len(b), len(o))] ==
        np.pad(o, (0, max(0, len(b) - len(o))))[:min(len(b), len(o))])
        for b, o in zip(base_w, outs)]))
    int8_block = {
        "max_slots": 2 * slots,
        "kv_pool_bytes": int(int8_bytes),
        "baseline_pool_bytes_1x": int(plain_bytes),
        "bytes_ratio_vs_1x_float": round(int8_bytes / plain_bytes, 3),
        "token_match_vs_float": round(match, 3),
        "decode_compiles": st["decode_compiles"],
    }
    print(f"# fast-path prefix hit_rate="
          f"{prefix_block_out['hit_rate']} spec tokens/verify="
          f"{spec_block['tokens_per_verify']} int8 2x-slots bytes ratio="
          f"{int8_block['bytes_ratio_vs_1x_float']} "
          f"match={int8_block['token_match_vs_float']}", file=sys.stderr)
    return {"prefix_cache": prefix_block_out, "speculative": spec_block,
            "kv_int8": int8_block}


def _bench_kv_tier(model, cfg, on_tpu):
    """KV tiering block (ISSUE 18): multi-turn conversations whose
    turn-1 KV pages are EVICTED from the device pool before the warm
    turn arrives.  The tiered engine (``host_prefix_mb=``) demotes the
    victims to host DRAM and serves the warm turn via promote —
    tail-prefill only; the untiered engine pays full re-prefill.
    Reports warm-vs-cold admit->first-token, the host-tier hit rate and
    promote p50, and GATES warm < cold (the whole point of the tier).
    In ROADMAP's standing next-hardware-round list."""
    import paddle_tpu as paddle
    from paddle_tpu.models import build_gpt, gpt_config
    from paddle_tpu.serving import Engine

    if on_tpu:
        slots, max_len, turn, new, n_conv, num_pages, block = \
            8, 640, 256, 32, 8, 192, 16
    else:
        # gpt-tiny prefill is dispatch-dominated on CPU (a 4-token tail
        # costs the same as a 64-token prompt), which would make the
        # warm-vs-cold gate meaningless — this block sizes the model up
        # until COMPUTE dominates, the regime the tier exists for
        cfg = gpt_config("gpt-tiny", hidden_size=512, num_layers=6,
                         num_attention_heads=8,
                         hidden_dropout_prob=0.0,
                         attention_dropout_prob=0.0)
        paddle.seed(0)
        model = build_gpt(cfg)
        model.eval()
        slots, max_len, turn, new, n_conv, num_pages, block = \
            2, 96, 56, 4, 4, 48, 4

    rs = np.random.RandomState(19)
    firsts = [rs.randint(0, cfg.vocab_size, turn).astype(np.int64)
              for _ in range(n_conv)]
    extras = [rs.randint(0, cfg.vocab_size, block).astype(np.int64)
              for _ in range(n_conv)]

    def run(tiered):
        kw = {"host_prefix_mb": 64} if tiered else {}
        eng = Engine(model, max_slots=slots, max_len=max_len,
                     max_queue=4 * n_conv, prefix_cache=True,
                     prefix_block=block, paged_kv=True,
                     num_pages=num_pages, **kw)
        try:
            # warm the prefill buckets + decode compile out of the
            # measured window
            eng.submit(firsts[0][:turn // 2],
                       max_new_tokens=2).result(timeout=600)
            # turn 1 of every conversation, sequentially: each insert
            # pressures the fixed pool, so early entries are evicted
            # (tiered: demoted to host) before their warm turn returns
            replies = [np.asarray(eng.submit(
                p, max_new_tokens=new,
                conversation=f"conv{i}").result(timeout=600))
                for i, p in enumerate(firsts)]
            if eng._host_tier is not None:
                eng._host_tier.flush()
            handles, outs = [], []
            for i, (p, r, x) in enumerate(zip(firsts, replies, extras)):
                warm = np.concatenate([p, r, x]).astype(np.int64)
                h = eng.submit(warm, max_new_tokens=new,
                               conversation=f"conv{i}")
                outs.append(np.asarray(h.result(timeout=600)))
                handles.append(h)
            st = eng.stats()
        finally:
            eng.shutdown()
        return handles, outs, st

    h_cold, outs_cold, st_cold = run(tiered=False)
    h_warm, outs_warm, st_warm = run(tiered=True)
    for b, o in zip(outs_cold, outs_warm):   # the tier changes nothing
        np.testing.assert_array_equal(b, o)
    if st_warm["decode_compiles"] != 1:
        raise RuntimeError(f"kv tier: promote retraced decode: {st_warm}")
    promoted = [h for h in h_warm if h.promote_s is not None]
    if not promoted:
        raise RuntimeError(
            f"kv tier: no warm turn was served via a host-tier promote "
            f"(nothing evicted?): {st_warm}")
    # cold reference: only TRUE re-prefills (a late conversation whose
    # entry survived in the device index would pollute the baseline)
    cold = [h for h in h_cold if not h.prefix_hit]
    if not cold:
        raise RuntimeError(
            "kv tier: the untiered run never re-prefilled — the pool "
            "never evicted, the comparison is void")

    def admit_to_first(handles):
        return [h.ttft_s - (h.t_admit - h.t_submit) for h in handles]

    warm_p50 = float(np.percentile(admit_to_first(promoted), 50))
    cold_p50 = float(np.percentile(admit_to_first(cold), 50))
    if warm_p50 >= cold_p50:
        raise RuntimeError(
            f"kv tier: warm TTFT p50 ({warm_p50 * 1e3:.2f}ms) is not "
            f"below cold re-prefill p50 ({cold_p50 * 1e3:.2f}ms)")
    tier_st = st_warm["host_prefix"]
    hit_rate = tier_st["hits"] / max(tier_st["hits"] +
                                     tier_st["misses"], 1)
    promote_p50 = float(np.percentile(
        [h.promote_s for h in promoted], 50))
    block_out = {
        "conversations": n_conv,
        "turn_tokens": turn,
        "host_capacity_mb": 64,
        "demotes": int(tier_st["demotes"]),
        "host_hit_rate": round(hit_rate, 3),
        "promotes": int(st_warm["host_prefix_promotes"]),
        "promote_ms_p50": round(promote_p50 * 1e3, 3),
        "warm_ttft_ms_p50": round(warm_p50 * 1e3, 2),
        "cold_ttft_ms_p50": round(cold_p50 * 1e3, 2),
        "ttft_delta_ms": round((cold_p50 - warm_p50) * 1e3, 2),
        "decode_compiles": int(st_warm["decode_compiles"]),
        "parity": "exact",
    }
    print(f"# kv-tier warm p50={block_out['warm_ttft_ms_p50']}ms "
          f"cold p50={block_out['cold_ttft_ms_p50']}ms "
          f"host hit_rate={block_out['host_hit_rate']} "
          f"promote p50={block_out['promote_ms_p50']}ms", file=sys.stderr)
    return block_out


def _bench_multi_lora(model, cfg, on_tpu):
    """Multi-LoRA block (ISSUE 12): many-adapter mixed traffic with a
    hot/cold skew through one engine, all CPU-gateable.

    * a registry holding MORE adapters than the resident bank, with 70%
      of traffic on two hot adapters — cold adapters churn through
      admission-time loads + LRU eviction while the hot ones stay
      resident; reports tokens/s, the resident-bank hit rate, and the
      p50 cold-adapter admit stall (bank upload wall time);
    * ``weight_int8`` — the SAME mixed traffic on
      ``Engine(weight_dtype="int8")``: stored weight bytes ratio vs f32
      and a token-match gate (>= 0.9) against the f32 outputs;
    * decode stays at ONE compiled signature in both configs.
    """
    from paddle_tpu.serving import AdapterRegistry, Engine, make_lora

    if on_tpu:
        slots, max_len, new, n_req = 8, 640, 32, 24
        n_adapters, resident, rank = 8, 4, 8
    else:
        slots, max_len, new, n_req = 4, 64, 8, 16
        n_adapters, resident, rank = 6, 3, 4

    reg = AdapterRegistry(model, max_resident=resident, max_rank=rank)
    names = [f"lora{i}" for i in range(n_adapters)]
    for i, nm in enumerate(names):
        reg.register(make_lora(cfg, rank=rank, seed=100 + i, name=nm,
                               std=0.1))
    rs = np.random.RandomState(21)
    prompts = [rs.randint(0, cfg.vocab_size, 8).astype(np.int64)
               for _ in range(n_req)]
    # hot/cold skew: most traffic on two hot adapters, the rest rotates
    # through a cold tail wider than the bank (forces load + eviction)
    picks = [names[i % 2] if rs.rand() < 0.7
             else names[2 + i % (n_adapters - 2)] for i in range(n_req)]

    def run(engine):
        engine.submit(prompts[0], max_new_tokens=2).result(
            timeout=600)                       # warm the compiles
        t0 = time.perf_counter()
        handles = [engine.submit(p, max_new_tokens=new, adapter=nm)
                   for p, nm in zip(prompts, picks)]
        outs = [h.result(timeout=600) for h in handles]
        return outs, time.perf_counter() - t0

    eng = Engine(model, max_slots=slots, max_len=max_len,
                 max_queue=2 * n_req, adapters=reg)
    outs, wall = run(eng)
    st = eng.stats()
    load_ms = [t * 1e3 for t in eng._adapter_load_times]
    f32_bytes = eng.weight_bytes()
    eng.shutdown()
    if st["decode_compiles"] != 1:
        raise RuntimeError(f"multi_lora: adapters retraced decode: {st}")
    if st["adapter_evictions"] <= 0 or st["adapter_loads"] <= resident:
        raise RuntimeError(
            f"multi_lora: no cold-adapter churn on a {n_adapters}-adapter "
            f"mix over a {resident}-row bank: {st}")
    hits, loads = st["adapter_hits"], st["adapter_loads"]
    tokens = sum(len(o) for o in outs)

    # -- int8 base weights on the same mixed traffic ---------------------
    q = Engine(model, max_slots=slots, max_len=max_len,
               max_queue=2 * n_req, adapters=reg, weight_dtype="int8")
    qouts, _ = run(q)
    q_st = q.stats()
    q_bytes = q.weight_bytes()
    q.shutdown()
    if q_st["decode_compiles"] != 1:
        raise RuntimeError(
            f"multi_lora: int8 weights retraced decode: {q_st}")
    ratio = q_bytes / max(f32_bytes, 1)
    if ratio >= 0.5:
        raise RuntimeError(
            f"multi_lora: int8 weights did not halve the stored bytes "
            f"({q_bytes}B vs {f32_bytes}B)")
    match = float(np.mean([np.mean(b == g) for b, g in zip(outs, qouts)]))
    if match < 0.9:
        raise RuntimeError(
            f"multi_lora: int8 weights token match {match:.3f} < 0.9")

    block = {
        "requests": n_req,
        "adapters": n_adapters,
        "resident_bank": resident,
        "rank": rank,
        "tokens_per_sec": round(tokens / wall, 1),
        "resident_hit_rate": round(hits / max(hits + loads, 1), 3),
        "cold_loads": int(loads),
        "evictions": int(st["adapter_evictions"]),
        "load_stalls": int(st["adapter_load_stalls"]),
        "cold_admit_stall_ms_p50": round(
            float(np.percentile(load_ms, 50)), 2) if load_ms else 0.0,
        "decode_compiles": int(st["decode_compiles"]),
        "weight_int8": {
            "weight_bytes": int(q_bytes),
            "baseline_weight_bytes_f32": int(f32_bytes),
            "bytes_ratio": round(ratio, 3),
            "token_match_vs_f32": round(match, 3),
            "decode_compiles": int(q_st["decode_compiles"]),
        },
    }
    print(f"# multi_lora adapters={n_adapters}/bank={resident} "
          f"hit_rate={block['resident_hit_rate']} "
          f"cold stall p50={block['cold_admit_stall_ms_p50']}ms "
          f"int8 weights ratio={block['weight_int8']['bytes_ratio']} "
          f"match={block['weight_int8']['token_match_vs_f32']}",
          file=sys.stderr)
    return block


def _bench_paged_kv(model, cfg, on_tpu):
    """Paged KV block (ISSUE 11): the block-granular pool against the
    dense slot pool, all CPU-gateable.

    * ``effective_slots_per_hbm_byte`` — a heavy-tail length mix (many
      short requests, a few long) runs through a dense pool and a paged
      pool holding NO MORE bytes; the paged pool must sustain strictly
      more concurrent resident sequences per byte (its HBM scales with
      actual tokens, the dense pool's with max_len * slots).
    * ``long_context`` — a completion past the dense pool's compiled
      ``max_len`` (more page-table entries, same decode program).
    * ``prefix_hit`` — admit→first-token for warm prefix hits: the paged
      hit shares pages by reference (zero-copy page-table writes) where
      the dense hit device-copies the whole row bitwise.
    """
    from paddle_tpu.serving import Engine

    if on_tpu:
        slots, max_len, page = 8, 640, 16
        short_lo, short_new, long_len, long_new, n_req = 24, 16, 500, 32, 24
        shared_len, tail_len, n_hit = 384, 16, 8
    else:
        slots, max_len, page = 3, 64, 8
        short_lo, short_new, long_len, long_new, n_req = 6, 4, 48, 8, 12
        shared_len, tail_len, n_hit = 24, 4, 6

    rs = np.random.RandomState(17)

    def heavy_tail_prompts():
        # ~5/6 short, ~1/6 near-max_len long — the traffic shape the
        # dense pool provisions every slot for
        out = []
        for i in range(n_req):
            if i % 6 == 5:
                out.append((rs.randint(0, cfg.vocab_size,
                                       long_len).astype(np.int64), long_new))
            else:
                plen = rs.randint(short_lo, short_lo + 8)
                out.append((rs.randint(0, cfg.vocab_size,
                                       plen).astype(np.int64), short_new))
        return out

    def run_mix(engine, mix):
        handles = [engine.submit(p, max_new_tokens=new) for p, new in mix]
        peak = 0
        while not all(h.done() for h in handles):
            peak = max(peak, engine.slots_in_use())
            time.sleep(0.001)
        for h in handles:
            h.result(timeout=600)
        return handles, peak

    mix = heavy_tail_prompts()
    dense = Engine(model, max_slots=slots, max_len=max_len,
                   max_queue=2 * n_req)
    d_handles, d_peak = run_mix(dense, mix)
    dense_bytes = dense.pool_bytes()
    dense.shutdown()
    d_peak = max(d_peak, 1)

    # paged pool: MORE lanes, NO MORE bytes — pages sized to the dense
    # budget, so the byte denominator is apples-to-apples
    pages_budget = (slots * -(-max_len // page))
    paged = Engine(model, max_slots=3 * slots, max_len=max_len,
                   max_queue=2 * n_req, paged_kv=True, page_size=page,
                   num_pages=pages_budget)
    p_handles, p_peak = run_mix(paged, mix)
    paged_bytes = paged.pool_bytes()
    p_stats = paged.stats()
    paged.shutdown()
    for (dh, ph) in zip(d_handles, p_handles):   # greedy parity gate
        np.testing.assert_array_equal(dh.result(), ph.result())
    if paged_bytes > dense_bytes:
        raise RuntimeError(
            f"paged pool ({paged_bytes}B) exceeds the dense budget "
            f"({dense_bytes}B)")
    d_eff = d_peak / dense_bytes
    p_eff = p_peak / paged_bytes
    if p_eff <= d_eff:
        raise RuntimeError(
            f"paged_kv: effective slots per HBM byte did not improve "
            f"(paged {p_peak}/{paged_bytes}B vs dense "
            f"{d_peak}/{dense_bytes}B)")
    if p_stats["decode_compiles"] != 1:
        raise RuntimeError(f"paged_kv: decode retraced: {p_stats}")

    # long context: complete past a dense pool's compiled max_len (the
    # probe pool compiles at max_len // 2 so the demo stays inside the
    # model's position-embedding table on every platform; the paged
    # engine's table simply holds twice the entries)
    lc_max = max_len // 2
    lc = Engine(model, max_slots=2, max_len=lc_max, paged_kv=True,
                page_size=page, max_pages_per_slot=2 * (-(-lc_max // page)))
    lc_prompt = rs.randint(0, cfg.vocab_size, lc_max - 2).astype(np.int64)
    lc_new = min(2 * page, lc_max)       # finishes past lc_max
    lc_out = lc.submit(lc_prompt, max_new_tokens=lc_new).result(timeout=600)
    lc_len = int(lc_prompt.size + lc_out.size)
    lc.shutdown()
    if lc_len <= lc_max:
        raise RuntimeError(
            f"paged_kv: long-context completion did not pass the "
            f"compiled max_len ({lc_len} <= {lc_max})")

    # prefix-hit TTFT: zero-copy page sharing vs the dense row copy
    shared = rs.randint(0, cfg.vocab_size, shared_len).astype(np.int64)

    def hit_wave():
        return [np.concatenate(
            [shared, rs.randint(0, cfg.vocab_size,
                                tail_len).astype(np.int64)])
            for _ in range(n_hit)]

    def admit_to_first(handles):
        return [h.ttft_s - (h.t_admit - h.t_submit) for h in handles]

    def measure_hits(**kw):
        eng = Engine(model, max_slots=slots, max_len=max_len,
                     max_queue=2 * n_hit, prefix_cache=True,
                     prefix_block=page, **kw)
        for p in hit_wave():                       # warm: seed + compile
            eng.submit(p, max_new_tokens=short_new).result(timeout=600)
        hs = [eng.submit(p, max_new_tokens=short_new)
              for p in hit_wave()]                 # measured: warm hits
        for h in hs:
            h.result(timeout=600)
        st = eng.stats()
        eng.shutdown()
        hits = [h for h in hs if h.prefix_hit]
        return admit_to_first(hits), st

    dense_adm, dense_st = measure_hits()
    paged_adm, paged_st = measure_hits(paged_kv=True)
    if not paged_adm or not dense_adm:
        raise RuntimeError(
            f"paged_kv: no warm prefix hits to measure "
            f"(dense {dense_st}, paged {paged_st})")
    dense_p50 = float(np.percentile(dense_adm, 50))
    paged_p50 = float(np.percentile(paged_adm, 50))

    block = {
        "pool_bytes": {"dense": int(dense_bytes), "paged": int(paged_bytes)},
        "heavy_tail": {
            "requests": n_req,
            "peak_concurrent": {"dense": int(d_peak), "paged": int(p_peak)},
            "effective_slots_per_mib": {
                "dense": round(d_peak / (dense_bytes / 2**20), 3),
                "paged": round(p_peak / (paged_bytes / 2**20), 3)},
            "parity": "exact",
        },
        "long_context": {
            "compiled_max_len": lc_max,
            "completed_len": lc_len,
            "page_size": page,
        },
        "prefix_hit": {
            "admit_to_first_ms_dense_copy_p50": round(dense_p50 * 1e3, 2),
            "admit_to_first_ms_paged_zero_copy_p50": round(
                paged_p50 * 1e3, 2),
            "ttft_delta_ms": round((dense_p50 - paged_p50) * 1e3, 2),
            "cow_copies": int(paged_st["page_cow_copies"]),
        },
        "decode_compiles": int(p_stats["decode_compiles"]),
    }
    print(f"# paged_kv eff-slots/MiB dense="
          f"{block['heavy_tail']['effective_slots_per_mib']['dense']} "
          f"paged={block['heavy_tail']['effective_slots_per_mib']['paged']} "
          f"long_context={lc_len}>{lc_max} "
          f"hit ttft delta={block['prefix_hit']['ttft_delta_ms']}ms",
          file=sys.stderr)
    return block


def _bench_decode_kernel(model, cfg, on_tpu):
    """Decode-kernel block (ISSUE 19): the fused Pallas paged-attention
    read (``Engine(decode_kernel="pallas")``) against the XLA
    gather-then-attend paged path, composed with the int8 pool and
    speculative verify it exists to accelerate.

    CPU (interpret mode) gates correctness: greedy token parity, ONE
    compiled decode signature, and identical per-step dispatch counts
    (exact parity forces the same speculative accept trace, so a step
    drift means the kernel changed math).  tokens/s and measured
    HBM-bytes/token vs the XLA read are hardware numbers — interpret
    walls are not kernel timings — and stay reserved for the TPU round;
    the analytic streamed-bytes ratio is reported from the kernel's own
    perfscope cost booking.
    """
    from paddle_tpu.kernels import paged_attention as pa
    from paddle_tpu.observability import perfscope
    from paddle_tpu.serving import Engine

    if on_tpu:
        slots, max_len, page, n_req, new = 8, 640, 16, 16, 32
    else:
        slots, max_len, page, n_req, new = 3, 64, 8, 8, 6

    rs = np.random.RandomState(23)
    prompts = [rs.randint(0, cfg.vocab_size,
                          rs.randint(6, 20)).astype(np.int64)
               for _ in range(n_req)]

    def run(kernel):
        eng = Engine(model, max_slots=slots, max_len=max_len,
                     max_queue=2 * n_req, paged_kv=True, page_size=page,
                     kv_dtype="int8", speculative_k=3,
                     decode_kernel=kernel)
        t0 = time.perf_counter()
        handles = [eng.submit(p, max_new_tokens=new) for p in prompts]
        outs = [h.result(timeout=600) for h in handles]
        wall = time.perf_counter() - t0
        st = eng.stats()
        eng.shutdown()
        return outs, st, wall

    x_out, x_st, x_wall = run("xla")
    p_out, p_st, p_wall = run("pallas")
    for a, b in zip(x_out, p_out):            # greedy parity gate
        np.testing.assert_array_equal(a, b)
    if p_st["decode_compiles"] != 1:
        raise RuntimeError(
            f"decode_kernel: pallas decode retraced: {p_st}")
    if p_st["decode_steps"] != x_st["decode_steps"]:
        raise RuntimeError(
            f"decode_kernel: per-step dispatch counts diverged "
            f"(xla {x_st['decode_steps']} vs pallas "
            f"{p_st['decode_steps']})")
    prog = perfscope._programs.get(pa.PERFSCOPE_PROGRAM)
    if prog is None or not prog.costs:
        raise RuntimeError(
            "decode_kernel: kernel never booked its perfscope cost")
    # analytic streamed-bytes ratio: what HBM moves per attended
    # position under the fused int8 read (1B/elem + one f32 absmax per
    # position per pool) vs the XLA f32 gather it replaces (4B/elem)
    hd = cfg.hidden_size // cfg.num_attention_heads * \
        cfg.num_attention_heads
    streamed_ratio = (hd + 4.0) / (4.0 * hd)
    total_tokens = sum(len(o) for o in p_out)
    block = {
        "parity": "exact",
        "requests": n_req,
        "tokens": int(total_tokens),
        "decode_steps": int(p_st["decode_steps"]),
        "decode_compiles": int(p_st["decode_compiles"]),
        "kernel_cost_signatures": sorted(prog.costs),
        "analytic_streamed_bytes_ratio_int8_vs_f32_gather": round(
            streamed_ratio, 3),
    }
    if on_tpu:
        block["tokens_per_sec"] = {
            "xla": round(total_tokens / x_wall, 1),
            "pallas": round(total_tokens / p_wall, 1)}
    else:
        block["tokens_per_sec"] = \
            "reserved for hardware round (interpret mode)"
        block["hbm_bytes_per_token"] = \
            "reserved for hardware round (interpret mode)"
    print(f"# decode_kernel parity=exact steps={p_st['decode_steps']} "
          f"compiles={p_st['decode_compiles']} "
          f"streamed-bytes ratio={streamed_ratio:.3f}",
          file=sys.stderr)
    return block


def _bench_autoscale_curve(measured):
    """Closed-loop fleet elasticity block (ISSUE 15): SLO-attainment vs
    replica-seconds curves instead of fixed-QPS points.  The seeded
    flash-crowd trace (tools/load_gen.py: diurnal + 8x flash +
    heavy-tail lengths) runs through FleetSim — virtual time, the
    shedder's latency model parameterized by THIS leg's measured
    prefill/per-token latencies — once per static fleet size and once
    autoscaled by the default ScalePolicy.  Gates: the autoscaled fleet
    matches the best static fleet's SLO attainment while spending fewer
    replica-seconds than the cheapest static fleet achieving it, with
    zero scale-flaps."""
    from paddle_tpu.serving import FleetSim, ScalePolicy
    from tools.load_gen import make_trace

    prefill_s = measured["prefill_s"]
    token_s = max(measured["token_s"], 1e-4)
    slots, out_mean, max_n = 4, 10.0, 5
    # the policy dynamics are scale-free: normalize the measured
    # latencies so the mean request's SERVICE time is 0.15 virtual
    # seconds (the regime the tier-1 sim tests pin down) — the measured
    # values contribute their prefill:token RATIO, the trace overloads
    # one replica by a fixed 25% at the flash peak, and absolute
    # magnitudes (which would otherwise quantize against the sim tick
    # for very fast engines) scale out.  Reported numbers carry the
    # virtual→measured conversion factor.
    service_meas = prefill_s + out_mean * token_s
    k = 0.15 / service_meas
    prefill_v, token_v = prefill_s * k, token_s * k
    capacity_qps = slots / 0.15
    base_qps = 0.15 * capacity_qps
    flash_mult = 1.25 * capacity_qps / base_qps
    slo_ttft_s = prefill_v + 1.5
    trace = make_trace(60.0, base_qps, seed=0, flash_mult=flash_mult,
                       flash_at=0.25, flash_duration_s=10.0,
                       prompt_mean=12.0, out_mean=out_mean, out_max=48,
                       deadline_s=prefill_v + 3.0)
    # headroom_frac 0.4 + up_ticks 1: trigger while the projected wait
    # is still well inside the SLO slack — the build takes 1.5 virtual
    # seconds and the backlog keeps growing until the replica lands.
    # cooldown_up 4.0 gives each new replica time to absorb the drained
    # backlog before the (still-elevated) estimate buys another chip;
    # cooldown_down 3.0 walks the flash fleet back down briskly — both
    # matter for the fewer-replica-seconds gate, and the heavy tail is
    # bounded at out_max=48 (4.8x the mean; an unbounded p99.9 request
    # holds a slot for ~25x mean service and makes ramp waits a lottery)
    policy = ScalePolicy(slo_ttft_s=slo_ttft_s, headroom_frac=0.4,
                         up_ticks=1, idle_ticks=8,
                         cooldown_up_s=4.0, cooldown_down_s=3.0)
    sim_kw = dict(slots_per_replica=slots, prefill_s=prefill_v,
                  token_s=token_v, slo_ttft_s=slo_ttft_s)
    auto = FleetSim(policy, min_replicas=1, max_replicas=max_n,
                    build_s=1.5, **sim_kw).run(trace)
    statics = {n: FleetSim(None, min_replicas=n, max_replicas=n,
                           start_replicas=n, **sim_kw).run(trace)
               for n in range(1, max_n + 1)}
    best_att = max(s["slo_attainment"] for s in statics.values())
    cheapest_best = min(
        (s["replica_seconds"] for s in statics.values()
         if s["slo_attainment"] >= best_att))
    if auto["slo_attainment"] < best_att - 1e-9:
        raise RuntimeError(
            f"autoscale gate: attainment {auto['slo_attainment']} < best "
            f"static {best_att}")
    if auto["replica_seconds"] >= cheapest_best:
        raise RuntimeError(
            f"autoscale gate: {auto['replica_seconds']} replica-seconds "
            f">= cheapest SLO-attaining static fleet ({cheapest_best})")
    if auto["flaps"] != 0:
        raise RuntimeError(f"autoscale gate: {auto['flaps']} scale-flaps "
                           f"(events: {auto['events']})")
    # warm-pool gate (ISSUE 20): the same policy with one parked spare
    # must answer the flash with a route-in, not a cold build — the
    # reaction time of every warm scale-up stays under the build time
    warm_policy = ScalePolicy(slo_ttft_s=slo_ttft_s, headroom_frac=0.4,
                              up_ticks=1, idle_ticks=8,
                              cooldown_up_s=4.0, cooldown_down_s=3.0)
    warm = FleetSim(warm_policy, min_replicas=1, max_replicas=max_n,
                    build_s=1.5, warm_pool=1, route_in_s=0.05,
                    **sim_kw).run(trace)
    wblock = warm["warm"] or {}
    if not wblock.get("warm_route_ins"):
        raise RuntimeError(
            f"warm-pool gate: no warm route-in fired "
            f"(events: {warm['events']})")
    if not wblock.get("max_warm_reaction_s", 1.5) < 1.5:
        raise RuntimeError(
            f"warm-pool gate: warm reaction "
            f"{wblock.get('max_warm_reaction_s')}s not under the 1.5s "
            f"cold build")
    print(f"# autoscale attainment={auto['slo_attainment']} "
          f"replica_s={auto['replica_seconds']} "
          f"(best static {best_att} @ {cheapest_best}) "
          f"peak={auto['peak_replicas']} events={len(auto['events'])} "
          f"warm_route_ins={wblock['warm_route_ins']} "
          f"warm_reaction_s={wblock['max_warm_reaction_s']}",
          file=sys.stderr)
    return {
        "trace": {"arrivals": len(trace), "duration_s": 60.0,
                  "base_qps": round(base_qps, 2),
                  "flash_mult": round(flash_mult, 2), "seed": 0},
        "model": {"prefill_s": round(prefill_s, 4),
                  "token_s": round(token_s, 5),
                  "virtual_per_measured_s": round(k, 4),
                  "slots_per_replica": slots,
                  "slo_ttft_virtual_s": round(slo_ttft_s, 3),
                  "slo_ttft_measured_s": round(slo_ttft_s / k, 3)},
        "autoscaled": {k: auto[k] for k in (
            "slo_attainment", "replica_seconds", "peak_replicas", "shed",
            "flaps", "ttft_p50_s", "ttft_p99_s")},
        "warm_pool": dict(
            wblock,
            slo_attainment=warm["slo_attainment"],
            replica_seconds=warm["replica_seconds"]),
        "scale_events": auto["events"],
        "curve": [{"replicas": n,
                   "slo_attainment": s["slo_attainment"],
                   "replica_seconds": s["replica_seconds"],
                   "shed": s["shed"]}
                  for n, s in sorted(statics.items())],
        "gates": {"attainment_vs_best_static": True,
                  "fewer_replica_seconds": True, "zero_flaps": True,
                  "warm_pool_reaction": True},
    }


def _bench_capture_fit(measured):
    """Capture→fit round-trip block (ISSUE 17): the seeded diurnal+flash
    trace is recorded through a shape-mode TrafficCapture (virtual
    arrival times, no HTTP — CPU-runnable like the autoscale curve),
    fitted back into a synthetic trace by ``capture.fit_trace``, and
    both traces run the SAME autoscaled FleetSim (measured latencies
    normalized to the 0.15 s mean service time).  Gates: the fit
    recovers the flash window (overlap with truth) and the heavy-tail
    output-length shape, and the fitted trace reproduces the source
    trace's scale-up decision sequence — same number of scale-ups, same
    peak fleet, first scale-up within a policy-poll-scaled tolerance."""
    from paddle_tpu.observability.capture import (TrafficCapture,
                                                  fit_params, fit_trace)
    from paddle_tpu.serving import FleetSim, ScalePolicy
    from tools.load_gen import make_trace

    prefill_s = measured["prefill_s"]
    token_s = max(measured["token_s"], 1e-4)
    slots, out_mean, out_sigma = 4, 10.0, 0.7
    service_meas = prefill_s + out_mean * token_s
    k = 0.15 / service_meas
    prefill_v, token_v = prefill_s * k, token_s * k
    capacity_qps = slots / 0.15
    base_qps = 0.15 * capacity_qps
    flash_mult = 1.25 * capacity_qps / base_qps
    slo_ttft_s = prefill_v + 1.5
    flash_t0, flash_t1 = 0.25 * 60.0, 0.25 * 60.0 + 10.0
    src = make_trace(60.0, base_qps, seed=0, flash_mult=flash_mult,
                     flash_at=0.25, flash_duration_s=10.0,
                     prompt_mean=12.0, out_mean=out_mean,
                     out_sigma=out_sigma, out_max=48,
                     deadline_s=prefill_v + 3.0)
    cap = TrafficCapture(max_entries=len(src) + 16, mode="shape")
    for e in src:
        cap.record(tenant="bench", priority="standard",
                   outcome="admitted", prompt_len=e["prompt_len"],
                   max_tokens=e["max_tokens"],
                   deadline_s=e["deadline_s"], t=e["t"])
    assert cap.stats()["dropped"] == 0
    # 1.0s bins: the auto heuristic picks ~2.5s bins for a 60s window,
    # which smears the 10s flash edges enough to drop a scale-up from
    # the fitted replay — fine bins keep the overload depth faithful
    p = fit_params(cap.entries(), bin_s=1.0)
    if p["flash"] is None or not (p["flash"]["t0"] < flash_t1
                                  and p["flash"]["t1"] > flash_t0):
        raise RuntimeError(
            f"capture gate: fitted flash window {p['flash']} misses the "
            f"true [{flash_t0}, {flash_t1})")
    if not (0.5 * flash_mult <= p["flash"]["mult"] <= 2.0 * flash_mult):
        raise RuntimeError(
            f"capture gate: fitted flash mult {p['flash']['mult']} "
            f"outside [{0.5 * flash_mult}, {2.0 * flash_mult}]")
    if abs(p["out"]["sigma"] - out_sigma) > 0.15:
        raise RuntimeError(
            f"capture gate: fitted out sigma {p['out']['sigma']} "
            f"not within 0.15 of the seeded {out_sigma} (heavy tail "
            f"lost in the fit)")
    fitted = fit_trace(cap.entries(), seed=1, params=p, out_max=48)

    def run(trace):
        pol = ScalePolicy(slo_ttft_s=slo_ttft_s, headroom_frac=0.4,
                          up_ticks=1, idle_ticks=8, cooldown_up_s=4.0,
                          cooldown_down_s=3.0)
        return FleetSim(pol, min_replicas=1, max_replicas=5, build_s=1.5,
                        slots_per_replica=slots, prefill_s=prefill_v,
                        token_s=token_v, slo_ttft_s=slo_ttft_s).run(trace)

    src_res, fit_res = run(src), run(fitted)
    src_ups = [e for e in src_res["events"] if e["direction"] == "up"]
    fit_ups = [e for e in fit_res["events"] if e["direction"] == "up"]
    if len(src_ups) != len(fit_ups):
        raise RuntimeError(
            f"capture gate: fitted trace drove {len(fit_ups)} scale-ups "
            f"vs the source's {len(src_ups)} "
            f"(src={src_res['events']}, fit={fit_res['events']})")
    if fit_res["peak_replicas"] != src_res["peak_replicas"]:
        raise RuntimeError(
            f"capture gate: fitted peak {fit_res['peak_replicas']} != "
            f"source peak {src_res['peak_replicas']}")
    # the first scale-up is the flash response; the fitted trace must
    # place it in the same regime (within the rate-curve bin width plus
    # policy hysteresis, not e.g. pre-scaled by a smeared-out flash)
    first_up_tol = 2.0 * p["bin_s"] + 2.0
    if src_ups and abs(fit_ups[0]["t"] - src_ups[0]["t"]) > first_up_tol:
        raise RuntimeError(
            f"capture gate: first scale-up at t={fit_ups[0]['t']} under "
            f"the fitted trace vs t={src_ups[0]['t']} under the source "
            f"(tolerance {first_up_tol})")
    print(f"# capture fit arrivals={len(src)}->{len(fitted)} "
          f"flash=[{p['flash']['t0']},{p['flash']['t1']}]x"
          f"{p['flash']['mult']} ups={len(src_ups)}=={len(fit_ups)} "
          f"first_up {src_ups[0]['t'] if src_ups else None}->"
          f"{fit_ups[0]['t'] if fit_ups else None} "
          f"peak={fit_res['peak_replicas']}", file=sys.stderr)
    return {
        "source": {"arrivals": len(src), "duration_s": 60.0,
                   "base_qps": round(base_qps, 2),
                   "flash_mult": round(flash_mult, 2), "seed": 0},
        "fit": {"arrivals": len(fitted), "bin_s": p["bin_s"],
                "flash": p["flash"], "base_qps": p["base_qps"],
                "prompt": p["prompt"], "out": p["out"]},
        "sim": {
            "source": {k2: src_res[k2] for k2 in (
                "slo_attainment", "replica_seconds", "peak_replicas",
                "shed")},
            "fitted": {k2: fit_res[k2] for k2 in (
                "slo_attainment", "replica_seconds", "peak_replicas",
                "shed")},
            "source_scale_ups": len(src_ups),
            "fitted_scale_ups": len(fit_ups),
            "first_up_delta_s": (round(abs(
                fit_ups[0]["t"] - src_ups[0]["t"]), 3)
                if src_ups and fit_ups else None),
        },
        "gates": {"flash_window_recovered": True,
                  "length_tail_recovered": True,
                  "scale_up_sequence_reproduced": True},
    }


def _bench_slo_alerting(measured):
    """Burn-rate alerting block (ISSUE 16): the multi-window SLO
    evaluator rides the same virtual-time FleetSim as the autoscale
    curve (measured prefill/token latencies normalized to a 0.15 s mean
    service time).  Gates: on the flash-crowd trace the fast-burn rule
    fires BEFORE the slow-window attainment itself crosses below the
    target (early warning, not postmortem), the alert resolves only
    after the autoscaler's first scale-up lands (absorption, not
    flapping), and the steady diurnal trace fires zero alerts (no false
    positives)."""
    from paddle_tpu.observability.slo import SloEvaluator, SloObjective
    from paddle_tpu.serving import FleetSim, ScalePolicy
    from tools.load_gen import make_trace

    prefill_s = measured["prefill_s"]
    token_s = max(measured["token_s"], 1e-4)
    slots, out_mean = 4, 10.0
    service_meas = prefill_s + out_mean * token_s
    k = 0.15 / service_meas
    prefill_v, token_v = prefill_s * k, token_s * k
    capacity_qps = slots / 0.15
    # base load leaves the 1-replica fleet comfortable (Poisson bursts
    # at high utilization would pre-scale the fleet and absorb the
    # flash before it ever burns); the 5x flash then hits cold
    base_qps = 0.375 * capacity_qps
    slo_ttft_s = prefill_v + 1.5
    target = 0.9

    def objective():
        # slow window 30 s: long enough that the flash's first seconds
        # barely move it — the 3 s fast window is what catches the
        # crowd, which is the whole point of the multi-window split
        return SloObjective("bench-ttft", "ttft_p99", target,
                            threshold_s=slo_ttft_s, fast_window_s=3.0,
                            fast_burn=6.0, slow_window_s=30.0,
                            slow_burn=2.0, fire_ticks=2, resolve_ticks=6,
                            min_events=4)

    def run(trace, start_replicas):
        pol = ScalePolicy(slo_ttft_s=slo_ttft_s, headroom_frac=0.4,
                          up_ticks=1, idle_ticks=8, cooldown_up_s=4.0,
                          cooldown_down_s=3.0)
        return FleetSim(pol, min_replicas=1, max_replicas=6,
                        start_replicas=start_replicas,
                        slots_per_replica=slots, prefill_s=prefill_v,
                        token_s=token_v, build_s=2.0, policy_poll_s=0.25,
                        window_s=5.0, slo_ttft_s=slo_ttft_s,
                        slo_evaluator=SloEvaluator([objective()])
                        ).run(trace)

    # a long pre-flash history makes the period attainment (the curve
    # the error budget is spent against) move slowly, which is exactly
    # why burn-rate alerts exist: the fast window reacts in seconds
    # while the compliance curve takes its time crossing the target
    flash = run(make_trace(120.0, base_qps, seed=0, flash_mult=5.0,
                           flash_at=0.75, flash_duration_s=10.0,
                           prompt_mean=12.0, out_mean=out_mean,
                           out_max=48), 1)
    slo = flash["slo"]
    firings = [t for t in slo["transitions"] if t["to"] == "firing"]
    resolves = [t for t in slo["transitions"] if t["to"] == "resolved"]
    if not firings:
        raise RuntimeError(f"slo gate: flash crowd never fired "
                           f"(transitions: {slo['transitions']})")
    breaches = [r["t"] for r in slo["attainment_series"]
                if r["attainment"] is not None
                and r["attainment"] < target]
    first_breach = breaches[0] if breaches else None
    if first_breach is not None and firings[0]["t"] >= first_breach:
        raise RuntimeError(
            f"slo gate: fast-burn fired at {firings[0]['t']} but the "
            f"period attainment crossed {target} at {first_breach} — "
            f"the alert must lead the breach")
    ups = [e for e in flash["events"] if e["direction"] == "up"]
    if not ups or not resolves or resolves[0]["t"] <= ups[0]["t"]:
        raise RuntimeError(
            f"slo gate: no resolve after absorption (ups={ups[:1]}, "
            f"resolves={resolves[:1]})")
    steady = run(make_trace(60.0, 0.3 * capacity_qps, seed=1,
                            flash_mult=1.0, prompt_mean=12.0,
                            out_mean=out_mean, out_max=48), 2)
    if steady["slo"]["fired"] != 0:
        raise RuntimeError(f"slo gate: steady diurnal fired "
                           f"{steady['slo']['fired']} false positives: "
                           f"{steady['slo']['transitions']}")
    lead_s = round(first_breach - firings[0]["t"], 3) \
        if first_breach is not None else None
    print(f"# slo fast-burn fired t={firings[0]['t']} "
          f"(lead {lead_s}s before period-attainment breach at "
          f"{first_breach}) resolved t={resolves[0]['t']} after up "
          f"t={ups[0]['t']} steady_false_positives=0", file=sys.stderr)
    return {
        "objective": objective().snapshot(),
        "flash": {"fired": slo["fired"], "resolved": slo["resolved"],
                  "first_fire_t": round(firings[0]["t"], 3),
                  "first_attainment_breach_t": first_breach,
                  "alert_lead_s": lead_s,
                  "first_up_t": round(ups[0]["t"], 3),
                  "first_resolve_t": round(resolves[0]["t"], 3),
                  "rules": sorted({t["rule"] for t in firings})},
        "steady": {"fired": 0,
                   "attainment": steady["slo_attainment"]},
        "gates": {"fires_before_attainment_breach": True,
                  "resolves_after_absorption": True,
                  "zero_false_positives": True},
    }


def _bench_gateway_curve(cfg, on_tpu, measured):
    """Latency-under-load curve through the HTTP gateway (ISSUE 8): an
    offered-QPS sweep of Poisson arrivals against a fresh engine behind
    the full front door.  Each level reports client-measured p50/p99 TTFT
    (time to the first streamed SSE chunk), token throughput, and the
    shed rate (429s from queue caps + the deadline shed model); asserts
    the decode program never retraces across the sweep."""
    import http.client
    import json as json_mod
    import threading

    import paddle_tpu as paddle
    from paddle_tpu.models import build_gpt
    from paddle_tpu.serving import Engine, EngineSupervisor
    from paddle_tpu.serving.gateway import (LoadShedder, TenantConfig,
                                            start_gateway)

    if on_tpu:
        slots, max_len, new, n_req = 8, 640, 32, 30
        qps_levels, p_len, deadline_ms = (10.0, 40.0, 160.0), 64, 2000
    else:
        slots, max_len, new, n_req = 4, 64, 6, 10
        qps_levels, p_len, deadline_ms = (2.0, 8.0, 32.0), 6, 1500

    paddle.seed(0)
    model = build_gpt(cfg)
    model.eval()
    # supervised replica (ISSUE 9): the sweep runs through the same
    # self-healing layer production would, and the kill/restart probe at
    # the end measures recovery TTFT through a supervisor rebuild
    engine = EngineSupervisor(
        lambda: Engine(model, max_slots=slots, max_len=max_len,
                       max_queue=slots),
        name="bench0", poll_interval_s=0.02)
    shedder = LoadShedder()
    shedder.seed(measured["prefill_s"], measured["token_s"])
    stack = start_gateway(
        [engine], own_engines=True, shedder=shedder,
        tenants=[TenantConfig("bench", max_queue=2 * slots)])
    curve = []
    rs = np.random.RandomState(7)
    try:
        port = stack.port
        # warm the wire path once (compiles already warm via seed model?
        # no — this is a fresh engine: the first request pays prefill +
        # decode compile; keep it out of the measured levels)
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=600)
        conn.request("POST", "/v1/completions", json_mod.dumps(
            {"prompt": [3] * p_len, "max_tokens": 2}).encode(),
            {"Content-Type": "application/json", "X-Tenant": "bench"})
        assert conn.getresponse().status == 200
        conn.close()

        def one_request(prompt, out, lock):
            """Streamed request; records (ttft_s, n_tokens, status)."""
            body = json_mod.dumps({
                "prompt": prompt, "max_tokens": new, "stream": True,
                "deadline_ms": deadline_ms}).encode()
            c = http.client.HTTPConnection("127.0.0.1", port, timeout=600)
            t0 = time.perf_counter()
            try:
                c.request("POST", "/v1/completions", body,
                          {"Content-Type": "application/json",
                           "X-Tenant": "bench"})
                r = c.getresponse()
                if r.status != 200:
                    r.read()
                    with lock:
                        out.append((None, 0, r.status))
                    return
                ttft, n_tok = None, 0
                for line in r:
                    if not line.startswith(b"data: "):
                        continue
                    if ttft is None:
                        ttft = time.perf_counter() - t0
                    data = line[6:].strip()
                    if data == b"[DONE]":
                        break
                    n_tok += len(json_mod.loads(data)
                                 ["choices"][0]["token_ids"])
                with lock:
                    out.append((ttft, n_tok, 200))
            except Exception:  # noqa: BLE001 — count as a failed sample
                with lock:
                    out.append((None, 0, -1))
            finally:
                c.close()

        for qps in qps_levels:
            out, lock = [], threading.Lock()
            threads = []
            t_level = time.perf_counter()
            for i in range(n_req):
                prompt = [int(t) for t in
                          rs.randint(1, cfg.vocab_size, p_len)]
                th = threading.Thread(target=one_request,
                                      args=(prompt, out, lock))
                th.start()
                threads.append(th)
                time.sleep(min(rs.exponential(1.0 / qps), 0.5))
            for th in threads:
                th.join(timeout=600)
            wall = time.perf_counter() - t_level
            ttfts_ms = sorted(t * 1e3 for t, _, s in out
                              if s == 200 and t is not None)
            tokens = sum(n for _, n, _ in out)
            shed = sum(1 for _, _, s in out if s == 429)
            completed = sum(1 for _, _, s in out if s == 200)
            level = {
                "offered_qps": qps,
                "achieved_qps": round(completed / wall, 2),
                "requests": n_req, "completed": completed, "shed": shed,
                "shed_rate": round(shed / n_req, 3),
                "tokens_per_sec": round(tokens / wall, 1),
                "ttft_ms": {
                    "p50": round(float(np.percentile(ttfts_ms, 50)), 1)
                    if ttfts_ms else None,
                    "p99": round(float(np.percentile(ttfts_ms, 99)), 1)
                    if ttfts_ms else None,
                },
            }
            curve.append(level)
            print(f"# gateway qps={qps} completed={completed}/{n_req} "
                  f"shed={shed} ttft_p50="
                  f"{level['ttft_ms']['p50']}ms", file=sys.stderr)
        decode_compiles = engine.compile_stats()["decode_compiles"]
        if decode_compiles != 1:
            raise RuntimeError(
                f"gateway sweep: decode retraced "
                f"({decode_compiles} signatures)")
        shed_total = stack.gateway.stats()["tenants"].get(
            "bench", {}).get("rejected", 0)

        # -- kill/restart recovery probe (ISSUE 9): SIGKILL-equivalent
        # scheduler fault mid-load, then TTFT of the first request that
        # COMPLETES after the supervisor rebuilt the engine
        from paddle_tpu.testing import faults as _faults
        kill_restart_ttft_ms = None
        try:
            bg = [threading.Thread(
                target=one_request,
                args=([int(t) for t in rs.randint(1, cfg.vocab_size,
                                                  p_len)], [],
                      threading.Lock()))
                for _ in range(max(2, slots // 2))]
            for th in bg:
                th.start()
            _faults.arm("serving.scheduler", times=1)
            t_kill = time.perf_counter()
            deadline = t_kill + 300
            while engine.restarts < 1:
                if time.perf_counter() > deadline:
                    raise RuntimeError("kill never absorbed by a restart")
                time.sleep(0.01)
            # first completion AFTER the rebuild (429/503 are retried:
            # recovery time includes the backpressure window)
            while time.perf_counter() < deadline:
                probe, plock = [], threading.Lock()
                one_request([int(t) for t in
                             rs.randint(1, cfg.vocab_size, p_len)],
                            probe, plock)
                if probe and probe[0][2] == 200:
                    kill_restart_ttft_ms = round(
                        (time.perf_counter() - t_kill) * 1e3, 1)
                    break
                time.sleep(0.05)
            for th in bg:
                th.join(timeout=300)
            if kill_restart_ttft_ms is None:
                raise RuntimeError("no request completed after the "
                                   "mid-load engine restart")
            print(f"# gateway kill_restart_ttft={kill_restart_ttft_ms}ms "
                  f"(supervisor restarts={engine.restarts})",
                  file=sys.stderr)
        finally:
            _faults.reset()
    finally:
        stack.close()
    return {"deadline_ms": deadline_ms, "curve": curve,
            "decode_compiles": decode_compiles,
            "queue_rejected": int(shed_total),
            "kill_restart_ttft_ms": kill_restart_ttft_ms,
            "supervisor_restarts": int(engine.restarts)}


# Flagship first (its number is the driver-parsed top level); then
# PP-YOLOE (the leg the round-4 budget dropped — it must land before the
# expensive 1.3B compile); then the north-star 1.3B leg; then the smaller
# legs.  Estimated seconds per leg (compile + steps, measured on the real
# chip) gate a global budget so the bench SKIPS trailing legs instead of
# being killed mid-run with no output at all.
# estimates are COLD-cache costs (compile + steps, measured); with the
# persistent compile cache warm they overestimate ~2-4x, so the budget
# gate only sheds trailing legs on a genuinely cold host
_LEGS = [
    ("gpt2_small", bench_gpt_small, 85),
    ("ppyoloe_s", bench_ppyoloe, 130),
    ("gpt3_1p3b", bench_gpt_1p3b, 200),
    ("resnet50", bench_resnet50, 115),
    ("bert_base", bench_bert, 85),
    ("gpt_decode", bench_gpt_decode, 110),
    ("serving", bench_serving, 150),
]


def _flight_tail(n=50):
    """Last flight-recorder events for a failed/skipped leg's artifact —
    the timeline that explains WHY (round-5 weak #1: 1,501 s inside
    jax.devices() with no artifact)."""
    try:
        from paddle_tpu.observability import flight
        return flight.tail(n)
    except Exception:
        return []


def _probe_backend(timeout_s=None, retries=3):
    """Fail-fast backend probe, run BEFORE the budget clock starts: a
    bounded-timeout jax.devices() with retries.  jax.devices() is not
    interruptible, so the probe runs it on a daemon thread and gives up
    waiting after timeout_s — on persistent failure the bench emits a
    distinct backend_unavailable artifact immediately instead of burning
    the whole budget inside leg 1.  Returns (devices | None, error)."""
    import threading

    if timeout_s is None:
        timeout_s = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "120"))
    err = "unknown"
    for attempt in range(1, retries + 1):
        result = {}

        def probe():
            try:
                import jax
                result["devices"] = jax.devices()
            except Exception as e:  # noqa: BLE001
                result["error"] = f"{type(e).__name__}: {e}"

        t = threading.Thread(target=probe, daemon=True,
                             name=f"bench-backend-probe-{attempt}")
        t0 = time.perf_counter()
        t.start()
        t.join(timeout_s)
        dt = time.perf_counter() - t0
        if "devices" in result:
            if attempt > 1:
                print(f"# backend probe recovered on attempt {attempt} "
                      f"({dt:.1f}s)", file=sys.stderr)
            return result["devices"], None
        err = result.get("error",
                         f"jax.devices() still blocked after {timeout_s:.0f}s")
        print(f"# backend probe attempt {attempt}/{retries} failed after "
              f"{dt:.1f}s: {err}", file=sys.stderr)
    return None, err


def _telemetry_block():
    """Per-leg telemetry summary from the observability registry (the
    registry is reset before each leg, so these are per-leg deltas):
    compile counts + retrace warnings from the sentinel, op-dispatch
    totals, step-latency stats, peak device memory.  Appended under a new
    'telemetry' key — the existing metric schema fields are untouched."""
    from paddle_tpu import observability as obs
    from paddle_tpu.observability import dispatch, retrace, steps
    reg = obs.registry()
    block = {
        "compiles": {}, "retraces": int(retrace.retrace_warning_count()),
        "op_dispatch_total": 0, "op_dispatch_eager": 0,
        "op_dispatch_traced": 0,
    }
    c = reg.get(retrace.JIT_COMPILE_TOTAL)
    if c is not None:
        for labels, v in c.series():
            block["compiles"][labels.get("fn", "?")] = int(v)
    d = reg.get(dispatch.OP_DISPATCH_TOTAL)
    if d is not None:
        for labels, v in d.series():
            block["op_dispatch_total"] += int(v)
            mode = labels.get("mode")
            if mode in ("eager", "traced"):
                block[f"op_dispatch_{mode}"] += int(v)
    h = reg.get(steps.STEP_LATENCY)
    if h is not None:
        for labels, _ in h.series():
            snap = h.snapshot(labels)
            if snap["count"]:
                block.setdefault("step_latency", {})[
                    labels.get("fn", "?")] = {
                    "count": snap["count"],
                    "mean_ms": round(1e3 * snap["sum"] / snap["count"], 3)}
    c = reg.get(steps.HOST_INPUT_WAIT)
    if c is not None:
        block["host_input_wait_s"] = round(c.total(), 4)
    c = reg.get(steps.PIPELINE_STALLS)
    if c is not None:
        block["pipeline_stalls"] = int(c.total())
    # per-leg perfscope roofline (programs that registered cost and/or
    # sampled device time this leg; empty when sampling was off)
    from paddle_tpu.observability import perfscope
    rep = perfscope.perf_report()
    if rep["programs"]:
        block["perfscope"] = {
            "sample_every": rep["sample_every"],
            "programs": {p["program"]: {
                "dispatches": p["dispatches"], "sampled": p["sampled"],
                "device_s": p["device_s"], "share": p["share"],
                "mfu": p["mfu"], "hbm_bw_frac": p["hbm_bw_frac"]}
                for p in rep["programs"]}}
    steps.record_memory_stats()  # refresh the gauges at leg end
    g = reg.get(steps.MEMORY_GAUGE)
    if g is not None:
        peak = g.value(labels={"stat": "peak_bytes_in_use"})
        if peak:
            block["peak_memory_bytes"] = int(peak)
    return block


def main():
    flagship_only = "--flagship-only" in sys.argv
    telemetry = "--telemetry" in sys.argv
    if telemetry:
        from paddle_tpu import observability as obs
        obs.enable(True)
    # fail-fast probe BEFORE the budget clock: a wedged backend becomes a
    # distinct artifact in ~3*timeout seconds, not a silently burned budget
    devices, probe_err = _probe_backend()
    if devices is None:
        print(json.dumps({
            "metric": "gpt_flagship_failed", "value": 0.0,
            "unit": "tokens/s/chip", "vs_baseline": 0.0,
            "error": "backend_unavailable", "detail": probe_err,
            "flight_tail": _flight_tail()}))
        return
    # default covers the measured sum of all seven legs + headroom;
    # a tighter driver can export BENCH_BUDGET_S to shed trailing legs
    budget = float(os.environ.get("BENCH_BUDGET_S", "810"))
    start = time.perf_counter()
    legs = {}
    for key, fn, est in _LEGS:
        if flagship_only and key != "gpt2_small":
            continue
        elapsed = time.perf_counter() - start
        if elapsed + est > budget and legs:
            legs[key] = {"skipped": f"time budget ({elapsed:.0f}s elapsed "
                                    f"+ ~{est}s > {budget:.0f}s)",
                         "flight_tail": _flight_tail()}
            continue
        try:
            _reset_parallel_state()
            if telemetry:
                from paddle_tpu import observability as obs
                from paddle_tpu.observability import perfscope
                obs.registry().reset()  # per-leg deltas
                perfscope.reset_programs()
            legs[key] = fn()
        except Exception as e:  # a failing leg must not kill the bench
            traceback.print_exc(file=sys.stderr)
            legs[key] = {"error": f"{type(e).__name__}: {e}",
                         "flight_tail": _flight_tail()}
        finally:
            if telemetry:
                try:
                    legs[key]["telemetry"] = _telemetry_block()
                except Exception:
                    traceback.print_exc(file=sys.stderr)
            _reset_parallel_state()
            import gc
            import jax
            gc.collect()           # drop the leg's device buffers
            jax.clear_caches()     # and its compiled executables
    flagship = legs.get("gpt2_small") or {}
    line = dict(flagship) if "error" not in flagship else {
        "metric": "gpt_flagship_failed", "value": 0.0,
        "unit": "tokens/s/chip", "vs_baseline": 0.0}
    if not flagship_only:
        line["legs"] = legs
    print(json.dumps(line))


if __name__ == "__main__":
    main()
