"""Benchmark: BASELINE.md configs on one TPU chip.

Prints ONE JSON line with the flagship GPT metric at the top level (the
schema the driver has parsed since round 1) plus a "legs" object carrying
EVERY leg's result — GPT-2-small, GPT-3-1.3B (north-star scale: on-device
bf16 state + scan_layers + remat), ResNet-50, BERT-base, PP-YOLOE — so
BENCH_r{N}.json records non-flagship regressions too (round-3 verdict
Weak #7/#2).

`python bench.py --flagship-only` restores the old single-leg behavior.
"""
from __future__ import annotations

import json
import sys
import time
import traceback

import numpy as np

# bf16 peak FLOPs/s per chip by TPU generation (public spec sheets)
_PEAK = {"v5 lite": 197e12, "v5e": 197e12, "v4": 275e12, "v5p": 459e12,
         "v6": 918e12}


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, val in _PEAK.items():
        if key in kind:
            return val
    return 197e12  # assume v5e-class


def _reset_parallel_state():
    import paddle_tpu.distributed as dist
    dist.set_global_mesh(None)


def bench_gpt_small():
    """Flagship: GPT-2-small pretraining step (125M; comparable to the
    round-1..3 flagship numbers)."""
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.models import (GPTPretrainingCriterion, build_gpt,
                                   gpt_config, gpt_train_flops_per_token)

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"

    if on_tpu:
        name, batch, seq, steps = "gpt2-small-en", 16, 1024, 20
    else:  # CI/CPU smoke: tiny shapes, same code path
        name, batch, seq, steps = "gpt-tiny", 2, 128, 3

    cfg = gpt_config(name, max_position_embeddings=max(seq, 1024),
                     hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    paddle.seed(0)
    model = build_gpt(cfg)
    crit = GPTPretrainingCriterion()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 weight_decay=0.01)
    step = dist.make_train_step(model, opt, loss_fn=crit,
                                compute_dtype="bfloat16" if on_tpu else None)
    ids = np.random.RandomState(0).randint(
        0, cfg.vocab_size, size=(batch, seq + 1)).astype(np.int64)
    x, y = ids[:, :-1], ids[:, 1:]

    loss = step(x, y)  # compile + warmup
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(x, y)
    float(loss)  # block on the last step
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * steps / dt
    flops_tok = gpt_train_flops_per_token(cfg, seq)
    mfu = tokens_per_sec * flops_tok / _peak_flops(dev) if on_tpu else 0.0
    print(f"# device={dev.device_kind} loss={float(loss):.4f} "
          f"mfu={mfu:.3f} steps={steps} dt={dt:.2f}s", file=sys.stderr)
    return {
        "metric": f"gpt_{name}_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.35, 4) if on_tpu else 0.0,
    }


def bench_gpt_1p3b():
    """North-star-scale leg (round-3 verdict #1): GPT-3 1.3B — the
    BASELINE.md gate model (>=0.35 MFU, FleetX recipe) — on ONE chip.
    Measured recipe (round 4): bf16 params + slots on device, scan_layers +
    per-layer remat, eager weight copies freed after the train state is
    built (the state owns the live weights; sync_to_model is never called
    here).  Host-offloaded slots were measured 8.8x slower (0.057 MFU, the
    PCIe staging dominates) and batch 16 regresses to 0.450 — batch 8 +
    remat gives 0.506 MFU, 1.45x the 0.35 gate.  MFU is per-step, so
    single-chip throughput is the honest scale measurement the 125M proxy
    could not provide."""
    import gc

    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.models import (GPTPretrainingCriterion, build_gpt,
                                   gpt_config, gpt_train_flops_per_token)

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    if on_tpu:
        name, batch, seq, steps = "gpt3-1.3B-en", 8, 1024, 5
    else:
        name, batch, seq, steps = "gpt-tiny", 2, 128, 2

    cfg = gpt_config(name, max_position_embeddings=max(seq, 1024),
                     hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                     scan_layers=True, use_recompute=True)
    paddle.seed(0)
    if on_tpu:
        paddle.set_default_dtype("bfloat16")
    try:
        model = build_gpt(cfg)
    finally:
        paddle.set_default_dtype("float32")
    crit = GPTPretrainingCriterion()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 weight_decay=0.01)
    step = dist.make_train_step(
        model, opt, loss_fn=crit,
        compute_dtype="bfloat16" if on_tpu else None)
    if on_tpu:
        # free the eager weight copies: 2.6 GiB of headroom the 1.3B
        # single-chip budget needs (params 2.6 + slots 5.2 + grads 2.6)
        for p in model.parameters():
            p._replace_(jnp.zeros((), p._value.dtype), None)
        gc.collect()
    ids = np.random.RandomState(0).randint(
        0, cfg.vocab_size, size=(batch, seq + 1)).astype(np.int64)
    x, y = ids[:, :-1], ids[:, 1:]
    loss = step(x, y)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(x, y)
    float(loss)
    dt = time.perf_counter() - t0

    tps = batch * seq * steps / dt
    flops_tok = gpt_train_flops_per_token(cfg, seq)
    mfu = tps * flops_tok / _peak_flops(dev) if on_tpu else 0.0
    print(f"# gpt-1.3B device={dev.device_kind} loss={float(loss):.4f} "
          f"mfu={mfu:.3f} step={dt / steps * 1000:.0f}ms", file=sys.stderr)
    return {
        "metric": f"gpt_{name}_tokens_per_sec_per_chip",
        "value": round(tps, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.35, 4) if on_tpu else 0.0,
    }


def bench_resnet50():
    """ResNet-50 ImageNet-shape training step, images/s/chip (BASELINE.md
    row 1; reference model zoo resnet50)."""
    import jax
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    import paddle_tpu.nn as nn
    from paddle_tpu.vision.models import resnet50

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    # batch 128 amortizes the fixed per-op costs best on one v5e chip
    # (measured: 64 -> 0.130 MFU, 128 -> 0.146, 256 -> 0.143)
    batch, steps = (128, 10) if on_tpu else (2, 2)
    size = 224 if on_tpu else 32

    paddle.seed(0)
    # stem_s2d: space-to-depth stem, +1.4% end-to-end measured (2541 ->
    # 2577 img/s; exact-equivalent math, docs/PERF.md round-4 A/B)
    model = resnet50(num_classes=1000, stem_s2d=on_tpu)
    crit = nn.CrossEntropyLoss()
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=model.parameters(),
                                    weight_decay=1e-4)
    step = dist.make_train_step(
        model, opt, loss_fn=crit,
        compute_dtype="bfloat16" if on_tpu else None)
    rng = np.random.RandomState(0)
    # device-resident batch: a real input pipeline overlaps H2D with
    # compute; through the remote tunnel an un-overlapped 38 MB image batch
    # would otherwise dominate the measurement (docs/PERF.md).  The K-step
    # stack is materialized ON DEVICE (broadcast of one batch) and stepped
    # through run_steps — one dispatch for all K steps, the same
    # amortization the reference gets from its C++ trainer run loop
    # (trainer.cc); at ~26 ms device steps the per-dispatch tunnel cost
    # would otherwise add ~8 ms/step.
    import jax.numpy as jnp
    x1 = jnp.asarray(
        rng.standard_normal((batch, 3, size, size)).astype(np.float32))
    y1 = jnp.asarray(rng.randint(0, 1000, (batch,)).astype(np.int64))
    rep = jax.jit(lambda a, k: jnp.broadcast_to(a[None], (k,) + a.shape) + 0,
                  static_argnums=1)
    x, y = rep(x1, steps), rep(y1, steps)
    jax.block_until_ready(x)
    loss = step.run_steps(x, y)  # compile + warmup
    np.asarray(loss.numpy() if hasattr(loss, "numpy") else loss)
    reps = 3
    t0 = time.perf_counter()
    for _ in range(reps):
        loss = step.run_steps(x, y)
    losses = np.asarray(loss.numpy() if hasattr(loss, "numpy") else loss)
    dt = time.perf_counter() - t0
    loss = float(losses[-1])
    ips = batch * steps * reps / dt
    # ~3.8 GFLOP/image fwd at 224², x3 for fwd+bwd
    mfu = ips * 3 * 3.8e9 / _peak_flops(dev) if on_tpu else 0.0
    print(f"# resnet50 device={dev.device_kind} loss={float(loss):.4f} "
          f"mfu={mfu:.3f} batch={batch} dt={dt:.2f}s", file=sys.stderr)
    return {
        "metric": "resnet50_images_per_sec_per_chip",
        "value": round(ips, 1),
        "unit": "images/s/chip",
        "vs_baseline": round(mfu / 0.35, 4) if on_tpu else 0.0,
    }


def bench_ppyoloe():
    """PP-YOLOE-s-class detector train step at 640x640 (BASELINE.md row 6;
    conv-heavy detection workload on top of the same conv/BN path as
    ResNet).  No reference number exists in-tree, so vs_baseline reports
    MFU/0.35 like the other rows (FLOPs ~17.4 GFLOP/image fwd at 6402 for
    the s scale)."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.vision.models import PPYOLOE, PPYOLOELoss

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    batch, size, steps = (8, 640, 10) if on_tpu else (2, 64, 2)

    paddle.seed(0)
    model = PPYOLOE(num_classes=80)
    loss_fn = PPYOLOELoss(model)
    opt = paddle.optimizer.Momentum(learning_rate=0.01, momentum=0.9,
                                    parameters=model.parameters(),
                                    weight_decay=5e-4)
    step = dist.make_train_step(
        model, opt, loss_fn=loss_fn, num_labels=2,
        compute_dtype="bfloat16" if on_tpu else None)
    rng = np.random.RandomState(0)
    x = jnp.asarray(
        rng.standard_normal((batch, 3, size, size)).astype(np.float32))
    gtb = jnp.asarray(np.stack([np.array([[4, 4, 300, 300], [64, 32, 400,
                                          500]], "float32")] * batch))
    gtl = jnp.asarray(np.stack([np.array([1, 3], "int64")] * batch))
    loss = step(x, gtb, gtl)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(x, gtb, gtl)
    float(loss)
    dt = time.perf_counter() - t0
    ips = batch * steps / dt
    mfu = ips * 3 * 17.4e9 / _peak_flops(dev) if on_tpu else 0.0
    print(f"# ppyoloe device={dev.device_kind} loss={float(loss):.4f} "
          f"step={dt / steps * 1000:.1f}ms mfu={mfu:.3f}", file=sys.stderr)
    return {
        "metric": "ppyoloe_s_images_per_sec_per_chip",
        "value": round(ips, 1),
        "unit": "images/s/chip",
        "vs_baseline": round(mfu / 0.35, 4) if on_tpu else 0.0,
    }


def bench_bert():
    """BERT-base MLM-shape step, tokens/s/chip (BASELINE.md row 2; the DP
    scaling leg runs on the CPU-sim mesh in tests/test_bert.py)."""
    import jax
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.models import (BertPretrainingCriterion, bert_config,
                                   build_bert)

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    batch, seq, steps = (16, 512, 10) if on_tpu else (2, 64, 2)
    name = "bert-base-uncased" if on_tpu else "bert-tiny"

    paddle.seed(0)
    cfg = bert_config(name, hidden_dropout_prob=0.0,
                      attention_dropout_prob=0.0)
    model = build_bert(cfg)
    crit = BertPretrainingCriterion()

    def loss_fn(out, labels, nsp_labels):
        mlm, nsp = out
        return crit(mlm, nsp, labels, nsp_labels)

    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    step = dist.make_train_step(
        model, opt, loss_fn=loss_fn, num_labels=2,
        compute_dtype="bfloat16" if on_tpu else None)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int64)
    labels = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int64)
    nsp = rng.randint(0, 2, (batch,)).astype(np.int64)
    loss = step(ids, labels, nsp)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(ids, labels, nsp)
    float(loss)
    dt = time.perf_counter() - t0
    tps = batch * seq * steps / dt
    # 6 * params flops/token (110M)
    mfu = tps * 6 * 110e6 / _peak_flops(dev) if on_tpu else 0.0
    print(f"# bert device={dev.device_kind} loss={float(loss):.4f} "
          f"mfu={mfu:.3f} dt={dt:.2f}s", file=sys.stderr)
    return {
        "metric": "bert_base_tokens_per_sec_per_chip",
        "value": round(tps, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.35, 4) if on_tpu else 0.0,
    }


# Flagship first (its number is the driver-parsed top level), the
# north-star-scale 1.3B leg second (the round-4 measurement that must land
# even under a tight budget), then the smaller legs.  Estimated seconds per
# leg (compile + steps, measured on the real chip) gate a global budget so
# the bench SKIPS trailing legs instead of being killed mid-run with no
# output at all.
_LEGS = [
    ("gpt2_small", bench_gpt_small, 90),
    ("gpt3_1p3b", bench_gpt_1p3b, 230),
    ("resnet50", bench_resnet50, 120),
    ("bert_base", bench_bert, 80),
    ("ppyoloe_s", bench_ppyoloe, 100),
]


def main():
    import os
    flagship_only = "--flagship-only" in sys.argv
    # default covers the measured sum of all five legs (~620s) + headroom;
    # a tighter driver can export BENCH_BUDGET_S to shed trailing legs
    budget = float(os.environ.get("BENCH_BUDGET_S", "700"))
    start = time.perf_counter()
    legs = {}
    for key, fn, est in _LEGS:
        if flagship_only and key != "gpt2_small":
            continue
        elapsed = time.perf_counter() - start
        if elapsed + est > budget and legs:
            legs[key] = {"skipped": f"time budget ({elapsed:.0f}s elapsed "
                                    f"+ ~{est}s > {budget:.0f}s)"}
            continue
        try:
            _reset_parallel_state()
            legs[key] = fn()
        except Exception as e:  # a failing leg must not kill the bench
            traceback.print_exc(file=sys.stderr)
            legs[key] = {"error": f"{type(e).__name__}: {e}"}
        finally:
            _reset_parallel_state()
            import gc
            import jax
            gc.collect()           # drop the leg's device buffers
            jax.clear_caches()     # and its compiled executables
    flagship = legs.get("gpt2_small") or {}
    line = dict(flagship) if "error" not in flagship else {
        "metric": "gpt_flagship_failed", "value": 0.0,
        "unit": "tokens/s/chip", "vs_baseline": 0.0}
    if not flagship_only:
        line["legs"] = legs
    print(json.dumps(line))


if __name__ == "__main__":
    main()
