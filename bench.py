"""Benchmark: flagship GPT pretraining step on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline (BASELINE.md north star): GPT at >=35% MFU — vs_baseline is
measured MFU / 0.35, so >=1.0 beats the target.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

# bf16 peak FLOPs/s per chip by TPU generation (public spec sheets)
_PEAK = {"v5 lite": 197e12, "v5e": 197e12, "v4": 275e12, "v5p": 459e12,
         "v6": 918e12}


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, val in _PEAK.items():
        if key in kind:
            return val
    return 197e12  # assume v5e-class


def main():
    import jax

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.models import (GPTPretrainingCriterion, build_gpt,
                                   gpt_config, gpt_train_flops_per_token)

    if on_tpu:
        name, batch, seq, steps = "gpt2-small-en", 16, 1024, 20
    else:  # CI/CPU smoke: tiny shapes, same code path
        name, batch, seq, steps = "gpt-tiny", 2, 128, 3

    cfg = gpt_config(name, max_position_embeddings=max(seq, 1024),
                     hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    paddle.seed(0)
    model = build_gpt(cfg)
    crit = GPTPretrainingCriterion()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 weight_decay=0.01)
    step = dist.make_train_step(model, opt, loss_fn=crit,
                                compute_dtype="bfloat16" if on_tpu else None)
    ids = np.random.RandomState(0).randint(
        0, cfg.vocab_size, size=(batch, seq + 1)).astype(np.int64)
    x, y = ids[:, :-1], ids[:, 1:]

    loss = step(x, y)  # compile + warmup
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(x, y)
    float(loss)  # block on the last step
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * steps / dt
    flops_tok = gpt_train_flops_per_token(cfg, seq)
    mfu = tokens_per_sec * flops_tok / _peak_flops(dev) if on_tpu else 0.0
    print(json.dumps({
        "metric": f"gpt_{name}_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.35, 4) if on_tpu else 0.0,
    }))
    print(f"# device={dev.device_kind} loss={float(loss):.4f} "
          f"mfu={mfu:.3f} steps={steps} dt={dt:.2f}s", file=sys.stderr)


if __name__ == "__main__":
    main()
